"""Pooling layers with exact Caffe geometry, lowered to XLA reduce_window.

Semantics match reference pooling_layer.cpp:
  * ceil-mode output sizing:  out = ceil((in + 2p - k)/s) + 1, then if padded
    and (out-1)*s >= in + p, out is decremented (pooling_layer.cpp:92-107).
  * MAX ignores padding entirely (window clipped to the real image,
    pooling_layer.cpp:156-161) — realized here by reduce_window's -inf pad.
  * AVE divides by the window area clipped to [start, in + pad) with the RAW
    (possibly negative) start (pooling_layer.cpp:199-203) — divisors are
    position-dependent at borders and computed statically at trace time.
  * STOCHASTIC samples an element proportional to its value in TRAIN and
    takes the value-weighted average in TEST (st_pooling GPU kernels).
SPP (reference spp_layer.cpp:12-56) stacks per-level poolings whose
kernel/pad derive from the input size.
"""

import numpy as np
import jax
from jax import lax
import jax.numpy as jnp

from ..graph.registry import Layer, register

MAX, AVE, STOCHASTIC = 0, 1, 2


def caffe_pool_geometry(pp, in_h, in_w):
    """Resolve kernel/stride/pad + ceil-mode output sizes from a
    PoolingParameter, reproducing pooling_layer.cpp LayerSetUp/Reshape."""
    if pp.global_pooling:
        kh, kw = in_h, in_w
        sh = sw = 1
        ph = pw = 0
    else:
        if pp.has_kernel_size():
            kh = kw = int(pp.kernel_size)
        else:
            kh, kw = int(pp.kernel_h), int(pp.kernel_w)
        if pp.has_stride_h():
            sh, sw = int(pp.stride_h), int(pp.stride_w)
        else:
            sh = sw = int(pp.stride)
        if pp.has_pad_h():
            ph, pw = int(pp.pad_h), int(pp.pad_w)
        else:
            ph = pw = int(pp.pad)
    oh = int(np.ceil((in_h + 2 * ph - kh) / sh)) + 1
    ow = int(np.ceil((in_w + 2 * pw - kw) / sw)) + 1
    if ph or pw:
        if (oh - 1) * sh >= in_h + ph:
            oh -= 1
        if (ow - 1) * sw >= in_w + pw:
            ow -= 1
    return (kh, kw), (sh, sw), (ph, pw), (oh, ow)


def _edge_pad(in_size, k, s, p, out):
    """Right-side padding needed so every (possibly overhanging) ceil-mode
    window lies inside the padded array."""
    return max(0, (out - 1) * s + k - p - in_size)


def _ave_counts(in_size, k, s, p, out):
    """Caffe AVE divisor per output position (raw start, end clipped to in+p)."""
    starts = np.arange(out) * s - p
    ends = np.minimum(starts + k, in_size + p)
    return (ends - starts).astype(np.float32)


def max_pool(x, kernel, stride, pad, out):
    (kh, kw), (sh, sw), (ph, pw), (oh, ow) = kernel, stride, pad, out
    n, c, h, w = x.shape
    rh = _edge_pad(h, kh, sh, ph, oh)
    rw = _edge_pad(w, kw, sw, pw, ow)
    return lax.reduce_window(
        x, -np.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else np.iinfo(np.dtype(x.dtype)).min,
        lax.max,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=((0, 0), (0, 0), (ph, rh), (pw, rw)),
    )


def ave_pool(x, kernel, stride, pad, out):
    (kh, kw), (sh, sw), (ph, pw), (oh, ow) = kernel, stride, pad, out
    n, c, h, w = x.shape
    rh = _edge_pad(h, kh, sh, ph, oh)
    rw = _edge_pad(w, kw, sw, pw, ow)
    sums = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=((0, 0), (0, 0), (ph, rh), (pw, rw)),
    )
    counts = np.outer(_ave_counts(h, kh, sh, ph, oh),
                      _ave_counts(w, kw, sw, pw, ow))
    return sums / jnp.asarray(counts, x.dtype)[None, None, :, :]


def _patches(x, kernel, stride, pad, out):
    """(N, C, kh*kw, OH, OW) zero-padded window patches."""
    (kh, kw), (sh, sw), (ph, pw), (oh, ow) = kernel, stride, pad, out
    n, c, h, w = x.shape
    rh = _edge_pad(h, kh, sh, ph, oh)
    rw = _edge_pad(w, kw, sw, pw, ow)
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, rh), (pw, rw)))
    p = lax.conv_general_dilated_patches(
        xp, (kh, kw), (sh, sw), [(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # (N, C*kh*kw, OH, OW), channel-major ordering
    return p.reshape(n, c, kh * kw, oh, ow)


def stochastic_pool(x, kernel, stride, pad, out, train, rng):
    p = _patches(x, kernel, stride, pad, out)
    if train:
        logits = jnp.where(p > 0, jnp.log(jnp.maximum(p, 1e-30)), -jnp.inf)
        # all-nonpositive windows: fall back to uniform choice over window
        dead = jnp.all(p <= 0, axis=2, keepdims=True)
        logits = jnp.where(dead, jnp.zeros_like(logits), logits)
        idx = jax.random.categorical(rng, logits, axis=2)
        return jnp.take_along_axis(p, idx[:, :, None], axis=2)[:, :, 0]
    denom = jnp.sum(p, axis=2)
    num = jnp.sum(p * p, axis=2)
    return jnp.where(denom > 0, num / jnp.maximum(denom, 1e-30),
                     jnp.zeros_like(denom))


@register
class Pooling(Layer):
    type_name = "Pooling"
    needs_rng = True  # only STOCHASTIC actually consumes it

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        pp = lp.pooling_param
        self.method = int(pp.pool)
        n, c, h, w = bottom_shapes[0]
        self.kernel, self.stride, self.pad, self.out = \
            caffe_pool_geometry(pp, h, w)

    def out_shapes(self):
        n, c, h, w = self.bottom_shapes[0]
        return [(n, c) + self.out]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        if self.method == MAX:
            return [max_pool(x, self.kernel, self.stride, self.pad, self.out)]
        if self.method == AVE:
            return [ave_pool(x, self.kernel, self.stride, self.pad, self.out)]
        return [stochastic_pool(x, self.kernel, self.stride, self.pad,
                                self.out, train, rng)]


@register
class SPP(Layer):
    """Spatial pyramid pooling (reference spp_layer.cpp): levels 0..H-1 with
    2^i x 2^i bins each, flattened and concatenated along channels."""

    type_name = "SPP"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        sp = lp.spp_param
        self.method = int(sp.pool)
        self.height = int(sp.pyramid_height)
        n, c, h, w = bottom_shapes[0]
        self.levels = []
        for i in range(self.height):
            bins = 2 ** i
            kh = int(np.ceil(h / bins))
            ph = (kh * bins - h + 1) // 2
            kw = int(np.ceil(w / bins))
            pw = (kw * bins - w + 1) // 2
            self.levels.append(((kh, kw), (kh, kw), (ph, pw), (bins, bins)))

    def out_shapes(self):
        n, c, h, w = self.bottom_shapes[0]
        total = sum(b * b for _, _, _, (b, _) in
                    [(k, s, p, o) for k, s, p, o in self.levels]) * c
        return [(n, total)]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        n = x.shape[0]
        outs = []
        for kernel, stride, pad, out in self.levels:
            if self.method == MAX:
                y = max_pool(x, kernel, stride, pad, out)
            elif self.method == AVE:
                y = ave_pool(x, kernel, stride, pad, out)
            else:
                y = stochastic_pool(x, kernel, stride, pad, out, train, rng)
            outs.append(y.reshape(n, -1))
        return [jnp.concatenate(outs, axis=1)]
