"""Data-source layers.

In the reference these pull minibatches *inside* the graph — JavaDataLayer
upcalls into the JVM to fill a host buffer mid-forward (java_data_layer.cpp:
37-45), and DataLayer runs LMDB prefetch threads. On TPU the graph is a pure
compiled function, so every data layer becomes a *feed*: its tops are taken
from the ``batch`` dict passed to the compiled step (host loaders in
``sparknet_tpu.data`` produce those arrays and device_put them). This is the
design inversion called out in SURVEY.md section 7: callback-pull becomes
loader-push.

Shape resolution:
  JavaData     java_data_param.shape (one top, reference Layers.scala RDDLayer)
  MemoryData   memory_data_param dims; label top (batch,)
  DummyData    dummy_data_param shapes + fillers (generated in-graph)
  Data/ImageData/HDF5Data/WindowData  from the ``feed_shapes`` build argument
  (their on-disk sources are host-side concerns, see sparknet_tpu.data)
"""

import numpy as np
import jax.numpy as jnp

from ..graph.registry import Layer, register
from ..graph import fillers as F


class FeedLayer(Layer):
    """Tops come from the batch dict, keyed by top name."""

    is_feed = True

    def __init__(self, lp, bottom_shapes, phase, feed_shapes=None):
        super().__init__(lp, bottom_shapes, phase)
        self.feed_shapes = feed_shapes or {}

    def _external_shapes(self, batch_size_hint=None):
        shapes = []
        for top in self.lp.top:
            if top in self.feed_shapes:
                shapes.append(tuple(self.feed_shapes[top]))
            elif top == "label" and batch_size_hint:
                shapes.append((batch_size_hint,))
            else:
                raise ValueError(
                    f"data layer {self.lp.name!r}: provide feed_shapes[{top!r}] "
                    f"at build time (its source is host-side)")
        return shapes

    def out_shapes(self):
        raise NotImplementedError

    def apply(self, params, bottoms, train, rng):
        raise RuntimeError("feed layers are resolved by the compiler")


@register
class JavaData(FeedLayer):
    type_name = "JavaData"

    def out_shapes(self):
        p = self.lp.java_data_param
        shapes = []
        for i, top in enumerate(self.lp.top):
            if top in self.feed_shapes:  # build-time override (e.g. the
                shapes.append(tuple(self.feed_shapes[top]))  # per-shard net)
            elif i == 0 and p.has("shape"):
                # java_data_param.shape describes the FIRST top only
                shapes.append(tuple(int(d) for d in p.shape.dim))
            elif i > 0 and p.has("shape"):
                # trailing tops are labels: (batch,), like Caffe data layers
                shapes.append((int(p.shape.dim[0]),))
            else:
                raise ValueError(
                    f"JavaData layer {self.lp.name!r}: no shape for top "
                    f"{top!r} (provide feed_shapes[{top!r}])")
        return shapes


@register
class Data(FeedLayer):
    type_name = "Data"

    def out_shapes(self):
        bs = int(self.lp.data_param.batch_size) \
            if self.lp.has("data_param") else None
        return self._external_shapes(batch_size_hint=bs)


@register
class ImageData(FeedLayer):
    type_name = "ImageData"

    def out_shapes(self):
        bs = int(self.lp.image_data_param.batch_size) \
            if self.lp.has("image_data_param") else None
        return self._external_shapes(batch_size_hint=bs)


@register
class WindowData(FeedLayer):
    type_name = "WindowData"

    def out_shapes(self):
        bs = int(self.lp.window_data_param.batch_size) \
            if self.lp.has("window_data_param") else None
        return self._external_shapes(batch_size_hint=bs)


@register
class HDF5Data(FeedLayer):
    type_name = "HDF5Data"

    def out_shapes(self):
        bs = int(self.lp.hdf5_data_param.batch_size) \
            if self.lp.has("hdf5_data_param") else None
        return self._external_shapes(batch_size_hint=bs)


@register
class MemoryData(FeedLayer):
    type_name = "MemoryData"

    def out_shapes(self):
        p = self.lp.memory_data_param
        shape = (int(p.batch_size), int(p.channels), int(p.height),
                 int(p.width))
        outs = [shape]
        if len(self.lp.top) > 1:
            outs.append((int(p.batch_size),))
        return outs


@register
class DummyData(Layer):
    """Generates tops from fillers in-graph (dummy_data_layer.cpp). Constant
    fillers are baked; random fillers draw from the step rng."""

    type_name = "DummyData"
    needs_rng = True

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.dummy_data_param
        if p.shape:
            self.shapes = [tuple(int(d) for d in s.dim) for s in p.shape]
        else:
            self.shapes = [(int(p.num[i]), int(p.channels[i]),
                            int(p.height[i]), int(p.width[i]))
                           for i in range(len(p.num))]
        n = len(self.shapes)
        fl = list(p.data_filler)
        if not fl:
            self.fillers = [None] * n
        elif len(fl) == 1:
            self.fillers = fl * n
        else:
            self.fillers = fl

    def out_shapes(self):
        return self.shapes

    def apply(self, params, bottoms, train, rng):
        import jax
        keys = jax.random.split(rng, len(self.shapes)) if rng is not None \
            else [None] * len(self.shapes)
        return [F.fill(k, s, f) for k, s, f in
                zip(keys, self.shapes, self.fillers)]


@register
class HDF5Output(Layer):
    """Sink layer (reference hdf5_output_layer.cpp wrote bottoms to disk).
    In a pure graph it is a no-op passthrough-to-nowhere; the CLI offers
    blob dumping instead."""

    type_name = "HDF5Output"

    def out_shapes(self):
        return []

    def apply(self, params, bottoms, train, rng):
        return []
