"""Fused conv-epilogue pallas kernels: bias+ReLU and bias+ReLU+LRN.

The XLA lowering of a Convolution layer's tail is bias-add + ReLU fused
into the conv output's epilogue, followed (in the GoogLeNet conv2 tower
and stock AlexNet variants) by a separate ACROSS_CHANNELS LRN that costs
several more HBM round-trips of the full activation (ops/lrn.py; the
pallas_lrn.py module header has the trace evidence). Running the LRN as
its own pallas kernel was a measured LOSS on v5e (PERF.md round-3): it
broke the bias+ReLU epilogue fusion and added a materialization
boundary. These kernels close that gap the other way — the entire
epilogue (bias add, ReLU, and optionally the channel-window LRN) runs in
ONE read and one write of the raw conv output, so the pallas boundary no
longer costs an extra pass:

    bias_relu:      out = max(x + b, 0)
    bias_relu_lrn:  y = max(x + b, 0)
                    out = y * (k + alpha/size * sum_{window} y^2)^-beta

Backward reuses the structure of pallas_lrn: the residual is the RAW
conv output x plus the (C,) bias — both already live — and the bwd pass
recomputes y = relu(x+b) instead of saving a second activation. For
bias_relu the backward is pure elementwise (dx = g * (y > 0)) and stays
in XLA where it fuses with its neighbors; only the LRN variant needs the
pallas backward, which it borrows from pallas_lrn._call_bwd applied to
y. dbias = sum(dx) over (N, H, W) is an XLA reduce outside the kernel.

Layout matches pallas_lrn: NCHW flattened to (N, C, H*W), spatial tiled
in 512-lane blocks, channels on the sublane axis. The bias rides in as a
(1, C, 128) broadcast so its block is a legal TPU tile at any C.

Selection lives in graph/compiler.py (SPARKNET_EPILOGUE gate); this
module only provides the fused ops.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_lrn import SPATIAL_BLOCK, _should_interpret, _window_sum, \
    _call_bwd as _lrn_call_bwd


def _bias_tile(b, dtype):
    """(C,) bias -> (1, C, 128) broadcast: a legal TPU tile whose block
    index map pins every grid step to the same lanes."""
    c = b.shape[0]
    return jnp.broadcast_to(b.astype(dtype).reshape(1, c, 1), (1, c, 128))


def _bias_relu_kernel(x_ref, b_ref, out_ref):
    x = x_ref[0].astype(jnp.float32)
    b = b_ref[0][:, :1].astype(jnp.float32)        # (C, 1) column
    out_ref[0] = jnp.maximum(x + b, 0.0).astype(out_ref.dtype)


def _bias_relu_lrn_kernel(size, alpha, beta, k, x_ref, b_ref, out_ref):
    x = x_ref[0].astype(jnp.float32)
    b = b_ref[0][:, :1].astype(jnp.float32)
    y = jnp.maximum(x + b, 0.0)
    half = (size - 1) // 2
    scale = k + (alpha / size) * _window_sum(y * y, size, half)
    out_ref[0] = (y * scale ** (-beta)).astype(out_ref.dtype)


def _call_epilogue(kernel, x, b, interpret):
    n, c, h, w = x.shape
    xf = x.reshape(n, c, h * w)
    bt = _bias_tile(b, x.dtype)
    grid = (n, pl.cdiv(h * w, SPATIAL_BLOCK))
    spec = pl.BlockSpec((1, c, SPATIAL_BLOCK), lambda i, j: (i, 0, j))
    bspec = pl.BlockSpec((1, c, 128), lambda i, j: (0, 0, 0))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec, bspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        interpret=interpret,
    )(xf, bt)
    return out.reshape(n, c, h, w)


# -- bias + ReLU -----------------------------------------------------------
@jax.custom_vjp
def bias_relu(x, b):
    """max(x + b[None,:,None,None], 0) on NCHW, one fused pass."""
    return _call_epilogue(_bias_relu_kernel, x, b, _should_interpret())


def _br_fwd(x, b):
    return bias_relu(x, b), (x, b)


def _br_bwd(res, g):
    x, b = res
    # recompute the mask from the cheap elementwise fwd; stays in XLA
    # where it fuses with whatever consumes dx
    y = x + b.astype(x.dtype)[None, :, None, None]
    dx = jnp.where(y > 0, g, jnp.zeros_like(g))
    db = jnp.sum(dx.astype(jnp.float32), axis=(0, 2, 3)).astype(b.dtype)
    return dx, db


bias_relu.defvjp(_br_fwd, _br_bwd)


# -- bias + ReLU + cross-channel LRN ---------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def bias_relu_lrn(x, b, size, alpha, beta, k):
    """lrn_across(max(x + b, 0)) on NCHW in ONE fused read/write."""
    return _call_epilogue(
        functools.partial(_bias_relu_lrn_kernel, size, alpha, beta, k),
        x, b, _should_interpret())


def _brl_fwd(x, b, size, alpha, beta, k):
    return bias_relu_lrn(x, b, size, alpha, beta, k), (x, b)


def _brl_bwd(size, alpha, beta, k, res, g):
    x, b = res
    y = jnp.maximum(x + b.astype(x.dtype)[None, :, None, None], 0)
    # d(lrn)/dy via the existing fused LRN backward kernel, then the ReLU
    # mask; both read y, which XLA materializes once
    dy = _lrn_call_bwd(y, g, size, alpha, beta, k, _should_interpret())
    dx = jnp.where(y > 0, dy, jnp.zeros_like(dy))
    db = jnp.sum(dx.astype(jnp.float32), axis=(0, 2, 3)).astype(b.dtype)
    return dx, db


bias_relu_lrn.defvjp(_brl_fwd, _brl_bwd)
