"""Neuron (elementwise) layers — XLA fuses these into adjacent matmul/conv
HLOs, so each is a plain jnp expression (replaces the per-op CUDA kernels in
reference neuron layers, e.g. relu_layer.cu, dropout_layer.cu).
"""

import jax
import jax.numpy as jnp

from ..graph.registry import Layer, register
from ..proto.message import Message


class _Elementwise(Layer):
    def out_shapes(self):
        return [self.bottom_shapes[0]]


@register
class ReLU(_Elementwise):
    type_name = "ReLU"

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        slope = self.lp.relu_param.negative_slope if self.lp.has("relu_param") \
            else 0.0
        if slope:
            return [jnp.where(x > 0, x, slope * x)]
        return [jnp.maximum(x, 0)]


@register
class PReLU(_Elementwise):
    """Learned negative slope (reference prelu_layer.cpp); slope blob is per
    channel, or a single scalar when channel_shared."""

    type_name = "PReLU"

    def __init__(self, lp, bottom_shapes, phase):
        super().__init__(lp, bottom_shapes, phase)
        p = lp.prelu_param
        self.shared = bool(p.channel_shared)
        self.channels = bottom_shapes[0][1] if len(bottom_shapes[0]) > 1 else 1
        self.filler = p.filler if p.has("filler") else \
            Message("FillerParameter", type="constant", value=0.25)

    def param_shapes(self):
        from .convolution import _param_mults
        shape = (1,) if self.shared else (self.channels,)
        (m,) = _param_mults(self.lp, 1)
        return [(shape, self.filler, *m)]

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        slope = params[0].astype(x.dtype)
        if not self.shared:
            bshape = [1] * x.ndim
            bshape[1] = self.channels
            slope = slope.reshape(bshape)
        return [jnp.maximum(x, 0) + slope * jnp.minimum(x, 0)]


@register
class Sigmoid(_Elementwise):
    type_name = "Sigmoid"

    def apply(self, params, bottoms, train, rng):
        return [jax.nn.sigmoid(bottoms[0])]


@register
class TanH(_Elementwise):
    type_name = "TanH"

    def apply(self, params, bottoms, train, rng):
        return [jnp.tanh(bottoms[0])]


@register
class BNLL(_Elementwise):
    """log(1 + exp(x)), computed stably (reference bnll_layer.cpp)."""

    type_name = "BNLL"

    def apply(self, params, bottoms, train, rng):
        return [jax.nn.softplus(bottoms[0])]


@register
class AbsVal(_Elementwise):
    type_name = "AbsVal"

    def apply(self, params, bottoms, train, rng):
        return [jnp.abs(bottoms[0])]


@register
class Power(_Elementwise):
    """(shift + scale * x) ^ power (reference power_layer.cpp)."""

    type_name = "Power"

    def apply(self, params, bottoms, train, rng):
        p = self.lp.power_param
        y = p.shift + p.scale * bottoms[0]
        if p.power == 1.0:
            return [y]
        return [y ** p.power]


@register
class Exp(_Elementwise):
    """base^(shift + scale*x); base -1 means e (reference exp_layer.cpp)."""

    type_name = "Exp"

    def apply(self, params, bottoms, train, rng):
        p = self.lp.exp_param
        inner = p.shift + p.scale * bottoms[0]
        if p.base == -1.0:
            return [jnp.exp(inner)]
        return [jnp.asarray(p.base, bottoms[0].dtype) ** inner]


@register
class Log(_Elementwise):
    """log_base(shift + scale*x) (reference log_layer.cpp)."""

    type_name = "Log"

    def apply(self, params, bottoms, train, rng):
        p = self.lp.log_param
        y = jnp.log(p.shift + p.scale * bottoms[0])
        if p.base != -1.0:
            y = y / jnp.log(jnp.asarray(p.base, bottoms[0].dtype))
        return [y]


@register
class Threshold(_Elementwise):
    """x > threshold ? 1 : 0 (reference threshold_layer.cpp)."""

    type_name = "Threshold"

    def apply(self, params, bottoms, train, rng):
        t = self.lp.threshold_param.threshold
        x = bottoms[0]
        return [(x > t).astype(x.dtype)]


@register
class Dropout(_Elementwise):
    """Inverted dropout (reference dropout_layer.cpp): TRAIN scales kept
    units by 1/(1-ratio); TEST is identity."""

    type_name = "Dropout"
    needs_rng = True

    def apply(self, params, bottoms, train, rng):
        x = bottoms[0]
        if not train:
            return [x]
        ratio = self.lp.dropout_param.dropout_ratio
        keep = 1.0 - ratio
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0).astype(x.dtype)]
