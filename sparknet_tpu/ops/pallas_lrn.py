"""Fused cross-channel LRN as pallas TPU kernels, forward and backward.

The XLA lowering of ACROSS_CHANNELS LRN (ops/lrn.py; reference
lrn_layer.cpp:108-151 / lrn_layer.cu) is a chain of elementwise ops
around a channel-window reduce_window: zero MXU FLOPs, several HBM
round-trips of the full activation. The trace work in PERF.md shows both
flagship CNNs paying it as pure VPU/HBM wall time between the big
matmuls. These kernels do each pass in ONE read and one write of the
activation: the channel-window sum runs over a (C, spatial-tile) VMEM
block as `size` shifted adds along the non-lane axis.

Forward (lrn_layer.cpp:108-133):
    scale = k + alpha/size * sum_{window} x^2,  out = x * scale^-beta
Backward (lrn_layer.cpp:180-204, the cuda CrossChannelBackward):
    dx = g * scale^-beta
       - (2*alpha*beta/size) * x * sum_{mirrored window} g*x*scale^(-beta-1)

The mirrored window: position i contributes to outputs j with
j - half <= i <= j + (size-1-half), so the backward gathers over
offsets [-(size-1-half), +half] — the forward window reversed.

Layout: callers pass NCHW; spatial dims are flattened to one minor axis
and tiled in 512-lane blocks, channels ride the sublane axis where the
shifted adds are cheap register moves. Block padding at the spatial edge
is benign (garbage lanes compute garbage scale and are masked on write).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SPATIAL_BLOCK = 512


def _should_interpret():
    return jax.default_backend() != "tpu"


def _window_sum(t, size, lo):
    """sum over window offsets [-lo, size-1-lo] along axis 0, zero-padded."""
    c = t.shape[0]
    tp = jnp.pad(t, ((lo, size - 1 - lo), (0, 0)))
    out = tp[0:c]
    for d in range(1, size):
        out = out + tp[d:d + c]
    return out


def _fwd_kernel(size, alpha, beta, k, x_ref, out_ref):
    x = x_ref[0].astype(jnp.float32)
    half = (size - 1) // 2
    scale = k + (alpha / size) * _window_sum(x * x, size, half)
    out_ref[0] = (x * scale ** (-beta)).astype(out_ref.dtype)


def _bwd_kernel(size, alpha, beta, k, x_ref, g_ref, dx_ref):
    # scale is recomputed from x (a few VPU adds) rather than saved by the
    # forward: writing an f32 scale tensor would 1.5x the forward's HBM
    # traffic and hold a full f32 activation as a residual
    x = x_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    half = (size - 1) // 2
    scale = k + (alpha / size) * _window_sum(x * x, size, half)
    t = g * x * scale ** (-beta - 1.0)
    acc = _window_sum(t, size, size - 1 - half)     # mirrored window
    dx = g * scale ** (-beta) - (2.0 * alpha * beta / size) * x * acc
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _call_fwd(x, size, alpha, beta, k, interpret):
    n, c, h, w = x.shape
    xf = x.reshape(n, c, h * w)
    grid = (n, pl.cdiv(h * w, SPATIAL_BLOCK))
    spec = pl.BlockSpec((1, c, SPATIAL_BLOCK), lambda i, j: (i, 0, j))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, size, alpha, beta, k),
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        interpret=interpret,
    )(xf)
    return out.reshape(n, c, h, w)


def _call_bwd(x, g, size, alpha, beta, k, interpret):
    n, c, h, w = x.shape
    xf = x.reshape(n, c, h * w)
    gf = g.reshape(n, c, h * w)
    grid = (n, pl.cdiv(h * w, SPATIAL_BLOCK))
    spec = pl.BlockSpec((1, c, SPATIAL_BLOCK), lambda i, j: (i, 0, j))
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, size, alpha, beta, k),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(xf.shape, g.dtype),
        interpret=interpret,
    )(xf, gf)
    return dx.reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_across(x, size, alpha, beta, k):
    """Cross-channel LRN on NCHW, fused fwd; exact Caffe semantics."""
    return _call_fwd(x, size, alpha, beta, k, _should_interpret())


def _lrn_fwd(x, size, alpha, beta, k):
    return (_call_fwd(x, size, alpha, beta, k, _should_interpret()), (x,))


def _lrn_bwd(size, alpha, beta, k, res, g):
    (x,) = res
    dx = _call_bwd(x, g, size, alpha, beta, k, _should_interpret())
    return (dx,)


lrn_across.defvjp(_lrn_fwd, _lrn_bwd)
