"""The canonical process exit-code table — ONE place, named constants.

Supervisors (k8s restart policies, the DEPLOY.md runbook, smoke.sh)
branch on these numbers, so a raw literal drifting in some call site is
an operational bug: the supervisor reads "42" as watchdog-killed whether
or not the code that exited meant that. `sparknet lint` SPK304 enforces
that every ``sys.exit``/``os._exit`` call with a non-trivial code spells
it through this table (0/1/2 are the universal Unix conventions and may
stay literal).

| code | name                | meaning                                    |
|------|---------------------|--------------------------------------------|
| 0    | EXIT_OK             | success                                    |
| 1    | EXIT_FAILURE        | generic failure; lint findings             |
| 2    | EXIT_USAGE          | bad usage / unreadable metrics or baseline |
| 3    | EXIT_RECOVERY_ABORT | divergence recovery gave up (RecoveryAbort)|
| 4    | EXIT_QUORUM_LOST    | too few live hosts for consensus           |
| 42   | EXIT_WATCHDOG_STALL | watchdog killed a stalled run              |

Adding a code: define the constant here, document it in DEPLOY.md, and
teach the supervisor — SPK304 flags any literal it has never heard of.
"""

EXIT_OK = 0
EXIT_FAILURE = 1
EXIT_USAGE = 2
EXIT_RECOVERY_ABORT = 3
EXIT_QUORUM_LOST = 4
EXIT_WATCHDOG_STALL = 42
