"""Timing — the reference util/benchmark.cpp Timer/CPUTimer, plus a
step-rate tracker and XLA profiler hookup.

Reference Timer used CUDA events for device-accurate timing; on TPU the
analog is forcing a value fetch (transfer of a scalar) before reading the
clock — under the axon tunnel block_until_ready alone does not synchronize.
"""

import contextlib
import time

import numpy as np


class Timer:
    """Start/Stop/MilliSeconds like benchmark.cpp:26-142."""

    def __init__(self):
        self._start = None
        self._elapsed = 0.0

    def start(self):
        self._start = time.perf_counter()
        return self

    def stop(self, sync=None):
        """sync: an optional jax array to fetch (device barrier)."""
        if sync is not None:
            np.asarray(sync).ravel()[:1]
        if self._start is not None:
            self._elapsed += time.perf_counter() - self._start
            self._start = None
        return self

    def milliseconds(self):
        return self._elapsed * 1e3

    def seconds(self):
        return self._elapsed


class StepTimer:
    """Rolling images/sec + step-time stats for the training loop."""

    def __init__(self, window=20):
        self.window = window
        self.times = []
        self._last = None

    def tick(self, batch_size=None):
        now = time.perf_counter()
        if self._last is not None:
            self.times.append((now - self._last, batch_size or 0))
            if len(self.times) > self.window:
                self.times.pop(0)
        self._last = now

    def step_ms(self):
        if not self.times:
            return float("nan")
        return float(np.mean([t for t, _ in self.times])) * 1e3

    def images_per_sec(self):
        ts = [(t, b) for t, b in self.times if b]
        if not ts:
            return float("nan")
        return sum(b for _, b in ts) / sum(t for t, _ in ts)


@contextlib.contextmanager
def xla_profile(log_dir="/tmp/sparknet_profile"):
    """Capture an XLA profiler trace around a block (view with
    tensorboard/xprof) — the `caffe time` deep-dive analog on TPU."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
