"""Training watchdog — failure detection the reference deliberately lacked.

SparkNet set spark.task.maxFailures=1 (CifarApp.scala:38): ANY failure was
fatal because native solver state couldn't survive Spark's lineage replay
(SURVEY.md section 5). With explicit checkpoints the right behavior is the
opposite: detect a stall (hung host callback, wedged device, dead peer) and
act — snapshot, log, or kill the process so the job scheduler restarts it
from the checkpoint.

Also detects non-finite losses (the "model blew up" failure class) so long
unattended runs stop burning chips on NaNs.
"""

import math
import os
import sys
import threading
import time

from .exit_codes import EXIT_WATCHDOG_STALL


class Watchdog:
    """Arm with expected step cadence; the training loop calls beat(loss).

    on_stall(elapsed) is invoked from the monitor thread once per stall
    detection (then re-arms); on_nan(loss) from beat(). Defaults: log via
    print; kill_on_stall escalates to os._exit so an external supervisor
    (k8s, xmanager) can reschedule from the last snapshot.

    With ``metrics`` (a utils.metrics.MetricsLogger), every stall/NaN
    also lands in the run's JSONL as a ``watchdog`` event, so `sparknet
    report` surfaces failure barks next to the loss curve they garbled.
    """

    def __init__(self, stall_seconds=300.0, on_stall=None, on_nan=None,
                 kill_on_stall=False, poll_seconds=None, metrics=None,
                 emergency_snapshot=None, emergency_timeout_s=30.0,
                 exit_fn=None):
        self.stall_seconds = float(stall_seconds)
        self.on_stall = on_stall or (lambda dt: print(
            f"[watchdog] no training step for {dt:.0f}s"))
        self.on_nan = on_nan or (lambda loss: print(
            f"[watchdog] non-finite loss {loss}"))
        self.kill_on_stall = kill_on_stall
        # kill path state preservation: a zero-arg snapshot callback tried
        # best-effort (own thread, bounded by emergency_timeout_s — a
        # wedged device can hang a snapshot too), then a final metrics
        # flush, THEN os._exit(42). exit_fn is injectable for tests.
        self.emergency_snapshot = emergency_snapshot
        self.emergency_timeout_s = float(emergency_timeout_s)
        self._exit = exit_fn or os._exit
        self.metrics = metrics
        self.poll = poll_seconds or min(10.0, self.stall_seconds / 4)
        # the beat timestamp is the one field BOTH sides touch — the
        # training thread writes it per step, the monitor thread reads
        # and re-arms it (a race here mistimes stall detection; found
        # by the SPK204 lock-discipline checker, sparknet lint)
        self._lock = threading.Lock()
        self._last = time.monotonic()   # spk: guarded-by=_lock
        self._stop = threading.Event()
        self._thread = None
        self.stalls = 0
        self.nans = 0

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self                     # idempotent: don't leak threads
        with self._lock:
            self._last = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sparknet-watchdog")
        self._thread.start()
        return self

    def beat(self, loss=None):
        """Call once per training step (host-side, costs nothing)."""
        with self._lock:
            self._last = time.monotonic()
        if loss is not None:
            v = float(loss)
            if not math.isfinite(v):
                self.nans += 1
                if self.metrics is not None:
                    self.metrics.log("watchdog", kind="nan", loss=v)
                self.on_nan(v)

    def _run(self):
        while not self._stop.wait(self.poll):
            with self._lock:
                dt = time.monotonic() - self._last
            if dt > self.stall_seconds:
                self.stalls += 1
                if self.metrics is not None:
                    self.metrics.log("watchdog", kind="stall",
                                     elapsed_s=round(dt, 1))
                try:
                    self.on_stall(dt)
                except Exception as e:      # a raising callback must not
                    print(f"[watchdog] on_stall raised: {e!r}",  # kill the
                          file=sys.stderr)                # monitor thread
                if self.kill_on_stall:
                    self._emergency_exit()
                with self._lock:
                    self._last = time.monotonic()   # re-arm

    def _emergency_exit(self):
        """Best-effort snapshot + metrics flush, then exit 42 (the code
        DEPLOY.md tells supervisors to restart with --resume auto)."""
        ok = None
        if self.emergency_snapshot is not None:
            result = {}

            def work():
                try:
                    result["path"] = self.emergency_snapshot()
                except Exception as e:
                    result["error"] = repr(e)

            t = threading.Thread(target=work, daemon=True,
                                 name="sparknet-emergency-snapshot")
            t.start()
            t.join(self.emergency_timeout_s)
            ok = "error" not in result and not t.is_alive()
            if not ok:
                print("[watchdog] emergency snapshot "
                      + ("timed out" if t.is_alive()
                         else f"failed: {result.get('error')}"),
                      file=sys.stderr)
        if self.metrics is not None:
            self.metrics.log("watchdog", kind="killed",
                             exit_code=EXIT_WATCHDOG_STALL,
                             emergency_snapshot_ok=ok)
            self.metrics.close()            # final flush before _exit
        self._exit(EXIT_WATCHDOG_STALL)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
