"""Structured metrics — what the reference lacked (SURVEY.md section 5:
"No structured metrics system"); loss/accuracy went to glog + ad-hoc
timing logs (CifarApp.scala:43-52). One JSONL stream, one line per event."""

import json
import sys
import time


class MetricsLogger:
    def __init__(self, path=None, stream=None, run_id=None):
        self.f = open(path, "a") if path else (stream or sys.stderr)
        self._own = path is not None
        self.run_id = run_id
        self.t0 = time.time()

    def log(self, event, **fields):
        rec = {"event": event, "t": round(time.time() - self.t0, 3)}
        if self.run_id:
            rec["run"] = self.run_id
        for k, v in fields.items():
            if hasattr(v, "item"):      # numpy/jax scalar
                v = v.item()
            rec[k] = v
        self.f.write(json.dumps(rec) + "\n")
        self.f.flush()

    def close(self):
        if self._own:
            self.f.close()
