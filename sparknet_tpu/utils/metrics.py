"""Structured metrics — what the reference lacked (SURVEY.md section 5:
"No structured metrics system"); loss/accuracy went to glog + ad-hoc
timing logs (CifarApp.scala:43-52). One JSONL stream, one line per event.

This is the backend of the sparknet_tpu.obs subsystem: the span tracer,
step accounting, comms meter, watchdog, and prefetch gauges all write
through one MetricsLogger, so a single JSONL file carries the whole run
and `sparknet report` can reconstruct it. Consequences: writes are
thread-safe (the tracer and watchdog log from their own threads), the
logger is a context manager, and field encoding must never crash a run —
numpy arrays, dtypes, Paths, and anything else non-JSON go through a
safe default encoder instead of raising mid-training.
"""

import json
import sys
import threading
import time


def json_default(o):
    """Best-effort JSON encoding for arbitrary metric field values."""
    if getattr(o, "ndim", None) == 0 and hasattr(o, "item"):
        try:
            return o.item()            # numpy/jax scalar
        except Exception:
            pass
    if hasattr(o, "tolist"):           # ndarray / jax array
        try:
            if getattr(o, "size", 0) <= 64:
                return o.tolist()
            return {"shape": list(getattr(o, "shape", ())),
                    "dtype": str(getattr(o, "dtype", "?")),
                    "summary": "array too large; elided"}
        except Exception:
            pass
    if isinstance(o, (set, frozenset)):
        return sorted(str(x) for x in o)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)                      # dtypes, Paths, enums, ...


class MetricsLogger:
    def __init__(self, path=None, stream=None, run_id=None):
        # lock discipline (checked by `sparknet lint`, SPK201/202): the
        # stream handle and closed flag are shared with the watchdog /
        # tracer / prefetch threads that log through this object
        self._lock = threading.Lock()
        stream = stream or sys.stderr
        self.f = open(path, "a") if path else stream  # spk: guarded-by=_lock
        self._own = path is not None
        self.run_id = run_id
        self.t0 = time.time()
        self._closed = False            # spk: guarded-by=_lock

    def log(self, event, **fields):
        rec = {"event": event, "t": round(time.time() - self.t0, 4)}
        if self.run_id:
            rec["run"] = self.run_id
        for k, v in fields.items():
            if hasattr(v, "item") and getattr(v, "ndim", 0) == 0:
                try:
                    v = v.item()       # numpy/jax scalar fast path
                except Exception:
                    pass
            rec[k] = v
        try:
            line = json.dumps(rec, default=json_default)
        except (TypeError, ValueError) as e:
            # circular refs etc. — record that the event existed
            line = json.dumps({"event": event, "t": rec["t"],
                               "encode_error": str(e)})
        with self._lock:
            if self._closed:
                return
            self.f.write(line + "\n")
            self.f.flush()

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._own:
                self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
