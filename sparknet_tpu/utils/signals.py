"""Signal-triggered snapshot/stop.

The reference installs SIGINT/SIGHUP handlers whose effects (snapshot /
stop / none) come from CLI flags (util/signal_handler.cpp:99-112,
tools/caffe.cpp:43-46); the solver polls CheckForSignals between steps.
Same design: handlers only record; the training loop polls pending().

Beyond the reference: SIGTERM — the preemption notice every scheduler
(k8s, borg, spot VMs) sends before a kill — maps to "snapshot_stop"
(snapshot, then stop cleanly), so a preempted job loses at most the
steps since its last sync round and `--resume auto` picks it back up.

Multi-process discipline: a scheduler delivers the SIGTERM to EVERY
process of the job, and each polls its own handler — but N processes
must not race N writes of the same (replicated) snapshot. The snapshot
the handlers trigger goes through Solver._snapshot, where only the
designated writer (process 0, or the lowest live host once failures
start) commits; the others barrier on the manifest it produced
(resilience/checkpoint.wait_for_manifest) and then stop with the same
documented exit code 0. See the DEPLOY.md preemption runbook.
"""

import signal


ACTIONS = ("snapshot", "stop", "snapshot_stop", "none")


class SignalPolicy:
    def __init__(self, sigint="stop", sighup="snapshot", sigterm="none"):
        for a in (sigint, sighup, sigterm):
            if a not in ACTIONS:
                raise ValueError(f"unknown signal action {a!r}")
        self.effects = {signal.SIGINT: sigint, signal.SIGHUP: sighup,
                        signal.SIGTERM: sigterm}
        self._pending = []
        self._prev = {}

    def _handler(self, signum, frame):
        action = self.effects.get(signum, "none")
        if action == "none":
            return
        if signum == signal.SIGINT and "stop" in action \
                and any("stop" in p for p in self._pending):
            # second ^C: restore default and re-raise (escape hatch)
            signal.signal(signal.SIGINT, signal.SIG_DFL)
            raise KeyboardInterrupt
        self._pending.append(action)

    def __enter__(self):
        for signum, action in self.effects.items():
            if action == "none" and signum == signal.SIGTERM:
                continue          # leave the default die-on-TERM alone
            try:
                self._prev[signum] = signal.signal(signum, self._handler)
            except ValueError:        # non-main thread: polling still works
                pass
        return self

    def __exit__(self, *exc):
        for signum, prev in self._prev.items():
            signal.signal(signum, prev)
        return False

    def pending(self):
        """Pop the oldest pending action ('snapshot'|'stop'|
        'snapshot_stop') or None — the Solver::GetRequestedAction analog."""
        return self._pending.pop(0) if self._pending else None
