"""Runtime utilities: signal-driven snapshot/stop, metrics, timing."""

from .signals import SignalPolicy
from .metrics import MetricsLogger
from .timing import Timer, StepTimer
from .watchdog import Watchdog

__all__ = ["SignalPolicy", "MetricsLogger", "Timer", "StepTimer",
           "Watchdog"]
