"""Model DSL + zoo (replaces reference Layers.scala + caffe/models/*)."""

from . import dsl
from .zoo import lenet, cifar10_full, caffenet, googlenet

__all__ = ["dsl", "lenet", "cifar10_full", "caffenet", "googlenet"]
