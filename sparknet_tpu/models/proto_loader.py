"""Prototxt manipulation utilities — parity with reference ProtoLoader.scala.

The reference round-tripped prototxt text through a C++ parser to get
protobuf-java objects (ProtoLoader.scala:9-29); here the text parser is
native Python (proto.text_format), so these are plain Message transforms.
"""

from ..proto import Message, text_format


def load_net_prototxt(path):
    """ProtoLoader.loadNetPrototxt (:20-29)."""
    return text_format.load(path, "NetParameter")


def load_solver_prototxt_with_net(solver_path, net, snapshot_prefix=None):
    """ProtoLoader.loadSolverPrototxtWithNet (:31-43): load a solver
    prototxt, embed ``net`` as net_param, and clear file-based net refs;
    snapshotting is cleared unless a prefix is given (the reference apps
    pass None — the driver's in-memory weights are the checkpoint)."""
    sp = text_format.load(solver_path, "SolverParameter")
    for f in ("net", "train_net", "test_net", "train_net_param",
              "test_net_param", "net_param"):
        sp.clear(f)
    sp.net_param = net
    if snapshot_prefix is None:
        sp.clear("snapshot")
        sp.clear("snapshot_prefix")
    else:
        sp.snapshot_prefix = snapshot_prefix
    return sp


def replace_data_layers(net, train_batch, test_batch, channels, height,
                        width, data_blob="data", label_blob="label"):
    """ProtoLoader.replaceDataLayers (:50-57): drop the first data layers
    and prepend JavaData train/test pairs producing (data, label) tops."""
    out = net.copy()
    layers = [lp for lp in out.layer
              if lp.type not in ("Data", "JavaData", "ImageData", "HDF5Data",
                                 "MemoryData", "WindowData", "DummyData")]
    out.clear("layer")

    def java_data(name, batch, phase):
        lp = Message("LayerParameter", name=name, type="JavaData")
        lp.top.append(data_blob)
        lp.top.append(label_blob)
        shape = Message("BlobShape")
        shape.dim.extend([batch, channels, height, width])
        lp.java_data_param = Message("JavaDataParameter", shape=shape)
        lp.include.append(Message("NetStateRule", phase=phase))
        return lp

    out.layer.append(java_data("java_train_data", train_batch, 0))  # TRAIN
    out.layer.append(java_data("java_test_data", test_batch, 1))    # TEST
    for lp in layers:
        out.layer.append(lp)
    return out
