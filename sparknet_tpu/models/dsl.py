"""Model-building DSL: Python builders producing LayerParameter messages.

The capability of the reference's Scala DSL (Layers.scala:18-137 — RDDLayer,
ConvolutionLayer, PoolingLayer, InnerProductLayer, ReLULayer, SoftmaxWithLoss,
NetParam), extended with the builders the bigger nets need (LRN, Dropout,
Concat, Accuracy, BatchNorm, Eltwise, Attention). Each returns a proto
Message, so DSL output and parsed prototxt are the same IR.
"""

from ..proto import Message

TRAIN, TEST = "TRAIN", "TEST"


def _base(type_name, name, bottoms=None, tops=None, include=None, **fields):
    lp = Message("LayerParameter", name=name, type=type_name, **fields)
    for b in (bottoms or []):
        lp.bottom.append(b)
    tops = [name] if tops is None else tops
    for t in tops:
        lp.top.append(t)
    if include is not None:
        lp.add("include", phase=include)
    return lp


def RDDLayer(name, shape, include=None):
    """Data feed layer (reference Layers.scala RDDLayer :18-40): one top,
    named after the layer, shape fixed up front."""
    return _base("JavaData", name, include=include,
                 java_data_param=dict(shape=dict(dim=list(shape))))


def ConvolutionLayer(name, bottoms, kernel, num_output, stride=None, pad=None,
                     group=None, weight_filler=None, bias_filler=None,
                     param=None):
    cp = dict(kernel_h=kernel[0], kernel_w=kernel[1], num_output=num_output)
    if stride is not None:
        cp.update(stride_h=stride[0], stride_w=stride[1])
    if pad is not None:
        cp.update(pad_h=pad[0], pad_w=pad[1])
    if group is not None:
        cp["group"] = group
    if weight_filler is not None:
        cp["weight_filler"] = weight_filler
    if bias_filler is not None:
        cp["bias_filler"] = bias_filler
    lp = _base("Convolution", name, bottoms, convolution_param=cp)
    for p in (param or []):
        lp.add("param", **p)
    return lp


def PoolingLayer(name, bottoms, pooling, kernel, stride, pad=None):
    """pooling: 'MAX' | 'AVE' | 'STOCHASTIC' (Layers.scala PoolingLayer)."""
    pp = dict(pool=pooling, kernel_h=kernel[0], kernel_w=kernel[1],
              stride_h=stride[0], stride_w=stride[1])
    if pad is not None:
        pp["pad"] = pad
    return _base("Pooling", name, bottoms, pooling_param=pp)


def InnerProductLayer(name, bottoms, num_output, weight_filler=None,
                      bias_filler=None, param=None, axis=None):
    ip = dict(num_output=num_output)
    if weight_filler is not None:
        ip["weight_filler"] = weight_filler
    if bias_filler is not None:
        ip["bias_filler"] = bias_filler
    if axis is not None:
        ip["axis"] = axis
    lp = _base("InnerProduct", name, bottoms, inner_product_param=ip)
    for p in (param or []):
        lp.add("param", **p)
    return lp


def ReLULayer(name, bottoms, tops=None):
    return _base("ReLU", name, bottoms, tops=tops)


def SoftmaxWithLoss(name, bottoms, axis=None):
    kw = {}
    if axis is not None:
        kw["softmax_param"] = dict(axis=axis)
    return _base("SoftmaxWithLoss", name, bottoms, **kw)


def AccuracyLayer(name, bottoms, top_k=1, include=TEST):
    return _base("Accuracy", name, bottoms, include=include,
                 accuracy_param=dict(top_k=top_k))


def LRNLayer(name, bottoms, local_size=5, alpha=1.0, beta=0.75,
             norm_region="ACROSS_CHANNELS"):
    return _base("LRN", name, bottoms, lrn_param=dict(
        local_size=local_size, alpha=alpha, beta=beta,
        norm_region=norm_region))


def DropoutLayer(name, bottoms, tops=None, ratio=0.5):
    return _base("Dropout", name, bottoms, tops=tops,
                 dropout_param=dict(dropout_ratio=ratio))


def ConcatLayer(name, bottoms, axis=1):
    return _base("Concat", name, bottoms, concat_param=dict(axis=axis))


def BatchNormLayer(name, bottoms, tops=None, **kw):
    return _base("BatchNorm", name, bottoms, tops=tops,
                 batch_norm_param=kw or None)


def EltwiseLayer(name, bottoms, operation="SUM", coeff=None):
    ep = dict(operation=operation)
    if coeff:
        ep["coeff"] = list(coeff)
    return _base("Eltwise", name, bottoms, eltwise_param=ep)


def SoftmaxLayer(name, bottoms):
    return _base("Softmax", name, bottoms)


def AttentionLayer(name, bottoms, num_heads, head_dim=None, causal=False,
                   ring=False, flash=False):
    """sparknet_tpu extension for the long-context path (see
    parallel.ring_attention, ops.pallas_attention)."""
    ap = dict(num_heads=num_heads, causal=causal, ring=ring, flash=flash)
    if head_dim is not None:
        ap["head_dim"] = head_dim
    return _base("Attention", name, bottoms, attention_param=ap)


def EmbedLayer(name, bottoms, input_dim, num_output, weight_filler=None):
    ep = dict(input_dim=input_dim, num_output=num_output)
    if weight_filler is not None:
        ep["weight_filler"] = weight_filler
    return _base("Embed", name, bottoms, embed_param=ep)


def PositionalEmbedLayer(name, bottoms, max_positions, num_output,
                         weight_filler=None, tops=None):
    """sparknet_tpu extension: learned positional table added in place."""
    ep = dict(input_dim=max_positions, num_output=num_output)
    if weight_filler is not None:
        ep["weight_filler"] = weight_filler
    return _base("PositionalEmbed", name, bottoms, tops=tops, embed_param=ep)


def MoELayer(name, bottoms, num_experts, hidden_dim=None,
             capacity_factor=None, expert_parallel=False,
             aux_loss_weight=None, weight_filler=None, stats=False):
    """sparknet_tpu extension: Switch-style MoE FFN. aux_loss_weight adds a
    second top carrying the load-balancing loss with that loss_weight;
    stats=True adds a third (weight-0) diagnostics top with per-expert
    token fractions + the overflow fraction."""
    mp = dict(num_experts=num_experts, expert_parallel=expert_parallel)
    if hidden_dim is not None:
        mp["hidden_dim"] = hidden_dim
    if capacity_factor is not None:
        mp["capacity_factor"] = capacity_factor
    if weight_filler is not None:
        mp["weight_filler"] = weight_filler
    if stats and aux_loss_weight is None:
        aux_loss_weight = 0.0          # stats is top 3; aux must exist
    tops = [name] if aux_loss_weight is None else [name, f"{name}_aux"]
    if stats:
        tops.append(f"{name}_stats")
    lp = _base("MoE", name, bottoms, tops=tops, moe_param=mp)
    if aux_loss_weight is not None:
        lp.loss_weight.extend([0.0, float(aux_loss_weight)]
                              + ([0.0] if stats else []))
    return lp


def LayerNormLayer(name, bottoms, tops=None, eps=None, affine=None):
    """sparknet_tpu extension: last-axis layer norm (transformer blocks)."""
    ln = {}
    if eps is not None:
        ln["eps"] = eps
    if affine is not None:
        ln["affine"] = affine
    return _base("LayerNorm", name, bottoms, tops=tops,
                 layer_norm_param=ln or None)


def NetParam(name, *layers):
    net = Message("NetParameter", name=name)
    for l in layers:
        net.layer.append(l)
    return net
