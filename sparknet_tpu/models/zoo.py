"""Programmatic model builders: the LeNet -> CIFAR -> AlexNet/CaffeNet ->
GoogLeNet progression of the reference (caffe/examples/mnist,
caffe/examples/cifar10, caffe/models/bvlc_reference_caffenet,
caffe/models/bvlc_googlenet), re-expressed with the DSL so the framework is
self-contained — no prototxt files needed (though stock ones load too).
"""

from .dsl import (NetParam, RDDLayer, ConvolutionLayer, PoolingLayer,
                  InnerProductLayer, ReLULayer, SoftmaxWithLoss,
                  AccuracyLayer, LRNLayer, DropoutLayer, ConcatLayer,
                  EltwiseLayer, AttentionLayer, EmbedLayer,
                  PositionalEmbedLayer, LayerNormLayer, MoELayer)


def _conv(name, bottom, num_output, kernel, stride=1, pad=0, group=None,
          w_std=0.01, w_type="gaussian", bias_value=0.0, lr=(1, 2),
          decay=(1, 0)):
    wf = dict(type=w_type)
    if w_type == "gaussian":
        wf["std"] = w_std
    lp = ConvolutionLayer(
        name, [bottom], (kernel, kernel), num_output,
        stride=(stride, stride), pad=(pad, pad), group=group,
        weight_filler=wf,
        bias_filler=dict(type="constant", value=bias_value),
        param=[dict(lr_mult=lr[0], decay_mult=decay[0]),
               dict(lr_mult=lr[1], decay_mult=decay[1])])
    return lp


def _fc(name, bottom, num_output, w_std=0.01, w_type="gaussian",
        bias_value=0.0, lr=(1, 2), decay=(1, 0)):
    wf = dict(type=w_type)
    if w_type == "gaussian":
        wf["std"] = w_std
    return InnerProductLayer(
        name, [bottom], num_output, weight_filler=wf,
        bias_filler=dict(type="constant", value=bias_value),
        param=[dict(lr_mult=lr[0], decay_mult=decay[0]),
               dict(lr_mult=lr[1], decay_mult=decay[1])])


def lenet(batch_size=64, with_data=True):
    """LeNet on 28x28x1 (reference examples/mnist/lenet_train_test.prototxt)."""
    layers = []
    if with_data:
        layers += [RDDLayer("data", [batch_size, 1, 28, 28]),
                   RDDLayer("label", [batch_size])]
    layers += [
        _conv("conv1", "data", 20, 5, w_type="xavier"),
        PoolingLayer("pool1", ["conv1"], "MAX", (2, 2), (2, 2)),
        _conv("conv2", "pool1", 50, 5, w_type="xavier"),
        PoolingLayer("pool2", ["conv2"], "MAX", (2, 2), (2, 2)),
        _fc("ip1", "pool2", 500, w_type="xavier"),
        ReLULayer("relu1", ["ip1"], tops=["ip1"]),
        _fc("ip2", "ip1", 10, w_type="xavier"),
        AccuracyLayer("accuracy", ["ip2", "label"]),
        SoftmaxWithLoss("loss", ["ip2", "label"]),
    ]
    return NetParam("LeNet", *layers)


def cifar10_full(batch_size=100, with_data=True):
    """CIFAR10_full (reference examples/cifar10/cifar10_full_train_test.prototxt)."""
    layers = []
    if with_data:
        layers += [RDDLayer("data", [batch_size, 3, 32, 32]),
                   RDDLayer("label", [batch_size])]
    layers += [
        _conv("conv1", "data", 32, 5, pad=2, w_std=0.0001, lr=(1, 2),
              decay=(1, 1)),
        PoolingLayer("pool1", ["conv1"], "MAX", (3, 3), (2, 2)),
        ReLULayer("relu1", ["pool1"], tops=["pool1"]),
        LRNLayer("norm1", ["pool1"], local_size=3, alpha=5e-5, beta=0.75,
                 norm_region="WITHIN_CHANNEL"),
        _conv("conv2", "norm1", 32, 5, pad=2, w_std=0.01, decay=(1, 1)),
        ReLULayer("relu2", ["conv2"], tops=["conv2"]),
        PoolingLayer("pool2", ["conv2"], "AVE", (3, 3), (2, 2)),
        LRNLayer("norm2", ["pool2"], local_size=3, alpha=5e-5, beta=0.75,
                 norm_region="WITHIN_CHANNEL"),
        _conv("conv3", "norm2", 64, 5, pad=2, w_std=0.01, lr=(1, 1),
              decay=(1, 1)),
        ReLULayer("relu3", ["conv3"], tops=["conv3"]),
        PoolingLayer("pool3", ["conv3"], "AVE", (3, 3), (2, 2)),
        InnerProductLayer(
            "ip1", ["pool3"], 10,
            weight_filler=dict(type="gaussian", std=0.01),
            bias_filler=dict(type="constant"),
            param=[dict(lr_mult=1, decay_mult=250),
                   dict(lr_mult=2, decay_mult=0)]),
        AccuracyLayer("accuracy", ["ip1", "label"]),
        SoftmaxWithLoss("loss", ["ip1", "label"]),
    ]
    return NetParam("CIFAR10_full", *layers)


def caffenet(batch_size=256, num_classes=1000, with_data=True,
             crop_size=227):
    """AlexNet-class CaffeNet (reference models/bvlc_reference_caffenet/
    train_val.prototxt): the pool-then-norm AlexNet variant with grouped
    conv2/4/5 — the ImageNetApp workload (ImageNetApp.scala)."""
    layers = []
    if with_data:
        layers += [RDDLayer("data", [batch_size, 3, crop_size, crop_size]),
                   RDDLayer("label", [batch_size])]
    layers += [
        _conv("conv1", "data", 96, 11, stride=4, w_std=0.01),
        ReLULayer("relu1", ["conv1"], tops=["conv1"]),
        PoolingLayer("pool1", ["conv1"], "MAX", (3, 3), (2, 2)),
        LRNLayer("norm1", ["pool1"], local_size=5, alpha=1e-4, beta=0.75),
        _conv("conv2", "norm1", 256, 5, pad=2, group=2, w_std=0.01,
              bias_value=1.0),
        ReLULayer("relu2", ["conv2"], tops=["conv2"]),
        PoolingLayer("pool2", ["conv2"], "MAX", (3, 3), (2, 2)),
        LRNLayer("norm2", ["pool2"], local_size=5, alpha=1e-4, beta=0.75),
        _conv("conv3", "norm2", 384, 3, pad=1, w_std=0.01),
        ReLULayer("relu3", ["conv3"], tops=["conv3"]),
        _conv("conv4", "conv3", 384, 3, pad=1, group=2, w_std=0.01,
              bias_value=1.0),
        ReLULayer("relu4", ["conv4"], tops=["conv4"]),
        _conv("conv5", "conv4", 256, 3, pad=1, group=2, w_std=0.01,
              bias_value=1.0),
        ReLULayer("relu5", ["conv5"], tops=["conv5"]),
        PoolingLayer("pool5", ["conv5"], "MAX", (3, 3), (2, 2)),
        _fc("fc6", "pool5", 4096, w_std=0.005, bias_value=1.0),
        ReLULayer("relu6", ["fc6"], tops=["fc6"]),
        DropoutLayer("drop6", ["fc6"], tops=["fc6"], ratio=0.5),
        _fc("fc7", "fc6", 4096, w_std=0.005, bias_value=1.0),
        ReLULayer("relu7", ["fc7"], tops=["fc7"]),
        DropoutLayer("drop7", ["fc7"], tops=["fc7"], ratio=0.5),
        _fc("fc8", "fc7", num_classes, w_std=0.01),
        AccuracyLayer("accuracy", ["fc8", "label"]),
        SoftmaxWithLoss("loss", ["fc8", "label"]),
    ]
    return NetParam("CaffeNet", *layers)


# GoogLeNet inception tower widths (models/bvlc_googlenet/train_val.prototxt)
_INCEPTION = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


def _gconv(name, bottom, num_output, kernel, stride=1, pad=0):
    return _conv(name, bottom, num_output, kernel, stride=stride, pad=pad,
                 w_type="xavier", bias_value=0.2)


def _inception(name, bottom, widths):
    n1, r3, n3, r5, n5, pp = widths
    p = f"inception_{name}"
    layers = [
        _gconv(f"{p}/1x1", bottom, n1, 1),
        ReLULayer(f"{p}/relu_1x1", [f"{p}/1x1"], tops=[f"{p}/1x1"]),
        _gconv(f"{p}/3x3_reduce", bottom, r3, 1),
        ReLULayer(f"{p}/relu_3x3_reduce", [f"{p}/3x3_reduce"],
                  tops=[f"{p}/3x3_reduce"]),
        _gconv(f"{p}/3x3", f"{p}/3x3_reduce", n3, 3, pad=1),
        ReLULayer(f"{p}/relu_3x3", [f"{p}/3x3"], tops=[f"{p}/3x3"]),
        _gconv(f"{p}/5x5_reduce", bottom, r5, 1),
        ReLULayer(f"{p}/relu_5x5_reduce", [f"{p}/5x5_reduce"],
                  tops=[f"{p}/5x5_reduce"]),
        _gconv(f"{p}/5x5", f"{p}/5x5_reduce", n5, 5, pad=2),
        ReLULayer(f"{p}/relu_5x5", [f"{p}/5x5"], tops=[f"{p}/5x5"]),
        PoolingLayer(f"{p}/pool", [bottom], "MAX", (3, 3), (1, 1), pad=1),
        _gconv(f"{p}/pool_proj", f"{p}/pool", pp, 1),
        ReLULayer(f"{p}/relu_pool_proj", [f"{p}/pool_proj"],
                  tops=[f"{p}/pool_proj"]),
        ConcatLayer(f"{p}/output",
                    [f"{p}/1x1", f"{p}/3x3", f"{p}/5x5", f"{p}/pool_proj"]),
    ]
    return layers, f"{p}/output"


def _aux_head(idx, bottom, num_classes):
    p = f"loss{idx}"
    layers = [
        PoolingLayer(f"{p}/ave_pool", [bottom], "AVE", (5, 5), (3, 3)),
        _gconv(f"{p}/conv", f"{p}/ave_pool", 128, 1),
        ReLULayer(f"{p}/relu_conv", [f"{p}/conv"], tops=[f"{p}/conv"]),
        _fc(f"{p}/fc", f"{p}/conv", 1024, w_type="xavier", bias_value=0.2),
        ReLULayer(f"{p}/relu_fc", [f"{p}/fc"], tops=[f"{p}/fc"]),
        DropoutLayer(f"{p}/drop_fc", [f"{p}/fc"], tops=[f"{p}/fc"],
                     ratio=0.7),
        _fc(f"{p}/classifier", f"{p}/fc", num_classes, w_type="xavier"),
    ]
    loss = SoftmaxWithLoss(f"{p}/loss", [f"{p}/classifier", "label"])
    loss.clear("top")
    # the stock prototxt names BOTH aux loss tops ".../loss1"
    # (bvlc_googlenet/train_val.prototxt) — keep the quirk for parity
    loss.top.append(f"{p}/loss{1 if idx == 2 else idx}")
    loss.loss_weight.append(0.3)
    layers.append(loss)
    layers.append(AccuracyLayer(f"{p}/top-1", [f"{p}/classifier", "label"]))
    return layers


def googlenet(batch_size=32, num_classes=1000, with_data=True,
              with_aux=True):
    """GoogLeNet (reference models/bvlc_googlenet/train_val.prototxt):
    9 inception modules, 2 auxiliary train-time classifiers at 0.3 weight."""
    layers = []
    if with_data:
        layers += [RDDLayer("data", [batch_size, 3, 224, 224]),
                   RDDLayer("label", [batch_size])]
    layers += [
        _gconv("conv1/7x7_s2", "data", 64, 7, stride=2, pad=3),
        ReLULayer("conv1/relu_7x7", ["conv1/7x7_s2"], tops=["conv1/7x7_s2"]),
        PoolingLayer("pool1/3x3_s2", ["conv1/7x7_s2"], "MAX", (3, 3), (2, 2)),
        LRNLayer("pool1/norm1", ["pool1/3x3_s2"], local_size=5, alpha=1e-4,
                 beta=0.75),
        _gconv("conv2/3x3_reduce", "pool1/norm1", 64, 1),
        ReLULayer("conv2/relu_3x3_reduce", ["conv2/3x3_reduce"],
                  tops=["conv2/3x3_reduce"]),
        _gconv("conv2/3x3", "conv2/3x3_reduce", 192, 3, pad=1),
        ReLULayer("conv2/relu_3x3", ["conv2/3x3"], tops=["conv2/3x3"]),
        LRNLayer("conv2/norm2", ["conv2/3x3"], local_size=5, alpha=1e-4,
                 beta=0.75),
        PoolingLayer("pool2/3x3_s2", ["conv2/norm2"], "MAX", (3, 3), (2, 2)),
    ]
    bottom = "pool2/3x3_s2"
    for key in ("3a", "3b"):
        ls, bottom = _inception(key, bottom, _INCEPTION[key])
        layers += ls
    layers.append(PoolingLayer("pool3/3x3_s2", [bottom], "MAX", (3, 3),
                               (2, 2)))
    bottom = "pool3/3x3_s2"
    for key in ("4a", "4b", "4c", "4d", "4e"):
        ls, bottom = _inception(key, bottom, _INCEPTION[key])
        layers += ls
        if with_aux and key == "4a":
            layers += _aux_head(1, bottom, num_classes)
        if with_aux and key == "4d":
            layers += _aux_head(2, bottom, num_classes)
    layers.append(PoolingLayer("pool4/3x3_s2", [bottom], "MAX", (3, 3),
                               (2, 2)))
    bottom = "pool4/3x3_s2"
    for key in ("5a", "5b"):
        ls, bottom = _inception(key, bottom, _INCEPTION[key])
        layers += ls
    pool5 = PoolingLayer("pool5/7x7_s1", [bottom], "AVE", (7, 7), (1, 1))
    layers += [
        pool5,
        DropoutLayer("pool5/drop_7x7_s1", ["pool5/7x7_s1"],
                     tops=["pool5/7x7_s1"], ratio=0.4),
        _fc("loss3/classifier", "pool5/7x7_s1", num_classes,
            w_type="xavier"),
    ]
    loss = SoftmaxWithLoss("loss3/loss3", ["loss3/classifier", "label"])
    layers.append(loss)
    layers.append(AccuracyLayer("loss3/top-1", ["loss3/classifier", "label"]))
    return NetParam("GoogleNet", *layers)


def transformer_lm(vocab_size=512, seq_len=256, batch_size=8, d_model=256,
                   num_layers=4, num_heads=8, d_ff=None, max_positions=None,
                   flash=True, ring=False, with_data=True, moe_experts=0,
                   moe_aux_weight=0.01, moe_capacity_factor=None,
                   moe_stats=False):
    """Decoder-only causal transformer LM — the long-context model family.

    No CNN-era reference twin (SURVEY.md section 5: the reference has no
    attention); this is the workload the framework's sequence machinery
    exists for: the Attention layer dispatches to the pallas flash kernel
    per chip (``flash=True``) or ring attention across a "seq" mesh axis
    (``ring=True``), and pre-LN blocks keep bf16 activations stable.
    ``moe_experts > 0`` replaces every block's dense FFN with a
    Switch-MoE of that many experts (expert_parallel engages under an
    "expert" mesh axis), adding the load-balancing aux loss with weight
    ``moe_aux_weight``.

    Blobs: "data" (B, S) int32 token ids, "label" (B, S) int32 next-token
    ids. Loss is mean cross-entropy per token (SoftmaxWithLoss axis=2).

    Every "block{i}/" group is emitted by this one loop, so the blocks
    are structurally isomorphic by construction and chain through a
    single boundary blob — exactly what graph/compiler.py's
    scan-over-layers detector (_scan_runs) requires to collapse the
    stack into one lax.scan body (SPARKNET_SCAN / ``--scan``), and what
    the per-block remat segments checkpoint (``--remat``). Renaming
    blocks away from the shared prefix, sharing params across blocks,
    or giving one block a different shape silently forfeits both.
    """
    d_ff = d_ff or 4 * d_model
    max_positions = max_positions or seq_len
    xavier = dict(type="xavier")
    layers = []
    if with_data:
        layers += [RDDLayer("data", [batch_size, seq_len]),
                   RDDLayer("label", [batch_size, seq_len])]
    layers += [
        EmbedLayer("tok_embed", ["data"], vocab_size, d_model,
                   weight_filler=xavier),
        PositionalEmbedLayer("pos_embed", ["tok_embed"], max_positions,
                             d_model, weight_filler=xavier,
                             tops=["embed"]),
    ]
    x = "embed"
    for i in range(num_layers):
        p = f"block{i}"
        layers += [
            LayerNormLayer(f"{p}/ln1", [x]),
            AttentionLayer(f"{p}/attn", [f"{p}/ln1"], num_heads,
                           causal=True, flash=flash, ring=ring),
            EltwiseLayer(f"{p}/res1", [x, f"{p}/attn"]),
            LayerNormLayer(f"{p}/ln2", [f"{p}/res1"]),
        ]
        if moe_experts:
            layers += [
                MoELayer(f"{p}/moe", [f"{p}/ln2"], moe_experts,
                         hidden_dim=d_ff, expert_parallel=True,
                         aux_loss_weight=moe_aux_weight,
                         capacity_factor=moe_capacity_factor,
                         stats=moe_stats),
                EltwiseLayer(f"{p}/res2", [f"{p}/res1", f"{p}/moe"]),
            ]
        else:
            layers += [
                InnerProductLayer(f"{p}/ffn1", [f"{p}/ln2"], d_ff,
                                  weight_filler=xavier, axis=2),
                ReLULayer(f"{p}/relu", [f"{p}/ffn1"], tops=[f"{p}/ffn1"]),
                InnerProductLayer(f"{p}/ffn2", [f"{p}/ffn1"], d_model,
                                  weight_filler=xavier, axis=2),
                EltwiseLayer(f"{p}/res2", [f"{p}/res1", f"{p}/ffn2"]),
            ]
        x = f"{p}/res2"
    layers += [
        LayerNormLayer("ln_f", [x]),
        InnerProductLayer("lm_head", ["ln_f"], vocab_size,
                          weight_filler=xavier, axis=2),
        SoftmaxWithLoss("loss", ["lm_head", "label"], axis=2),
    ]
    return NetParam("TransformerLM", *layers)


def transformer_lm_pieces(vocab_size=512, seq_len=256, batch_size=8,
                          d_model=256, num_heads=8, d_ff=None,
                          max_positions=None, flash=True):
    """transformer_lm split for pipeline parallelism: (prefix, block,
    suffix) NetParams.

    The trunk block is expressed ONCE; PipelineLMSolver stacks L inits of
    it on a leading dim and runs them as GPipe stages over a "pipe" mesh
    axis (parallel/pipeline_solver.py). Embedding (prefix) and head+loss
    (suffix) stay outside the pipeline, replicated — the stage-
    heterogeneous ends the pipeline docstring plans for.

    Layer names match transformer_lm's per-block names (ln1/attn/ffn1/
    ffn2...) so params map 1:1 onto "block{i}/<name>" for equivalence
    tests and checkpoint conversion.
    """
    d_ff = d_ff or 4 * d_model
    max_positions = max_positions or seq_len
    xavier = dict(type="xavier")
    prefix = NetParam(
        "TransformerLM_prefix",
        RDDLayer("data", [batch_size, seq_len]),
        EmbedLayer("tok_embed", ["data"], vocab_size, d_model,
                   weight_filler=xavier),
        PositionalEmbedLayer("pos_embed", ["tok_embed"], max_positions,
                             d_model, weight_filler=xavier, tops=["embed"]),
    )
    block = NetParam(
        "TransformerLM_block",
        RDDLayer("x", [batch_size, seq_len, d_model]),
        LayerNormLayer("ln1", ["x"]),
        AttentionLayer("attn", ["ln1"], num_heads, causal=True, flash=flash),
        EltwiseLayer("res1", ["x", "attn"]),
        LayerNormLayer("ln2", ["res1"]),
        InnerProductLayer("ffn1", ["ln2"], d_ff, weight_filler=xavier,
                          axis=2),
        ReLULayer("relu", ["ffn1"], tops=["ffn1"]),
        InnerProductLayer("ffn2", ["ffn1"], d_model, weight_filler=xavier,
                          axis=2),
        EltwiseLayer("res2", ["res1", "ffn2"]),
    )
    suffix = NetParam(
        "TransformerLM_suffix",
        RDDLayer("x", [batch_size, seq_len, d_model]),
        RDDLayer("label", [batch_size, seq_len]),
        LayerNormLayer("ln_f", ["x"]),
        InnerProductLayer("lm_head", ["ln_f"], vocab_size,
                          weight_filler=xavier, axis=2),
        SoftmaxWithLoss("loss", ["lm_head", "label"], axis=2),
    )
    return prefix, block, suffix
