"""sparknet_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA rebuild of the capabilities of SparkNet (Berkeley, 2015:
Scala/Spark driver + Caffe/CUDA workers; reference at /root/reference). Caffe-style
NetParameter/prototxt model definitions are compiled to a single jitted XLA train
step; the Spark broadcast -> tau-step local SGD -> collect/average loop and Caffe's
intra-node GPU tree allreduce are both replaced by XLA collectives over a TPU
device mesh (with the tau-step weight-averaging mode kept as a configurable
strategy); data flows from host-sharded loaders straight into device memory.

Layer map (vs reference SURVEY.md section 1):
  proto/     prototxt + binaryproto codecs (replaces protobuf-java + C++ text parse)
  graph/     NetParameter -> init/apply compiler (replaces caffe::Net, net.cpp)
  ops/       layer forward functions on jnp/lax (replaces caffe/src/caffe/layers/*)
  solver/    solver semantics + jitted train step (replaces caffe::Solver hierarchy)
  parallel/  mesh, DP psum, local-SGD averaging, ring attention (replaces Spark
             broadcast/collect + parallel.cpp P2PSync)
  data/      host-side loaders, sampler, prefetch (replaces RDD->JNA callback path)
  models/    NetParam DSL + model builders (replaces Layers.scala)
  utils/     checkpoint, metrics, timing, signals
"""

__version__ = "0.1.0"

# public custom-layer API (see ops/python_layer.py): subclass Layer,
# decorate with @register_layer, and prototxts can use your type string —
# or use type: "Python" + python_param to plug a class in by module path.
from .graph.registry import Layer, register as register_layer  # noqa: E402,F401
