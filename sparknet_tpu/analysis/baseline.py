"""Committed-baseline support: legacy findings don't block CI, new ones do.

The baseline file (default ``.sparknet-lint-baseline.json``, committed
at the repo root) maps finding fingerprints — code + path + symbol +
message, never line numbers, so edits elsewhere in the file don't
invalidate entries — to a written justification. The contract:

  * a finding whose fingerprint is in the baseline is reported as
    "baselined" and does not fail the run
  * every entry must carry a non-empty justification (``--strict``
    fails on placeholder ones) — the baseline is a ledger of accepted
    debt, not a mute button
  * entries whose finding no longer exists are STALE: reported always,
    fatal under ``--strict``, and dropped by ``--write-baseline`` — the
    baseline can only shrink by itself, never silently rot

``sparknet lint --write-baseline --justification "..."`` adds the
current unbaselined findings (and expires stale entries) in one step.
"""

import json
import os

PLACEHOLDER = "TODO: justify"


class Baseline:
    def __init__(self, path=None, entries=None):
        self.path = path
        self.entries = dict(entries or {})   # fingerprint -> entry dict

    @classmethod
    def load(cls, path):
        """Load a baseline file; a missing file is an empty baseline
        (first run bootstraps), a malformed one raises ValueError —
        silently ignoring a corrupt baseline would un-suppress nothing
        and hide everything."""
        if not path or not os.path.exists(path):
            return cls(path)
        with open(path) as f:
            try:
                data = json.load(f)
            except ValueError as e:
                raise ValueError(f"{path}: malformed baseline: {e}")
        if not isinstance(data, dict) or \
                not isinstance(data.get("entries", {}), dict):
            raise ValueError(f"{path}: malformed baseline: expected an "
                             "object with an 'entries' object")
        return cls(path, data.get("entries", {}))

    def split(self, findings):
        """Partition findings into (new, baselined) and compute the
        stale entries (fingerprints with no live finding)."""
        new, baselined, live = [], [], set()
        for f in findings:
            fp = f.fingerprint()
            if fp in self.entries:
                baselined.append(f)
                live.add(fp)
            else:
                new.append(f)
        stale = {fp: e for fp, e in self.entries.items() if fp not in live}
        return new, baselined, stale

    def unjustified(self):
        """Entries with an empty or placeholder justification."""
        return {fp: e for fp, e in self.entries.items()
                if not str(e.get("justification", "")).strip()
                or e.get("justification") == PLACEHOLDER}

    def update(self, findings, justification=None):
        """Rewrite the entry set from ``findings``: new findings are
        added with ``justification`` (or the placeholder), existing
        entries keep their justification, stale ones expire. Returns
        (added, expired) counts."""
        new_entries, added = {}, 0
        for f in findings:
            fp = f.fingerprint()
            if fp in self.entries:
                new_entries[fp] = self.entries[fp]
                continue
            added += 1
            new_entries[fp] = {
                "code": f.code, "path": f.path, "symbol": f.symbol,
                "message": f.message,
                "justification": justification or PLACEHOLDER,
            }
        expired = len(self.entries) - (len(new_entries) - added)
        self.entries = new_entries
        return added, expired

    def save(self, path=None):
        path = path or self.path
        data = {
            "comment": "sparknet lint baseline — accepted findings with "
                       "justifications; see README 'Static analysis'. "
                       "Entries expire via --write-baseline when the "
                       "finding disappears.",
            "entries": {fp: self.entries[fp]
                        for fp in sorted(self.entries)},
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)
        return path
