"""SPK3xx — the distributed file-protocol rules.

The resilience layer has no control plane: hosts coordinate entirely
through files on the shared filesystem (heartbeats ``hb-*.json``,
consensus parts ``part-*.npz``, masks, restart barriers, checkpoint
manifests ``*.latest.json``). The protocol survives crashes only if
every write is atomic — unique temp name, fsync, ``os.replace`` — and
every wait on another host is bounded. These rules enforce that
discipline repo-wide, using the ProjectIndex to expand path
expressions (f-strings, constants, ``*_path`` helper returns) into
literal fragments so ``self._part_path(h, r)`` is recognized as a
rendezvous file two modules away.

Rules:
  SPK301 (error)  ``open(path, "w")`` / ``np.savez(path, ...)`` on a
                  protocol-marked path with no temp-file tag — a
                  reader (or the crash-restart scan) can observe the
                  torn half-written file. Use
                  ``checkpoint.atomic_write_bytes/atomic_write_json``.
  SPK302 (warn)   ``os.replace(src, dst)`` whose source is not created
                  in the same scope (no local assignment, no matching
                  ``open``) — the tmp+replace pair is split across
                  functions, where crash-cleanup and the unique-name
                  discipline rot independently.
  SPK303 (error)  a gate/barrier/manifest wait whose result is
                  discarded AND that passes no ``timeout=`` — a lost
                  peer parks this caller forever with nothing
                  (quorum check, eviction) to unstick it.
  SPK304 (error)  ``sys.exit``/``os._exit``/``SystemExit`` with a raw
                  integer literal — exit codes are a cross-process
                  protocol (the launcher pattern-matches them), so
                  they come from ``utils/exit_codes.py``, nowhere
                  else.
"""

import ast

from .engine import rule, make_finding, SEVERITY_ERROR, SEVERITY_WARN
from .project import dotted

# substrings that mark a path as part of the on-disk coordination
# protocol (heartbeats, consensus parts, masks, deltas, restart
# barriers, checkpoint snapshots + manifests)
_PROTOCOL_MARKERS = ("hb-", "part-", "mask-", "delta-", "consensus-",
                     "restart-", ".latest.json", "_iter_",
                     ".solverstate", ".caffemodel", ".lm.npz")

_WRITE_MODES = {"w", "wb", "w+", "wb+", "wt", "x", "xb"}

_SAVEZ_CALLS = {"np.savez", "np.savez_compressed", "numpy.savez",
                "numpy.savez_compressed"}

_GATE_CALLS = {"gate", "restart_barrier", "wait_for_manifest"}

_EXIT_CALLS = {"sys.exit", "os._exit", "exit", "SystemExit"}


def _functions_with_calls(module):
    """Yield (enclosing function or None, qualname, call node) for
    every Call in the module, tracking the scope stack."""

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                fn = None
                for s in reversed(stack):
                    if isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        fn = s
                        break
                qual = ".".join(
                    s.name for s in stack
                    if isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef))) or "<module>"
                yield fn, qual, child
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                yield from walk(child, stack + [child])
            else:
                yield from walk(child, stack)

    yield from walk(module.tree, [])


def _open_mode(call):
    """The literal mode of an ``open()`` call, default 'r'."""
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


def _protocol_marker(fragments):
    """The first protocol marker present in the expanded path, or None;
    tmp-tagged paths (any '.tmp' fragment) are exempt — they are the
    atomic protocol's own first half."""
    joined = "".join(fragments)
    if ".tmp" in joined or ".build." in joined:
        return None
    for marker in _PROTOCOL_MARKERS:
        if marker in joined:
            return marker
    return None


@rule("SPK301", "non-atomic-protocol-write", SEVERITY_ERROR)
def non_atomic_protocol_write(module, ctx):
    """Direct write to a rendezvous/checkpoint path. A peer polling the
    path (or the restart scan) can read the half-written file; a crash
    mid-write leaves a torn file that satisfies the existence check.
    Write to a unique temp name, fsync, then ``os.replace`` — i.e. use
    ``resilience.checkpoint.atomic_write_bytes``/``atomic_write_json``."""
    proj = ctx.project
    for fn, qual, call in _functions_with_calls(module):
        target = None
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            if _open_mode(call) in _WRITE_MODES and call.args:
                target = call.args[0]
        elif dotted(call.func) in _SAVEZ_CALLS and call.args:
            target = call.args[0]
        if target is None:
            continue
        frags = proj.expr_fragments(target, module, fn)
        marker = _protocol_marker(frags)
        if marker is None:
            continue
        yield make_finding(
            non_atomic_protocol_write, module,
            f"non-atomic write to protocol path (marker `{marker}`) — "
            "a concurrent reader or crash-restart scan can observe the "
            "torn file; use atomic_write_bytes/atomic_write_json from "
            "resilience.checkpoint",
            node=call, symbol=qual)


@rule("SPK302", "replace-source-not-local", SEVERITY_WARN)
def replace_source_not_local(module, ctx):
    """``os.replace(src, dst)`` where ``src`` is not created in the
    same scope (not assigned locally, never opened here). Splitting the
    tmp-write from its commit across functions is how the unique-name
    and crash-cleanup halves of the discipline drift apart."""
    for fn, qual, call in _functions_with_calls(module):
        if dotted(call.func) != "os.replace" or len(call.args) < 2:
            continue
        src = call.args[0]
        if not isinstance(src, (ast.Name, ast.Attribute, ast.Constant)):
            continue                    # inline expression: built here
        scope = fn if fn is not None else module.tree
        created = False
        src_dump = ast.dump(src)
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign) and isinstance(src, ast.Name) \
                    and any(isinstance(leaf, ast.Name) and
                            leaf.id == src.id
                            for t in n.targets
                            for leaf in ast.walk(t)):
                created = True
                break
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "open" and n.args \
                    and ast.dump(n.args[0]) == src_dump:
                created = True
                break
        if created:
            continue
        yield make_finding(
            replace_source_not_local, module,
            "os.replace source is not created in this scope — keep the "
            "tmp write and its os.replace commit in one function (or "
            "use checkpoint.atomic_write_bytes, which does both)",
            node=call, symbol=qual)


@rule("SPK303", "unbounded-gate-wait", SEVERITY_ERROR)
def unbounded_gate_wait(module, ctx):
    """A rendezvous wait (``gate``/``restart_barrier``/
    ``wait_for_manifest``) whose result is discarded and that passes no
    ``timeout=``: when a peer dies mid-round, this caller parks forever
    and the quorum/eviction machinery never runs. Pass ``timeout=`` and
    act on the result (evict the dead, or abort with
    EXIT_QUORUM_LOST)."""
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Expr) and
                isinstance(node.value, ast.Call)):
            continue
        call = node.value
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in _GATE_CALLS:
            continue
        if any(kw.arg == "timeout" for kw in call.keywords):
            continue
        yield make_finding(
            unbounded_gate_wait, module,
            f"`{name}(...)` result discarded with no timeout= — a dead "
            "peer parks this caller forever; bound the wait and handle "
            "the stragglers in the result",
            node=call, symbol="")


@rule("SPK304", "raw-exit-code", SEVERITY_ERROR)
def raw_exit_code(module, ctx):
    """Exit with a raw integer literal. Exit codes are a cross-process
    protocol — the multi-host launcher and the restart logic
    pattern-match them — so every exit goes through the canonical
    table in ``sparknet_tpu/utils/exit_codes.py``."""
    table = ctx.project.exit_table
    for fn, qual, call in _functions_with_calls(module):
        d = dotted(call.func)
        if d not in _EXIT_CALLS:
            continue
        if not (call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, int)
                and not isinstance(call.args[0].value, bool)):
            continue
        n = call.args[0].value
        known = table.get(n)
        hint = (f"use `{known}`" if known else
                "add a named constant") + \
            " from sparknet_tpu.utils.exit_codes"
        yield make_finding(
            raw_exit_code, module,
            f"raw exit-code literal `{n}` — exit codes are a "
            f"cross-process protocol; {hint}",
            node=call, symbol=qual)
