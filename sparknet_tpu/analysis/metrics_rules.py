"""SPK4xx — metrics-schema rules, plus the event-registry generator.

The metrics pipeline is stringly-typed end to end: producers call
``metrics.log("host_round", host=..., round=...)`` and the consumers
(obs/report.py's aggregations, obs/monitor.py's live panes) filter on
those names with ``e.get("event") == "host_round"``. Nothing checks
the two sides agree — a renamed event or a typo'd consumer silently
reports zeros forever (the ``host_alive``/``host-alive`` class of bug).

The ProjectIndex collects every emit site via constant propagation
(literal first argument, or a name resolving to one), giving a
*registry* of event names and their field sets. Two rules compare the
sides:

  SPK401 (error)  a consumer filters on an event/kind string nobody
                  emits (checked against the live registry ∪ the
                  committed schema — the schema covers emitters
                  outside the lint target, e.g. repo-root bench.py)
  SPK402 (error)  an emit site drifts from the committed schema: the
                  event is unregistered, or it passes fields the
                  schema doesn't list — regenerate the schema
                  (``sparknet lint --write-event-schema``) and commit

The registry is also materialized as a generated module,
``sparknet_tpu/obs/event_schema.py``, consumed by the runtime
regression test (tests/test_event_schema.py) and the docs. Both rules
resolve that file package-relative, so fixture runs with a different
root still see it.
"""

import ast
import os

from .engine import rule, make_finding, SEVERITY_ERROR


def _package_dir():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def schema_path():
    return os.path.join(_package_dir(), "obs", "event_schema.py")


_SCHEMA_CACHE = {}


def load_schema(path=None):
    """The committed registry as ``{"events": {...}, "kinds": set,
    "kinds_open": bool}``, or None when no schema file exists yet."""
    path = path or schema_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    cached = _SCHEMA_CACHE.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    data = {"events": {}, "kinds": set(), "kinds_open": False}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        try:
            val = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            continue
        if name == "EVENTS":
            data["events"] = val
        elif name == "KINDS":
            data["kinds"] = set(val)
        elif name == "KINDS_OPEN":
            data["kinds_open"] = bool(val)
    _SCHEMA_CACHE[path] = (mtime, data)
    return data


# -- consumer extraction (shared with tests/test_event_schema.py) -----------

_DOMAINS = ("event", "kind")


def _get_domain(call):
    """'event'/'kind' when ``call`` is ``<x>.get("event"|"kind", ...)``."""
    if isinstance(call, ast.Call) and \
            isinstance(call.func, ast.Attribute) and \
            call.func.attr == "get" and call.args and \
            isinstance(call.args[0], ast.Constant) and \
            call.args[0].value in _DOMAINS:
        return call.args[0].value
    return None


def _subscript_domain(node):
    if isinstance(node, ast.Subscript) and \
            isinstance(node.slice, ast.Constant) and \
            node.slice.value in _DOMAINS:
        return node.slice.value
    return None


def _literal_strs(node):
    """The string constants a comparator contributes: a literal, or a
    tuple/list/set of literals. Non-literal members poison the whole
    comparator (return None → don't judge)."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else None
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


def iter_consumer_checks(tree):
    """Yield ``(node, domain, name)`` for every comparison of an
    event/kind lookup against a string literal anywhere in ``tree``:
    direct (``e.get("event") == "train"``, ``ev["kind"] in (...)``) and
    through a local (``kind = ev.get("event", "?")`` then
    ``kind == "train"`` / ``if kind in ("a", "b")``). This is the one
    implementation of "what names do the consumers filter on" — the
    lint rule and the runtime regression test both use it."""
    # pass 1: locals assigned from a domain lookup, per function scope
    var_domain = {}                     # (scope id, var) -> domain
    # map every node to its enclosing function via a parent walk
    enclosing = {}

    def _mark(node, scope):
        for child in ast.iter_child_nodes(node):
            s = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else scope
            enclosing[id(child)] = scope
            _mark(child, s)

    _mark(tree, None)

    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            dom = _get_domain(n.value) or _subscript_domain(n.value)
            if dom is not None:
                var_domain[(id(enclosing.get(id(n))),
                            n.targets[0].id)] = dom

    def node_domain(node, scope_key):
        dom = _get_domain(node) or _subscript_domain(node)
        if dom is not None:
            return dom
        if isinstance(node, ast.Name):
            return var_domain.get((scope_key, node.id))
        return None

    # pass 2: comparisons
    for n in ast.walk(tree):
        if not isinstance(n, ast.Compare):
            continue
        scope_key = id(enclosing.get(id(n)))
        sides = [n.left] + list(n.comparators)
        for i, op in enumerate(n.ops):
            if not isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn)):
                continue
            a, b = sides[i], sides[i + 1]
            for lookup, lits in ((a, b), (b, a)):
                dom = node_domain(lookup, scope_key)
                if dom is None:
                    continue
                names = _literal_strs(lits)
                if names is None:
                    continue
                for name in names:
                    yield n, dom, name


@rule("SPK401", "unknown-event-consumer", SEVERITY_ERROR)
def unknown_event_consumer(module, ctx):
    """A consumer filters on an event (or kind) name that no emit site
    produces — the filter matches nothing, the report/pane shows zeros,
    and nobody notices. Known names = the live emit registry of this
    lint run ∪ the committed event schema (which covers emitters
    outside the lint target, like repo-root bench.py)."""
    proj = ctx.project
    schema = load_schema()
    known_events = set(proj.events)
    known_kinds = set(proj.kinds)
    kinds_open = proj.kinds_open
    events_open = any(s.event is None for s in proj.emit_sites)
    if schema is not None:
        known_events |= set(schema["events"])
        known_kinds |= schema["kinds"]
        kinds_open = kinds_open or schema["kinds_open"]
    # placeholder sentinels consumers use for "anything else"
    known_events |= {"?", ""}
    known_kinds |= {"?", ""}
    for node, dom, name in iter_consumer_checks(module.tree):
        if dom == "event":
            if events_open or name in known_events:
                continue
            universe = "emit site"
        else:
            if kinds_open or name in known_kinds:
                continue
            universe = "kind= emit"
        yield make_finding(
            unknown_event_consumer, module,
            f"consumer filters on {dom} `{name}` but no {universe} "
            "produces it — typo, or the producer was renamed; fix the "
            "name or regenerate the event schema",
            node=node, symbol="")


@rule("SPK402", "event-schema-drift", SEVERITY_ERROR)
def event_schema_drift(module, ctx):
    """An emit site disagrees with the committed event schema: the
    event name is unregistered, or the site passes fields the schema
    doesn't list for it. Regenerate and commit the schema
    (``sparknet lint --write-event-schema``) so consumers and the
    runtime regression test see the new shape."""
    schema = load_schema()
    if schema is None:
        return
    events = schema["events"]
    for site in ctx.project.emit_sites:
        if site.relpath != module.relpath or site.event is None:
            continue
        reg = events.get(site.event)
        if reg is None:
            yield make_finding(
                event_schema_drift, module,
                f"emit site for event `{site.event}` is not in the "
                "committed event schema — run `sparknet lint "
                "--write-event-schema` and commit the result",
                node=site.node, symbol="")
            continue
        if reg.get("open"):
            continue
        extra = sorted(set(site.fields) - set(reg.get("fields", ())))
        if site.open_fields:
            yield make_finding(
                event_schema_drift, module,
                f"emit site for `{site.event}` forwards **kwargs but "
                "the committed schema lists a closed field set — "
                "regenerate the event schema",
                node=site.node, symbol="")
        elif extra:
            yield make_finding(
                event_schema_drift, module,
                f"emit site for `{site.event}` passes fields "
                f"{extra} not in the committed schema — regenerate "
                "the event schema and commit it",
                node=site.node, symbol="")


# -- registry generation ----------------------------------------------------

def build_registry(repo_root):
    """Scan the package plus repo-root scripts and return the registry
    dict the schema module is rendered from."""
    from .engine import LintEngine, Module
    from .project import ProjectIndex
    pkg = _package_dir()
    targets = [pkg]
    for fn in sorted(os.listdir(repo_root)):
        if fn.endswith(".py"):
            targets.append(os.path.join(repo_root, fn))
    modules = []
    for path in LintEngine().collect_files(targets):
        if os.path.abspath(path) == os.path.abspath(schema_path()):
            continue                    # never self-feed the registry
        try:
            modules.append(Module.load(path, repo_root))
        except (SyntaxError, ValueError, UnicodeDecodeError):
            continue
    proj = ProjectIndex(modules)
    events = {}
    for name in sorted(proj.events):
        e = proj.events[name]
        events[name] = {
            "fields": sorted(e["fields"]),
            "open": bool(e["open"]),
            "sites": sorted(e["sites"]),
        }
    return {"events": events, "kinds": sorted(proj.kinds),
            "kinds_open": bool(proj.kinds_open)}


def render_schema(registry):
    """The generated module's source text, deterministic."""
    lines = [
        '"""Metrics event registry — GENERATED, do not edit by hand.',
        "",
        "Every event name the repo emits via ``metrics.log(...)`` with",
        "the union of field names seen at its emit sites (``open`` =",
        "some site forwards **kwargs, so the field set is not closed).",
        "Consumers (obs/report.py, obs/monitor.py) may only filter on",
        "names in this registry — `sparknet lint` rule SPK401 and",
        "tests/test_event_schema.py both enforce it.",
        "",
        "Regenerate with:  python -m sparknet_tpu lint"
        " --write-event-schema",
        '"""',
        "",
        "EVENTS = {",
    ]
    for name, info in registry["events"].items():
        lines.append(f"    {name!r}: {{")
        lines.append(f"        \"fields\": {info['fields']!r},")
        lines.append(f"        \"open\": {info['open']!r},")
        lines.append("    },")
    lines.append("}")
    lines.append("")
    lines.append(f"KINDS = {registry['kinds']!r}")
    lines.append("")
    lines.append(f"KINDS_OPEN = {registry['kinds_open']!r}")
    lines.append("")
    return "\n".join(lines)


def write_event_schema(repo_root, out_path=None):
    """Generate and write the schema module; returns the path."""
    out_path = out_path or schema_path()
    content = render_schema(build_registry(repo_root))
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(content)
    return out_path
