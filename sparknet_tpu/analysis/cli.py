"""`sparknet lint` — the CLI face of sparknet_tpu.analysis.

Exit codes (scripts/lint.sh relies on them):
  0  clean, modulo the baseline
  1  findings — errors always; warnings too under --strict; under
     --strict also stale or unjustified baseline entries
  2  usage / baseline-file errors

Deliberately jax-free: linting runs on checkout hosts (CI, laptops)
with no accelerator stack, like `sparknet monitor`.
"""

import json
import os
import sys

from .engine import LintEngine, ALL_CODES, all_rules, SEVERITY_ERROR
from .baseline import Baseline

DEFAULT_BASELINE = ".sparknet-lint-baseline.json"
DEFAULT_CACHE = ".sparknet-lint-cache.json"

# --select profiles: the relaxed per-tree rule sets scripts/lint.sh
# applies outside the package source. Tests monkeypatch state and poke
# internals on purpose, so only the parse + file-protocol + exit-code
# rules hold there; tools/experiments additionally get the host-sync
# JAX hazard rules.
SELECT_PROFILES = {
    "@tests": {"SPK001", "SPK301", "SPK302", "SPK304"},
    "@tools": {"SPK001", "SPK101", "SPK103", "SPK104", "SPK105",
               "SPK301", "SPK302", "SPK303", "SPK304"},
}


def default_target():
    """With no paths given, lint the installed sparknet_tpu package —
    which, in a checkout, IS the repo source tree. Returns
    (paths, root) with root chosen so finding paths render as
    'sparknet_tpu/...' (the form the committed baseline uses)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg], os.path.dirname(pkg)


def _find_baseline(paths, root):
    """Default baseline file: next to the lint root, then the CWD."""
    for d in (root, os.getcwd()):
        p = os.path.join(d, DEFAULT_BASELINE)
        if os.path.exists(p):
            return p
    return os.path.join(root, DEFAULT_BASELINE)


def list_rules(out=print):
    all_rules()
    out(f"{'code':<8}{'severity':<10}rule")
    for code in sorted(ALL_CODES):
        name, severity, help_ = ALL_CODES[code]
        out(f"{code:<8}{severity:<10}{name}")
        first = " ".join((help_ or "").split(". ")[0].split())
        if first:
            out(f"{'':<18}{first if first.endswith('.') else first + '.'}")
    return 0


def run_lint(args, out=print, err=None):
    """Drive one lint run from parsed CLI args (see cli.py's `lint`
    subparser). Returns the process exit code."""
    err = err or (lambda s: print(s, file=sys.stderr))
    if args.list_rules:
        return list_rules(out)
    if args.paths:
        paths = [os.path.abspath(p) for p in args.paths]
        for p in paths:
            if not os.path.exists(p):
                err(f"sparknet lint: error: no such path: {p}")
                return 2
        root = os.path.abspath(args.root) if args.root else os.getcwd()
    else:
        paths, root = default_target()
        if args.root:
            root = os.path.abspath(args.root)
    if getattr(args, "write_event_schema", False):
        from .metrics_rules import write_event_schema
        path = write_event_schema(root)
        out(f"event schema written: {path}")
        return 0
    select = None
    if args.select:
        select = set()
        for c in args.select.split(","):
            c = c.strip()
            if not c:
                continue
            if c.lower() in SELECT_PROFILES:
                select |= SELECT_PROFILES[c.lower()]
            else:
                select.add(c.upper())
        all_rules()
        unknown = select - set(ALL_CODES) - {"SPK001"}
        if unknown:
            err(f"sparknet lint: error: unknown rule code(s) or "
                f"profile(s): {', '.join(sorted(unknown))}")
            return 2

    cache_path = None
    if getattr(args, "cache", False):
        cache_path = os.path.join(root, DEFAULT_CACHE)

    engine = LintEngine(select=select,
                        exclude=getattr(args, "exclude", None),
                        jobs=getattr(args, "jobs", 1) or 1,
                        cache_path=cache_path)
    findings = engine.run(paths, root=root)

    baseline_path = args.baseline or _find_baseline(paths, root)
    try:
        baseline = Baseline.load(baseline_path)
    except ValueError as e:
        err(f"sparknet lint: error: {e}")
        return 2
    new, baselined, stale = baseline.split(findings)

    if args.write_baseline:
        added, expired = baseline.update(findings,
                                         justification=args.justification)
        baseline.save(baseline_path)
        out(f"baseline written: {baseline_path} "
            f"({len(baseline.entries)} entries, +{added} added, "
            f"-{expired} expired)")
        if added and not args.justification:
            out("note: new entries carry a placeholder justification; "
                "edit the baseline file — --strict will refuse it")
        return 0

    if args.json:
        out(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "stale_baseline": sorted(stale),
        }, indent=2))
    else:
        for f in new:
            out(f.render())
        if args.verbose:
            for f in baselined:
                just = baseline.entries[f.fingerprint()].get(
                    "justification", "")
                out(f"{f.render()}  [baselined: {just}]")
        for fp in sorted(stale):
            e = stale[fp]
            out(f"stale baseline entry {fp}: {e.get('code')} "
                f"{e.get('path')} ({e.get('symbol')}) — finding no "
                "longer exists; run --write-baseline to expire it")

    errors = sum(1 for f in new if f.severity == SEVERITY_ERROR)
    warns = len(new) - errors
    unjustified = baseline.unjustified() if args.strict else {}
    if not args.json:
        bits = [f"{len(new)} finding{'s' if len(new) != 1 else ''}",
                f"{errors} error{'s' if errors != 1 else ''}",
                f"{warns} warning{'s' if warns != 1 else ''}"]
        if baselined:
            bits.append(f"{len(baselined)} baselined")
        if stale:
            bits.append(f"{len(stale)} stale baseline "
                        f"entr{'ies' if len(stale) != 1 else 'y'}")
        out("sparknet lint: " + ", ".join(bits))
        if unjustified:
            for fp in sorted(unjustified):
                out(f"unjustified baseline entry {fp}: "
                    f"{unjustified[fp].get('code')} "
                    f"{unjustified[fp].get('path')} — every accepted "
                    "finding needs a written justification")

    if args.strict:
        if new or stale or unjustified:
            return 1
        return 0
    return 1 if errors else 0
