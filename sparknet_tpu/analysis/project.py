"""The whole-repo project index — cross-module facts rules query.

PR 5's rules see one file at a time; the invariants the runtime now
carries (lock order across classes, the rendezvous write discipline,
the metrics event vocabulary) span modules. This index is built once
per lint run from every parsed module and hands rules four families of
facts:

  * **module graph**: which module imports which, with the imported
    names resolved back to in-repo files (relative and absolute forms)
  * **class/method resolution + call edges**: ``self.m()``,
    ``self.field.m()`` (via ``self.field = ClassName(...)``),
    ``imported_fn()``, and ``local = ClassName(...); local.m()`` all
    resolve to the defining function when the definition is in-repo
  * **string/int constant propagation**: module-level constants plus
    per-function single-assignment locals feed
    :meth:`ProjectIndex.expr_fragments`, which flattens a path
    expression (f-strings, ``+``/``%``, ``os.path.join``, calls into
    ``*_path`` helpers) into its best-effort literal fragments — how
    SPK301 knows ``self._part_path(h, r)`` names a ``part-*.npz``
    rendezvous file two modules away
  * **domain registries**: the metrics event/kind vocabulary from every
    ``.log("...")`` emit site (SPK401/402), the blocking-call and
    lock-acquisition summaries behind the deadlock family
    (SPK205-207), and the canonical ``EXIT_*`` table (SPK304)

Everything here is AST-only and jax-free, like the rest of the
package. Resolution is deliberately best-effort: when a name cannot be
resolved the index answers None/empty and rules stay silent —
the linter's contract is no false alarms over full recall.
"""

import ast
import hashlib
import os

_MAX_DEPTH = 8          # expansion recursion guard (self-recursive helpers)

# receivers whose ``.log("event", **fields)`` calls are metrics emit
# sites (utils.metrics.MetricsLogger and the names it travels under);
# ``self.log`` / ``coord.log`` are plain text loggers, not emit sites
_METRIC_RECEIVERS = {"metrics", "_metrics", "sink", "_sink", "mlog"}

# call shapes that block: (dotted-name prefixes, attribute names)
_BLOCKING_DOTTED = {
    "time.sleep", "os.fsync", "os.replace", "os.rename", "os.remove",
    "os.makedirs", "np.load", "np.savez", "np.savez_compressed",
    "numpy.load", "numpy.savez", "glob.glob", "shutil.copy",
    "shutil.move", "shutil.rmtree", "subprocess.run", "subprocess.call",
    "json.dump", "json.load",
}
_BLOCKING_NAME_CALLS = {"open"}
# sync-primitive ctors whose .join()/.get()/.wait() calls block
_JOINABLE_CTORS = {"Thread", "Process", "Pool"}
_GETTABLE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_WAITABLE_CTORS = {"Event", "Condition", "Barrier", "Thread", "Process"}
_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def dotted(node):
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ctor_basename(value):
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
    return None


def _own_nodes(fn):
    """Walk ``fn``'s body without entering nested function/class defs."""
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class FuncInfo:
    """One function or method definition the index can resolve calls
    to."""

    __slots__ = ("relpath", "qualname", "node", "cls")

    def __init__(self, relpath, qualname, node, cls=None):
        self.relpath = relpath
        self.qualname = qualname        # "f" or "Class.m"
        self.node = node
        self.cls = cls                  # owning ClassFacts or None

    @property
    def key(self):
        return (self.relpath, self.qualname)


class ClassFacts:
    """Per-class facts for resolution and the deadlock family."""

    __slots__ = ("relpath", "name", "node", "methods", "locks",
                 "attr_types", "callback_fields", "sync_ctors")

    def __init__(self, relpath, node):
        self.relpath = relpath
        self.name = node.name
        self.node = node
        self.methods = {}           # name -> FuncInfo
        self.locks = set()          # self.<attr> Lock/RLock/Condition
        self.attr_types = {}        # self.<attr> -> ClassName str
        self.callback_fields = set()  # stored callables invoked via self.f()
        self.sync_ctors = {}        # self.<attr> -> ctor basename

    def _collect(self):
        called_fields, stored_callables = set(), set()
        for item in self.node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for n in ast.walk(item):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            ctor = _ctor_basename(n.value)
                            if ctor in _LOCK_CTORS:
                                self.locks.add(t.attr)
                            if ctor:
                                self.sync_ctors.setdefault(t.attr, ctor)
                                self.attr_types.setdefault(t.attr, ctor)
                            # ``self.on_x = on_x or default`` — a stored
                            # callable, not a method: the shape SPK207
                            # cares about (methods inherited from a base
                            # class are NOT this shape, so they never
                            # false-positive here)
                            if isinstance(n.value,
                                          (ast.Name, ast.Attribute,
                                           ast.Lambda, ast.BoolOp,
                                           ast.IfExp)):
                                stored_callables.add(t.attr)
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == "self":
                    called_fields.add(n.func.attr)
        self.callback_fields = ((called_fields & stored_callables)
                                - set(self.methods))


class EmitSite:
    """One ``metrics.log("event", **fields)`` call."""

    __slots__ = ("relpath", "line", "event", "fields", "open_fields",
                 "node", "kind")

    def __init__(self, relpath, line, event, fields, open_fields, node,
                 kind=None):
        self.relpath = relpath
        self.line = line
        self.event = event              # str, or None when unresolvable
        self.fields = tuple(fields)
        self.open_fields = open_fields  # True when **kwargs forwarded
        self.node = node
        self.kind = kind                # literal kind= value if any


class ProjectIndex:
    """Cross-module facts over one set of parsed modules."""

    def __init__(self, modules):
        self.modules = {m.relpath: m for m in modules}
        self.functions = {}         # (relpath, qualname) -> FuncInfo
        self.classes_by_name = {}   # name -> [ClassFacts]
        self.classes = {}           # (relpath, name) -> ClassFacts
        self.imports = {}           # relpath -> {local name: (relpath, sym)}
        self.constants = {}         # (relpath, name) -> str|int
        self._global_consts = {}    # name -> value (first wins)
        self._ambiguous = set()
        self.exit_table = {}        # int -> EXIT_* name
        self.emit_sites = []        # [EmitSite]
        self.events = {}            # event -> {"fields", "open", "sites"}
        self.kinds = set()          # every literal kind value seen
        self.kinds_open = False     # a non-literal kind= was seen
        self._local_cache = {}      # id(fn-node) -> {name: value expr}
        self._blocking_memo = {}
        self._acquire_memo = {}
        for m in modules:
            self._index_module(m)
        for m in modules:
            self._index_imports(m)
        for m in modules:
            self._index_emits(m)

    # -- construction ------------------------------------------------------

    def _index_module(self, module):
        rel = module.relpath
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(rel, node.name, node)
                self.functions[fi.key] = fi
            elif isinstance(node, ast.ClassDef):
                cf = ClassFacts(rel, node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = FuncInfo(rel, f"{node.name}.{item.name}",
                                      item, cls=cf)
                        cf.methods[item.name] = fi
                        self.functions[fi.key] = fi
                cf._collect()
                self.classes[(rel, node.name)] = cf
                self.classes_by_name.setdefault(node.name, []).append(cf)
            elif isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, (str, int)) and \
                    not isinstance(node.value.value, bool):
                name, val = node.targets[0].id, node.value.value
                self.constants[(rel, name)] = val
                if name in self._global_consts and \
                        self._global_consts[name] != val:
                    self._ambiguous.add(name)
                else:
                    self._global_consts.setdefault(name, val)
                if name.startswith("EXIT_") and isinstance(val, int):
                    self.exit_table.setdefault(val, name)

    def _module_rel_for(self, importer_rel, level, modname):
        """Resolve an import to an in-repo relpath, or None."""
        if level:                                   # from . / .. import
            base = os.path.dirname(importer_rel)
            for _ in range(level - 1):
                base = os.path.dirname(base)
            parts = ([base] if base else []) + \
                (modname.split(".") if modname else [])
        else:
            parts = modname.split(".") if modname else []
        cand = "/".join(p for p in parts if p)
        for suffix in (".py", "/__init__.py"):
            if cand + suffix in self.modules:
                return cand + suffix
        return None

    def _index_imports(self, module):
        table = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                target = self._module_rel_for(module.relpath,
                                              node.level,
                                              node.module or "")
                for a in node.names:
                    local = a.asname or a.name
                    if target is None:
                        continue
                    # `from pkg import mod` may name a submodule
                    sub = self._module_rel_for(
                        module.relpath, node.level,
                        f"{node.module or ''}.{a.name}".strip("."))
                    if sub is not None:
                        table[local] = (sub, None)
                    else:
                        table[local] = (target, a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    target = self._module_rel_for(module.relpath, 0,
                                                  a.name)
                    if target is not None:
                        table[local] = (target, None)
        self.imports[module.relpath] = table

    def imported_modules(self, relpath):
        """In-repo module relpaths ``relpath`` imports (the module
        graph edge set)."""
        return sorted({rel for rel, _ in
                       self.imports.get(relpath, {}).values()})

    # -- emit sites / event registry ---------------------------------------

    @staticmethod
    def _is_metric_receiver(func):
        """True for ``<...>.metrics.log`` / ``metrics.log`` etc."""
        if not (isinstance(func, ast.Attribute) and func.attr == "log"):
            return False
        recv = func.value
        if isinstance(recv, ast.Name):
            return recv.id in _METRIC_RECEIVERS
        if isinstance(recv, ast.Attribute):
            return recv.attr in _METRIC_RECEIVERS
        return False

    def _index_emits(self, module):
        rel = module.relpath
        for fn in self._all_function_nodes(module):
            for n in _own_nodes(fn):
                if not (isinstance(n, ast.Call) and
                        self._is_metric_receiver(n.func) and n.args):
                    continue
                event = self._const_str(n.args[0], module, fn)
                fields, open_fields, kind = [], False, None
                for kw in n.keywords:
                    if kw.arg is None:
                        open_fields = True
                        continue
                    fields.append(kw.arg)
                    if kw.arg == "kind":
                        kv = self._const_str(kw.value, module, fn)
                        if kv is not None:
                            kind = kv
                            self.kinds.add(kv)
                        else:
                            self.kinds_open = True
                site = EmitSite(rel, n.lineno, event, fields,
                                open_fields, n, kind=kind)
                self.emit_sites.append(site)
                if event is not None:
                    e = self.events.setdefault(
                        event, {"fields": set(), "open": False,
                                "sites": []})
                    e["fields"].update(fields)
                    e["open"] = e["open"] or open_fields
                    e["sites"].append((rel, n.lineno))
        # kind vocabulary: kind= on emit sites is collected above (a
        # kind= on a non-emit call, e.g. divergence.observe, never
        # reaches the metrics stream); event rows built as dict
        # literals can also carry "kind"
        for n in ast.walk(module.tree):
            if isinstance(n, ast.Dict):
                for k, v in zip(n.keys, n.values):
                    if isinstance(k, ast.Constant) and k.value == "kind":
                        if isinstance(v, ast.Constant) and \
                                isinstance(v.value, str):
                            self.kinds.add(v.value)
                        else:
                            self.kinds_open = True
            elif isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Subscript):
                sub = n.targets[0]
                if isinstance(sub.slice, ast.Constant) and \
                        sub.slice.value == "kind" and \
                        isinstance(n.value, ast.Constant) and \
                        isinstance(n.value.value, str):
                    self.kinds.add(n.value.value)

    @staticmethod
    def _all_function_nodes(module):
        for n in ast.walk(module.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield n

    # -- constant / expression resolution ----------------------------------

    def resolve_constant(self, name, relpath=None):
        """Module-level constant value for ``name``: the defining
        module first, imported names next, then the global first-wins
        table (None when the name is ambiguous across modules)."""
        if relpath is not None:
            if (relpath, name) in self.constants:
                return self.constants[(relpath, name)]
            imp = self.imports.get(relpath, {}).get(name)
            if imp is not None and imp[1] is not None and \
                    (imp[0], imp[1]) in self.constants:
                return self.constants[(imp[0], imp[1])]
        if name in self._ambiguous:
            return None
        return self._global_consts.get(name)

    def _locals_of(self, fn):
        """{name: value-expr} for names assigned exactly once in ``fn``
        (the per-function half of constant propagation)."""
        cache = self._local_cache.get(id(fn))
        if cache is not None:
            return cache
        assigns, multi = {}, set()
        for n in _own_nodes(fn):
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            elif isinstance(n, ast.For):
                targets = [n.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        if leaf.id in assigns or leaf.id in multi or \
                                not isinstance(n, ast.Assign):
                            multi.add(leaf.id)
                            assigns.pop(leaf.id, None)
                        else:
                            assigns[leaf.id] = n.value
        self._local_cache[id(fn)] = assigns
        return assigns

    def _const_str(self, node, module, fn):
        """The string value of ``node`` if statically known."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if fn is not None:
                local = self._locals_of(fn).get(node.id)
                if local is not None:
                    return self._const_str(local, module, None)
            v = self.resolve_constant(node.id, module.relpath)
            return v if isinstance(v, str) else None
        return None

    def expr_fragments(self, node, module, fn, _depth=0):
        """Best-effort literal fragments of a (path) expression:
        constants, resolved names, f-string/%/+ pieces, ``os.path.join``
        arguments, and the return expressions of resolved in-repo call
        targets (``self._part_path(...)`` → ``["part-", ".npz", ...]``).
        Unresolvable sub-expressions contribute nothing."""
        if _depth > _MAX_DEPTH or node is None:
            return []
        out = []
        if isinstance(node, ast.Constant):
            if isinstance(node.value, str):
                out.append(node.value)
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.Constant):
                    if isinstance(part.value, str):
                        out.append(part.value)
                elif isinstance(part, ast.FormattedValue):
                    out.extend(self.expr_fragments(
                        part.value, module, fn, _depth + 1))
        elif isinstance(node, ast.BinOp) and \
                isinstance(node.op, (ast.Add, ast.Mod)):
            out.extend(self.expr_fragments(node.left, module, fn,
                                           _depth + 1))
            out.extend(self.expr_fragments(node.right, module, fn,
                                           _depth + 1))
        elif isinstance(node, ast.Name):
            if fn is not None:
                local = self._locals_of(fn).get(node.id)
                if local is not None:
                    return self.expr_fragments(local, module, fn,
                                               _depth + 1)
            v = self.resolve_constant(node.id, module.relpath)
            if isinstance(v, str):
                out.append(v)
        elif isinstance(node, ast.Call):
            d = dotted(node.func)
            if d in ("os.path.join", "posixpath.join", "str"):
                for a in node.args:
                    out.extend(self.expr_fragments(a, module, fn,
                                                   _depth + 1))
            else:
                target = self.resolve_call(node, module, fn)
                if target is not None:
                    tmod = self.modules.get(target.relpath)
                    for r in _own_nodes(target.node):
                        if isinstance(r, ast.Return) and \
                                r.value is not None:
                            out.extend(self.expr_fragments(
                                r.value, tmod, target.node, _depth + 1))
        elif isinstance(node, ast.Attribute):
            pass                        # self.dir etc: unknown, silent
        return out

    # -- call resolution ---------------------------------------------------

    def _enclosing_class(self, module, fn):
        """ClassFacts whose method ``fn`` is (by identity), or None."""
        for (rel, _name), cf in self.classes.items():
            if rel != module.relpath:
                continue
            for mi in cf.methods.values():
                if mi.node is fn:
                    return cf
        return None

    def resolve_call(self, call, module, fn):
        """FuncInfo for ``call``'s target, or None. Handles:
        plain names (same module, then imports), ``self.m()``,
        ``self.field.m()`` via attr types, ``local = Cls(...);
        local.m()``, and ``imported_module.f()``."""
        func = call.func
        rel = module.relpath
        if isinstance(func, ast.Name):
            fi = self.functions.get((rel, func.id))
            if fi is not None:
                return fi
            imp = self.imports.get(rel, {}).get(func.id)
            if imp is not None:
                target_rel, sym = imp
                if sym is None:         # imported a module, not callable
                    return None
                fi = self.functions.get((target_rel, sym))
                if fi is not None:
                    return fi
                # `from m import ClassName` then ClassName(...) — the
                # constructor; resolution target is __init__
                cf = self.classes.get((target_rel, sym))
                if cf is not None:
                    return cf.methods.get("__init__")
            cf = self.classes.get((rel, func.id))
            if cf is not None:
                return cf.methods.get("__init__")
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv, mname = func.value, func.attr
        # self.m()
        if isinstance(recv, ast.Name) and recv.id == "self":
            cf = self._enclosing_class(module, fn) if fn is not None \
                else None
            if cf is not None and mname in cf.methods:
                return cf.methods[mname]
            return None
        # module.f() through an imported module name
        if isinstance(recv, ast.Name):
            imp = self.imports.get(rel, {}).get(recv.id)
            if imp is not None and imp[1] is None:
                return self.functions.get((imp[0], mname))
            # local = ClassName(...); local.m()
            if fn is not None:
                local = self._locals_of(fn).get(recv.id)
                ctor = _ctor_basename(local) if local is not None else None
                cf = self._class_by_ctor(ctor, rel)
                if cf is not None:
                    return cf.methods.get(mname)
            return None
        # self.field.m() via the field's recorded ctor type
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and fn is not None:
            cf = self._enclosing_class(module, fn)
            if cf is not None:
                tname = cf.attr_types.get(recv.attr)
                tcf = self._class_by_ctor(tname, rel)
                if tcf is not None:
                    return tcf.methods.get(mname)
        return None

    def _class_by_ctor(self, name, from_rel):
        """ClassFacts for a constructor basename, same module first,
        then unique across the project."""
        if not name:
            return None
        cf = self.classes.get((from_rel, name))
        if cf is not None:
            return cf
        imp = self.imports.get(from_rel, {}).get(name)
        if imp is not None and imp[1] is not None:
            return self.classes.get((imp[0], imp[1]))
        cands = self.classes_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def callees(self, func_key):
        """Resolved in-repo callees of a function (the call-edge set)."""
        fi = self.functions.get(func_key)
        if fi is None:
            return []
        module = self.modules.get(fi.relpath)
        out, seen = [], set()
        for n in _own_nodes(fi.node):
            if isinstance(n, ast.Call):
                t = self.resolve_call(n, module, fi.node)
                if t is not None and t.key not in seen:
                    seen.add(t.key)
                    out.append(t)
        return out

    # -- blocking-call summaries (SPK206) ----------------------------------

    def _sync_ctor_of_receiver(self, recv, module, fn):
        """Ctor basename of a ``.join()/.get()/.wait()`` receiver when
        statically known (self.field / single-assignment local)."""
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and fn is not None:
            cf = self._enclosing_class(module, fn)
            if cf is not None:
                return cf.sync_ctors.get(recv.attr)
        if isinstance(recv, ast.Name) and fn is not None:
            local = self._locals_of(fn).get(recv.id)
            if local is not None:
                return _ctor_basename(local)
        return None

    def classify_blocking(self, n, module, fn):
        """Description when call node ``n`` blocks (sleep, file I/O,
        thread join, queue get, event wait), else None. `.get()` is
        queue-shaped only with zero positional args (dict.get has a
        key), `.join()` thread-shaped only when the receiver resolves
        to a Thread/Process or a timeout= is passed (str.join has
        neither)."""
        if not isinstance(n, ast.Call):
            return None
        d = dotted(n.func)
        if d in _BLOCKING_DOTTED:
            return f"`{d}(...)`"
        if isinstance(n.func, ast.Name) and \
                n.func.id in _BLOCKING_NAME_CALLS:
            return f"`{n.func.id}(...)` (file I/O)"
        if not isinstance(n.func, ast.Attribute):
            return None
        attr, recv = n.func.attr, n.func.value
        ctor = self._sync_ctor_of_receiver(recv, module, fn)
        if attr == "join" and (ctor in _JOINABLE_CTORS or
                               (ctor is None and any(
                                   kw.arg == "timeout"
                                   for kw in n.keywords))):
            return "`.join(...)` on a thread"
        if attr == "get" and ctor in _GETTABLE_CTORS:
            return "`.get(...)` on a queue"
        if attr == "get" and ctor is None and not n.args and \
                all(kw.arg in ("timeout", "block") for kw in n.keywords):
            return "`.get(...)` on a queue"
        if attr == "wait" and ctor in _WAITABLE_CTORS:
            return f"`.wait(...)` on a {ctor}"
        return None

    def direct_blocking_calls(self, module, fn):
        """[(call node, description)] for calls in ``fn`` that block."""
        out = []
        for n in _own_nodes(fn):
            desc = self.classify_blocking(n, module, fn)
            if desc is not None:
                out.append((n, desc))
        return out

    def transitively_blocking(self, func_key, _seen=None):
        """Description of the first blocking op reachable from
        ``func_key`` through resolved call edges, or None."""
        if func_key in self._blocking_memo:
            return self._blocking_memo[func_key]
        _seen = _seen or set()
        if func_key in _seen:
            return None
        _seen.add(func_key)
        fi = self.functions.get(func_key)
        if fi is None:
            return None
        module = self.modules.get(fi.relpath)
        direct = self.direct_blocking_calls(module, fi.node)
        if direct:
            res = f"{direct[0][1]} at {fi.relpath}:{direct[0][0].lineno}"
            self._blocking_memo[func_key] = res
            return res
        for callee in self.callees(func_key):
            sub = self.transitively_blocking(callee.key, _seen)
            if sub is not None:
                res = f"`{callee.qualname}` → {sub}"
                self._blocking_memo[func_key] = res
                return res
        self._blocking_memo[func_key] = None
        return None

    # -- lock-acquisition summaries (SPK205) -------------------------------

    def direct_acquires(self, func_key):
        """[(class name, lock attr, line)] for every ``with
        self.<lock>:`` in the method."""
        fi = self.functions.get(func_key)
        if fi is None or fi.cls is None:
            return []
        out = []
        for n in _own_nodes(fi.node):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute) and \
                            isinstance(e.value, ast.Name) and \
                            e.value.id == "self" and \
                            e.attr in fi.cls.locks:
                        out.append((fi.cls.name, e.attr, n.lineno))
        return out

    def transitive_acquires(self, func_key, _seen=None):
        """{(class name, lock attr)} acquired by the function or any
        resolved callee."""
        if func_key in self._acquire_memo:
            return self._acquire_memo[func_key]
        _seen = _seen or set()
        if func_key in _seen:
            return set()
        _seen.add(func_key)
        out = {(c, l) for c, l, _ in self.direct_acquires(func_key)}
        for callee in self.callees(func_key):
            out |= self.transitive_acquires(callee.key, _seen)
        self._acquire_memo[func_key] = out
        return out

    # -- cache invalidation ------------------------------------------------

    def fingerprint(self):
        """Hash of every cross-module summary a cached per-file result
        can depend on. Editing one file only invalidates OTHER files'
        cache entries when a summary actually changed."""
        h = hashlib.sha256()
        for key in sorted(self.constants):
            h.update(repr((key, self.constants[key])).encode())
        for name in sorted(self.events):
            e = self.events[name]
            h.update(repr((name, sorted(e["fields"]),
                           e["open"])).encode())
        h.update(repr(sorted(self.kinds)).encode())
        h.update(repr(sorted(self.exit_table.items())).encode())
        for (rel, name), cf in sorted(self.classes.items()):
            h.update(repr((rel, name, sorted(cf.locks),
                           sorted(cf.methods),
                           sorted(cf.callback_fields))).encode())
        for rel in sorted(self.imports):
            h.update(repr((rel, sorted(self.imports[rel].items()))
                          ).encode())
        return h.hexdigest()[:16]
