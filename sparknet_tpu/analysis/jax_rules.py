"""SPK1xx — JAX compiled-code hazard rules.

The common machinery is a per-module *scope index* (every function/
lambda with its lexical parent) plus a *traced-set* computation: find
the functions handed to ``jax.jit`` / ``jax.pmap`` / ``shard_map``
(directly, through ``grad``/``value_and_grad``/``vmap`` wrappers,
through a builder method that returns a local def — the
``jax.jit(self._train_step_fn(), ...)`` idiom — or as a decorator),
then close over local calls: everything a traced function defines or
calls locally runs under the tracer too. Rules then look only inside
that traced set, which is what keeps them quiet on host-side driver
code where ``float(loss)`` is exactly right.

Rules:
  SPK101  host sync inside jit-traced code (.item()/float()/np.asarray/
          jax.device_get reachable from a jit/pmap/shard_map root) —
          each one is a device round trip serialized into the hot path
  SPK102  recompile/trace hazards: Python if/for/while on traced
          function parameters, closure capture of mutable module
          globals, unhashable literals passed to static jit args
  SPK103  PRNG key reuse: the same key name consumed by two
          ``jax.random.*`` sampler calls with no intervening
          split/fold_in rebind, or consumed inside a loop while bound
          outside it
  SPK104  collective axis-name mismatch: pmean/psum/all_gather/... axis
          names checked against the enclosing pmap/shard_map axis
          declarations (resolvable literals only — never guesses), incl.
          calls through axis-forwarding helpers like masked_consensus
  SPK105  missing buffer donation: a jitted update-style function
          (takes AND returns params/state/history) with no
          donate_argnums — every step pays a params-sized HBM copy
"""

import ast

from .engine import (rule, make_finding, qualname_of, SEVERITY_ERROR,
                     SEVERITY_WARN)


# -- scope index ------------------------------------------------------------

class Scope:
    """One function-ish lexical scope (module root included)."""

    def __init__(self, node, name, parent):
        self.node = node                # FunctionDef/Lambda/Module/Class
        self.name = name
        self.parent = parent
        self.children = {}              # name -> Scope (functions only)
        self.bound = set()              # names assigned/params here
        self.qualname = name if parent is None else (
            f"{parent.qualname}.{name}" if parent.qualname != "<module>"
            else name)

    def resolve(self, name):
        """Lexical lookup of a *function* scope named ``name``."""
        s = self
        while s is not None:
            if name in s.children:
                return s.children[name]
            if name in s.bound:          # shadowed by a non-function
                return None
            s = s.parent
        return None

    def binds(self, name):
        s = self
        while s is not None:
            if name in s.bound or name in s.children:
                return True
            s = s.parent
        return False

    def params(self):
        if not isinstance(self.node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
            return []
        a = self.node.args
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


def _is_funcdef(node):
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda))


def build_scopes(module):
    """Index every function/lambda scope with lexical parents, bound
    names, and a node->scope map."""
    root = Scope(module.tree, "<module>", None)
    by_node = {module.tree: root}

    def handle(node, scope):
        if _is_funcdef(node):
            define_func(node, scope, getattr(node, "name", "<lambda>"))
            return
        if isinstance(node, ast.ClassDef):
            scope.bound.add(node.name)
            sub = Scope(node, node.name, scope)
            by_node[node] = sub
            for b in node.body:
                handle(b, sub)
            for extra in node.decorator_list + node.bases:
                handle(extra, scope)
            return
        _note_bindings(node, scope)
        # a lambda assigned to a name acts like a local def
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Lambda) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            define_func(node.value, scope, node.targets[0].id)
            return
        for child in ast.iter_child_nodes(node):
            handle(child, scope)

    def define_func(node, scope, name):
        sub = Scope(node, name, scope)
        for p in sub.params():
            sub.bound.add(p)
        scope.children[name] = sub
        by_node[node] = sub
        body = node.body if isinstance(node.body, list) else [node.body]
        for b in body:
            handle(b, sub)
        # decorators/defaults evaluate in the ENCLOSING scope
        for extra in (getattr(node, "decorator_list", []) +
                      node.args.defaults +
                      [d for d in node.args.kw_defaults if d]):
            handle(extra, scope)

    def _note_bindings(node, scope):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        scope.bound.add(n.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    scope.bound.add(n.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                scope.bound.add((alias.asname or
                                 alias.name.split(".")[0]))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name):
                            scope.bound.add(n.id)

    for stmt in module.tree.body:
        handle(stmt, root)
    return root, by_node


# -- name/call classification ----------------------------------------------

def dotted(node):
    """'jax.lax.pmean' for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def numpy_aliases(module):
    """Names the module binds to the numpy module ('np', 'numpy', ...)."""
    out = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out or {"np", "numpy"}


def random_aliases(module):
    """Names bound to the jax.random module ('jax.random', 'jr', ...),
    as dotted prefixes."""
    out = {"jax.random"}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        out.add(a.asname or "random")
    return out


_JIT_NAMES = {"jax.jit", "jit", "jax.pmap", "pmap"}
_SHARD_MAP_NAMES = {"shard_map", "jax.experimental.shard_map.shard_map"}
_WRAPPERS = {"jax.grad", "grad", "jax.value_and_grad", "value_and_grad",
             "jax.vmap", "vmap", "jax.checkpoint", "checkpoint",
             "jax.remat", "remat", "functools.partial", "partial"}


def _callable_kind(call_or_name):
    """Classify a dotted callee name: 'jit' | 'shard_map' | 'wrapper'
    | None."""
    d = call_or_name
    if d is None:
        return None
    if d in _JIT_NAMES or d.endswith(".jit") or d.endswith(".pmap"):
        return "jit"
    if d in _SHARD_MAP_NAMES or d.endswith(".shard_map"):
        return "shard_map"
    if d in _WRAPPERS:
        return "wrapper"
    return None


def _unwrap_target(arg, scope, depth=0):
    """Resolve the function ultimately wrapped by a jit/pmap/shard_map
    argument expression: a Name (local def / lambda), a Lambda literal,
    a wrapper call (grad/vmap/partial/shard_map of something), or a
    builder call whose return statement returns a local def."""
    if depth > 8:                        # self-referential assignments
        return None, None
    if isinstance(arg, ast.Lambda):
        return arg, scope
    if isinstance(arg, ast.Name):
        target = scope.resolve(arg.id)
        if target is not None:
            return target.node, target.parent
        # `fn = self._builder()` / `sharded = shard_map(step, ...)`:
        # chase the single local assignment and unwrap its RHS
        assign = _single_assignment(arg.id, scope)
        if assign is not None and isinstance(assign, ast.Call):
            return _unwrap_target(assign, scope, depth + 1)
        return None, None
    if isinstance(arg, ast.Call):
        kind = _callable_kind(dotted(arg.func))
        if kind in ("wrapper", "shard_map", "jit") and arg.args:
            return _unwrap_target(arg.args[0], scope, depth + 1)
        # builder idiom: jax.jit(self._train_step_fn()) — resolve the
        # builder and follow its `return <local def>`
        builder = None
        if isinstance(arg.func, ast.Attribute) and \
                isinstance(arg.func.value, ast.Name) and \
                arg.func.value.id in ("self", "cls"):
            cls_scope = scope
            while cls_scope and not isinstance(cls_scope.node,
                                               ast.ClassDef):
                cls_scope = cls_scope.parent
            if cls_scope:
                builder = cls_scope.children.get(arg.func.attr)
        elif isinstance(arg.func, ast.Name):
            builder = scope.resolve(arg.func.id)
        if builder and isinstance(builder.node, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)):
            for n in ast.walk(builder.node):
                if isinstance(n, ast.Return) and \
                        isinstance(n.value, ast.Name):
                    t = builder.resolve(n.value.id)
                    if t:
                        return t.node, t.parent
    return None, None


def _single_assignment(name, scope):
    """RHS of the one assignment binding ``name`` in the lexical chain,
    or None when unbound or bound more than once (ambiguous)."""
    s = scope
    while s is not None:
        found = []
        it = _own_statements(s.node) if _is_funcdef(s.node) \
            else ast.walk(s.node)
        for n in it:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.targets[0].id == name:
                found.append(n.value)
        if found:
            return found[0] if len(found) == 1 else None
        s = s.parent
    return None


def _own_statements(fnode):
    """Walk a function's body WITHOUT descending into nested function
    definitions (those are separate scopes, analyzed on their own)."""
    body = fnode.body if isinstance(fnode.body, list) else [fnode.body]
    stack = list(body)
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not _is_funcdef(child):
                stack.append(child)


class TraceIndex:
    """Per-module: which function scopes run under a jax tracer, which
    jit root each one descends from, and the axis names (if statically
    resolvable) declared by the enclosing pmap/shard_map."""

    def __init__(self, module, ctx):
        self.module = module
        self.root, self.by_node = build_scopes(module)
        self.traced = {}                # Scope -> root qualname
        self.axes = {}                  # Scope -> frozenset | None
        self.roots = set()              # scopes jit'd DIRECTLY: their
        self._find_roots(ctx)           # params are traced for sure;
        self.roots = set(self.traced)   # helpers may get static args
        self._propagate()

    def _find_roots(self, ctx):
        for node, scope in list(self.by_node.items()):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                d = dotted(dec if not isinstance(dec, ast.Call)
                           else dec.func)
                if d and _callable_kind(d) == "jit":
                    self._mark(scope, scope.qualname, axes=None)
                elif isinstance(dec, ast.Call) and d in (
                        "functools.partial", "partial") and dec.args:
                    inner = dotted(dec.args[0])
                    if inner and _callable_kind(inner) == "jit":
                        self._mark(scope, scope.qualname, axes=None)
        for node in ast.walk(self.module.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _callable_kind(dotted(node.func))
            if kind not in ("jit", "shard_map"):
                continue
            scope = self._enclosing_scope(node)
            if not node.args:
                continue
            target, tscope = _unwrap_target(node.args[0], scope)
            if target is None or target not in self.by_node:
                continue
            axes = self._declared_axes(node, scope, ctx, kind)
            self._mark(self.by_node[target],
                       self.by_node[target].qualname, axes)

    def _enclosing_scope(self, node):
        # cheap: recompute by walking — build a parent map once instead
        if not hasattr(self, "_parents"):
            self._parents = {}
            for n in ast.walk(self.module.tree):
                for c in ast.iter_child_nodes(n):
                    self._parents[c] = n
        n = self._parents.get(node)
        while n is not None:
            if n in self.by_node and not isinstance(n, ast.ClassDef):
                return self.by_node[n]
            n = self._parents.get(n)
        return self.root

    def _declared_axes(self, call, scope, ctx, kind):
        """Axis names declared by this pmap/shard_map call, or None
        when not statically resolvable."""
        if kind != "shard_map":
            d = dotted(call.func) or ""
            if d.endswith("pmap") or d == "pmap":
                for kw in call.keywords:
                    if kw.arg == "axis_name":
                        v = _axis_value(kw.value, scope, ctx)
                        return frozenset([v]) if v else None
                if len(call.args) >= 2:
                    v = _axis_value(call.args[1], scope, ctx)
                    return frozenset([v]) if v else None
            return None
        mesh_expr = None
        for kw in call.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
        if mesh_expr is None and len(call.args) >= 2:
            mesh_expr = call.args[1]
        return _mesh_axes(mesh_expr, scope, ctx)

    def _mark(self, scope, root_qualname, axes):
        if scope in self.traced:
            if axes:
                prev = self.axes.get(scope)
                self.axes[scope] = (prev | axes) if prev else axes
            return
        self.traced[scope] = root_qualname
        self.axes[scope] = axes

    def _propagate(self):
        changed = True
        while changed:
            changed = False
            for scope, rootq in list(self.traced.items()):
                axes = self.axes.get(scope)
                # (a) functions DEFINED inside a traced function trace
                for child in scope.children.values():
                    if child not in self.traced:
                        self._mark(child, rootq, axes)
                        changed = True
                    elif axes and not self.axes.get(child):
                        self.axes[child] = axes
                        changed = True
                # (b) local functions CALLED (or passed as callbacks)
                # from a traced body trace too
                for n in _own_statements(scope.node):
                    names = []
                    if isinstance(n, ast.Call):
                        if isinstance(n.func, ast.Name):
                            names.append(n.func.id)
                        names.extend(a.id for a in n.args
                                     if isinstance(a, ast.Name))
                    for name in names:
                        t = scope.resolve(name)
                        if t is None or t.node is scope.node:
                            continue
                        if not _is_funcdef(t.node):
                            continue
                        if t not in self.traced:
                            self._mark(t, rootq, axes)
                            changed = True
                        elif axes and not self.axes.get(t):
                            self.axes[t] = axes
                            changed = True


def _axis_value(node, scope, ctx):
    """Resolve an axis-name expression to a string, or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return ctx.resolve_str_constant(node.id)
    return None


def _mesh_axes(expr, scope, ctx):
    """Axis names of a mesh expression, or None when unresolvable:
    make_mesh({"data": 8, ...}), Mesh(devs, ("data",)),
    Mesh(devs, axis_names=(...)), or a local Name bound to one."""
    seen = set()
    while isinstance(expr, ast.Name) and expr.id not in seen:
        seen.add(expr.id)
        target = None
        s = scope
        while s is not None and target is None:
            for n in _own_statements(s.node) \
                    if _is_funcdef(s.node) else ast.walk(s.node):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and n.targets[0].id == expr.id:
                    target = n.value
            s = s.parent
        if target is None:
            return None
        expr = target
    if not isinstance(expr, ast.Call):
        return None
    d = dotted(expr.func) or ""
    if d.endswith("make_mesh") or d == "make_mesh":
        if expr.args and isinstance(expr.args[0], ast.Dict):
            keys = []
            for k in expr.args[0].keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    return None
                keys.append(k.value)
            return frozenset(keys)
        return None
    if d.endswith("Mesh") or d == "Mesh":
        names_expr = None
        for kw in expr.keywords:
            if kw.arg == "axis_names":
                names_expr = kw.value
        if names_expr is None and len(expr.args) >= 2:
            names_expr = expr.args[1]
        if isinstance(names_expr, (ast.Tuple, ast.List)):
            vals = []
            for e in names_expr.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)):
                    return None
                vals.append(e.value)
            return frozenset(vals)
        if isinstance(names_expr, ast.Constant) \
                and isinstance(names_expr.value, str):
            return frozenset([names_expr.value])
    return None


def get_trace_index(module, ctx):
    cache = getattr(module, "_trace_index", None)
    if cache is None:
        cache = TraceIndex(module, ctx)
        module._trace_index = cache
    return cache


# -- SPK101: host sync in traced code ---------------------------------------

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_NP_SYNC = {"asarray", "array", "copy", "save"}


@rule("SPK101", "host-sync-in-jit", SEVERITY_ERROR)
def host_sync_in_jit(module, ctx):
    """Host-device synchronization inside jit-traced code: .item() /
    .tolist() / float() / int() / np.asarray / jax.device_get reachable
    from a jit/pmap/shard_map root. Each is a blocking device round
    trip serialized into the compiled hot path (and most fail outright
    on tracers)."""
    idx = get_trace_index(module, ctx)
    np_alias = numpy_aliases(module)
    for scope, rootq in idx.traced.items():
        for n in _own_statements(scope.node):
            if not isinstance(n, ast.Call):
                continue
            msg = None
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _SYNC_ATTRS and not n.args:
                msg = f"`.{n.func.attr}()`"
            elif isinstance(n.func, ast.Name) \
                    and n.func.id in ("float", "int") and n.args \
                    and not isinstance(n.args[0], ast.Constant):
                msg = f"`{n.func.id}()` on a traced value"
            else:
                d = dotted(n.func)
                if d:
                    head, _, tail = d.rpartition(".")
                    if head in np_alias and tail in _NP_SYNC:
                        msg = f"`{d}()` (numpy materializes on host)"
                    elif d in ("jax.device_get", "jax.device_put"):
                        msg = f"`{d}()`"
            if msg:
                yield make_finding(
                    host_sync_in_jit, module,
                    f"host sync {msg} inside jit-traced code "
                    f"(reachable from `{rootq}`); hoist it out of the "
                    "compiled path", node=n, symbol=scope.qualname)


# -- SPK102: recompile / trace hazards --------------------------------------

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}


def _static_name_uses(cond, params):
    """Names in ``cond`` that are traced params used as VALUES (not via
    .shape/.ndim/len()/`is None`, which are static under tracing)."""
    hits = []
    parents = {}
    for n in ast.walk(cond):
        for c in ast.iter_child_nodes(n):
            parents[c] = n
    for n in ast.walk(cond):
        if not (isinstance(n, ast.Name) and n.id in params):
            continue
        p = parents.get(n)
        if isinstance(p, ast.Attribute) and p.attr in _SHAPE_ATTRS:
            continue
        if isinstance(p, ast.Call) and p.func is not n:
            d = dotted(p.func)
            if isinstance(p.func, ast.Name) and p.func.id in (
                    "len", "isinstance", "hasattr", "getattr", "type"):
                continue
            if d and (d.rpartition(".")[2] in ("ndim", "result_type")):
                continue
        if isinstance(p, ast.Compare):
            ops = p.ops
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in ops):
                continue
        if isinstance(p, ast.Subscript) and p.value is not n:
            continue                     # x[i]: i static is common
        hits.append(n)
    return hits


@rule("SPK102", "recompile-hazard", SEVERITY_WARN)
def recompile_hazard(module, ctx):
    """Patterns that force retraces/recompiles (or TracerBoolConversion
    errors): Python `if`/`while` branching on a traced function
    parameter, `for` iterating a traced parameter or `range(<traced>)`,
    closure capture of a mutable module-level global inside traced
    code, and list/dict/set literals passed to jit static args."""
    idx = get_trace_index(module, ctx)
    mutable_globals = _mutable_module_globals(module)
    for scope, rootq in idx.traced.items():
        # only a jit ROOT's own parameters are traced for certain;
        # helpers it calls may legitimately take static arguments
        # (axis lists, tree_map flags), so param-flow checks stop there
        params = set(scope.params()) if scope in idx.roots else set()
        for n in _own_statements(scope.node):
            if isinstance(n, (ast.If, ast.While)):
                for hit in _static_name_uses(n.test, params):
                    yield make_finding(
                        recompile_hazard, module,
                        f"Python `{type(n).__name__.lower()}` on traced "
                        f"value `{hit.id}` (param of `{scope.qualname}`)"
                        ": branches on data retrace per value or fail "
                        "under jit; use lax.cond/jnp.where",
                        node=n, symbol=scope.qualname)
            elif isinstance(n, ast.For):
                it = n.iter
                bad = None
                if isinstance(it, ast.Name) and it.id in params:
                    bad = it.id
                elif isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Name) \
                        and it.func.id == "range" and it.args \
                        and isinstance(it.args[-1], ast.Name) \
                        and it.args[-1].id in params:
                    bad = it.args[-1].id
                if bad:
                    yield make_finding(
                        recompile_hazard, module,
                        f"Python `for` over traced value `{bad}` in "
                        f"`{scope.qualname}`: loop length becomes part "
                        "of the trace; use lax.scan/fori_loop",
                        node=n, symbol=scope.qualname)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                if n.id in mutable_globals \
                        and not _bound_below_module(scope, n.id):
                    yield make_finding(
                        recompile_hazard, module,
                        f"traced code in `{scope.qualname}` reads "
                        f"mutable module global `{n.id}`: its value is "
                        "baked in at trace time and silently goes "
                        "stale (or retraces)", node=n,
                        symbol=scope.qualname)
    yield from _static_arg_hazards(module, ctx, idx)


def _bound_below_module(scope, name):
    """Is ``name`` shadowed by any FUNCTION scope on the chain (the
    module root doesn't count — that's where the global itself lives)?"""
    s = scope
    while s is not None and s.parent is not None:
        if name in s.bound or name in s.children:
            return True
        s = s.parent
    return False


def _mutable_module_globals(module):
    """Module-level names bound to mutable literals, or rebound more
    than once at module level."""
    counts, mutable = {}, set()
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    counts[t.id] = counts.get(t.id, 0) + 1
                    if isinstance(node.value, (ast.List, ast.Dict,
                                               ast.Set)):
                        mutable.add(t.id)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name):
            mutable.add(node.target.id)
    mutable.update(n for n, c in counts.items() if c > 1)
    return mutable


def _static_arg_hazards(module, ctx, idx):
    """`f = jax.jit(g, static_argnums=(1,)); f(x, [1, 2])` — the list
    is unhashable, so every call raises (or, with tuple-ish coercions
    upstream, recompiles per call)."""
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        if _callable_kind(dotted(call.func)) != "jit":
            continue
        static_nums, static_names = set(), set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                static_nums = _int_tuple(kw.value)
            elif kw.arg == "static_argnames":
                static_names = _str_tuple(kw.value)
        if not static_nums and not static_names:
            continue
        jitted = node.targets[0].id
        fscope = idx._enclosing_scope(node)
        for n in _own_statements(fscope.node) \
                if _is_funcdef(fscope.node) else ast.walk(module.tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == jitted):
                continue
            for i, a in enumerate(n.args):
                if i in static_nums and isinstance(
                        a, (ast.List, ast.Dict, ast.Set)):
                    yield make_finding(
                        recompile_hazard, module,
                        f"unhashable {type(a).__name__.lower()} literal "
                        f"passed to static arg {i} of jitted "
                        f"`{jitted}`", node=a, symbol=fscope.qualname)
            for kw in n.keywords:
                if kw.arg in static_names and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    yield make_finding(
                        recompile_hazard, module,
                        "unhashable "
                        f"{type(kw.value).__name__.lower()} literal "
                        f"passed to static arg `{kw.arg}` of jitted "
                        f"`{jitted}`", node=kw.value,
                        symbol=fscope.qualname)


def _int_tuple(node):
    out = set()
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
        else [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.add(e.value)
    return out


def _str_tuple(node):
    out = set()
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
        else [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


# -- SPK103: PRNG key reuse -------------------------------------------------

_KEY_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "clone",
                 "key_data", "wrap_key_data"}


@rule("SPK103", "prng-key-reuse", SEVERITY_ERROR)
def prng_key_reuse(module, ctx):
    """The same PRNG key consumed by two `jax.random.*` sampler calls
    without an intervening split/fold_in rebind — the draws are
    identical, which silently correlates what should be independent
    noise (dropout masks, init, augmentation). Also flags a sampler
    consuming, inside a loop, a key that was created outside the loop
    (every iteration redraws the same randomness)."""
    aliases = random_aliases(module)
    root, by_node = build_scopes(module)
    seen = set()

    def is_sampler(call):
        d = dotted(call.func)
        if d is None:
            return False
        head, _, tail = d.rpartition(".")
        return head in aliases and tail not in _KEY_DERIVERS

    def is_key_expr(expr):
        if isinstance(expr, ast.Call):
            d = dotted(expr.func)
            if d:
                head, _, tail = d.rpartition(".")
                if head in aliases and tail in _KEY_DERIVERS:
                    return True
        if isinstance(expr, ast.Subscript) and is_key_expr(expr.value):
            return True
        return False

    def walk_fn(fnode, qual):
        if id(fnode) in seen:
            return
        seen.add(id(fnode))
        keys = {}
        # params that are by-convention PRNG keys are tracked from the
        # start — `rng` consumed twice inside one body is the bug
        # whether the key was made here or passed in
        for a in fnode.args.posonlyargs + fnode.args.args \
                + fnode.args.kwonlyargs:
            n = a.arg.lower()
            if n in ("rng", "key", "rngs", "prng_key") \
                    or n.endswith("_rng") or n.endswith("_key"):
                keys[a.arg] = [0, None]
        body = fnode.body if isinstance(fnode.body, list) else []
        yield from walk_block(body, keys, 0, qual)

    def walk_block(stmts, keys, loop_depth, qual):
        # keys: name -> [bound_loop_depth, consumed_line_or_None]
        for st in stmts:
            if _is_funcdef(st):
                continue                 # separate scope, walked below
            # find sampler consumptions anywhere in this statement
            for call in _calls_in(st):
                if not is_sampler(call) or not call.args:
                    continue
                a = call.args[0]
                if not isinstance(a, ast.Name) or a.id not in keys:
                    continue
                rec = keys[a.id]
                if rec[1] is not None:
                    yield make_finding(
                        prng_key_reuse, module,
                        f"PRNG key `{a.id}` reused: already consumed "
                        f"by a jax.random call at line {rec[1]}; "
                        "split/fold_in a fresh key instead",
                        node=call, symbol=qual)
                elif rec[0] < loop_depth:
                    yield make_finding(
                        prng_key_reuse, module,
                        f"PRNG key `{a.id}` consumed inside a loop but "
                        "created outside it: every iteration draws "
                        "identical randomness; fold_in the loop index",
                        node=call, symbol=qual)
                    rec[1] = call.lineno
                else:
                    rec[1] = call.lineno
            # then process (re)bindings this statement makes
            if isinstance(st, ast.Assign):
                names = []
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, ast.Tuple):
                        names.extend(e.id for e in t.elts
                                     if isinstance(e, ast.Name))
                if is_key_expr(st.value):
                    for nm in names:
                        keys[nm] = [loop_depth, None]
                else:
                    for nm in names:
                        keys.pop(nm, None)
            # recurse into compound statements
            if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                inner = dict((k, list(v)) for k, v in keys.items())
                yield from walk_block(st.body, inner, loop_depth + 1,
                                      qual)
                yield from walk_block(st.orelse, keys, loop_depth, qual)
            elif isinstance(st, ast.If):
                then_keys = dict((k, list(v)) for k, v in keys.items())
                else_keys = dict((k, list(v)) for k, v in keys.items())
                yield from walk_block(st.body, then_keys, loop_depth,
                                      qual)
                yield from walk_block(st.orelse, else_keys, loop_depth,
                                      qual)
                # a key is consumed after the If only if BOTH branches
                # consumed it (conservative: no false reuse reports
                # across exclusive branches)
                for nm, rec in keys.items():
                    t = then_keys.get(nm, [0, None])[1]
                    e = else_keys.get(nm, [0, None])[1]
                    if t is not None and e is not None:
                        rec[1] = rec[1] or t
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                yield from walk_block(st.body, keys, loop_depth, qual)
            elif isinstance(st, ast.Try):
                for blk in (st.body, st.orelse, st.finalbody):
                    yield from walk_block(blk, keys, loop_depth, qual)
                for h in st.handlers:
                    yield from walk_block(h.body, keys, loop_depth, qual)
        return

    def _calls_in(stmt):
        """Calls in this statement, excluding nested function bodies
        AND nested statement blocks (compound statements only expose
        their header expressions here; their bodies are re-walked with
        the right loop depth / branch state by walk_block)."""
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots = [stmt.iter]
        elif isinstance(stmt, (ast.While, ast.If)):
            roots = [stmt.test]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, ast.Try):
            roots = []
        else:
            roots = [stmt]
        stack = list(roots)
        while stack:
            n = stack.pop()
            if _is_funcdef(n) and n is not stmt:
                continue
            if isinstance(n, ast.Call):
                yield n
            for c in ast.iter_child_nodes(n):
                if not _is_funcdef(c):
                    stack.append(c)

    for node, scope in by_node.items():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from walk_fn(node, scope.qualname)


# -- SPK104: collective axis-name mismatch ----------------------------------

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                "axis_index", "pswapaxes"}
# which argument of each collective is the axis name
_AXIS_ARG = {"axis_index": 0, "ppermute": 1, "pshuffle": 1}
# collectives whose `axis=` KWARG is an array dimension, not the mesh
# axis name (all_gather(x, axis_name, *, axis=0, tiled=...) and
# friends) — the axis name is positional there, never that kwarg
_DIM_AXIS_KWARG = {"all_gather", "all_to_all", "pswapaxes"}


def _collective_axis_expr(call):
    d = dotted(call.func)
    if d is None:
        return None, None
    tail = d.rpartition(".")[2]
    if tail not in _COLLECTIVES:
        return None, None
    for kw in call.keywords:
        if kw.arg == "axis_name" or (kw.arg == "axis"
                                     and tail not in _DIM_AXIS_KWARG):
            return tail, kw.value
    pos = _AXIS_ARG.get(tail, 1)
    if len(call.args) > pos:
        return tail, call.args[pos]
    return tail, None


def collect_axis_helpers(module):
    """{function basename: set of param indices forwarded as a
    collective axis argument} — the cross-module summary that lets call
    sites of masked_consensus & co. be checked against the caller's
    declared axes."""
    out = {}
    root, by_node = build_scopes(module)
    for node, scope in by_node.items():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = scope.params()
        fwd = set()
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            tail, axis_expr = _collective_axis_expr(n)
            if tail and isinstance(axis_expr, ast.Name) \
                    and axis_expr.id in params:
                fwd.add(params.index(axis_expr.id))
        if fwd:
            out.setdefault(node.name, set()).update(fwd)
    return out


@rule("SPK104", "collective-axis-mismatch", SEVERITY_ERROR)
def collective_axis_mismatch(module, ctx):
    """A collective (pmean/psum/all_gather/axis_index/...) names an
    axis the enclosing pmap/shard_map does not declare — at runtime
    this is a NameError deep inside the compiled call, or worse, a
    reduction over the wrong axis. Only fires when both the declared
    mesh axes and the collective's axis argument resolve statically;
    calls through axis-forwarding helpers (e.g. masked_consensus) are
    checked at the call site."""
    idx = get_trace_index(module, ctx)
    for scope, rootq in idx.traced.items():
        axes = idx.axes.get(scope)
        if not axes:
            continue
        for n in _own_statements(scope.node):
            if not isinstance(n, ast.Call):
                continue
            tail, axis_expr = _collective_axis_expr(n)
            if tail:
                for val, enode in _axis_literals(axis_expr, scope, ctx):
                    if val not in axes:
                        yield make_finding(
                            collective_axis_mismatch, module,
                            f"collective `{tail}` uses axis "
                            f"`{val}` but the enclosing mesh declares "
                            f"{sorted(axes)}", node=enode or n,
                            symbol=scope.qualname)
                continue
            # helper forwarding: f(..., "axis", ...) where f is known
            # to forward that param to a collective
            fname = None
            if isinstance(n.func, ast.Name):
                fname = n.func.id
            elif isinstance(n.func, ast.Attribute):
                fname = n.func.attr
            helper_idxs = ctx.axis_helpers.get(fname)
            if not helper_idxs:
                continue
            for i in helper_idxs:
                if i < len(n.args):
                    for val, enode in _axis_literals(n.args[i], scope,
                                                     ctx):
                        if val not in axes:
                            yield make_finding(
                                collective_axis_mismatch, module,
                                f"`{fname}` forwards axis `{val}` to a "
                                "collective but the enclosing mesh "
                                f"declares {sorted(axes)}",
                                node=n, symbol=scope.qualname)


def _axis_literals(expr, scope, ctx):
    """Resolvable string axis names in an axis expression (handles
    tuples of axes); yields (value, node)."""
    if expr is None:
        return
    if isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            yield from _axis_literals(e, scope, ctx)
        return
    v = _axis_value(expr, scope, ctx)
    if v is not None:
        yield v, expr


# -- SPK105: missing buffer donation ----------------------------------------

_STATE_PARAMS = {"params", "state", "history", "opt_state",
                 "optimizer_state", "variables", "weights"}


@rule("SPK105", "missing-donation", SEVERITY_WARN)
def missing_donation(module, ctx):
    """A jitted update-style function — it takes params/state/history
    AND returns them — without donate_argnums/donate_argnames: every
    step allocates a second copy of the model in HBM instead of
    updating in place. Eval-style functions (state in, scores out) are
    exempt — donating their params would free buffers the next call
    still needs."""
    idx = get_trace_index(module, ctx)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _callable_kind(dotted(node.func)) != "jit":
            continue
        if any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in node.keywords):
            continue
        if not node.args:
            continue
        scope = idx._enclosing_scope(node)
        target, tscope = _unwrap_target(node.args[0], scope)
        if target is None or not _is_funcdef(target) \
                or isinstance(target, ast.Lambda):
            continue
        tparams = [p.arg for p in target.args.args]
        statey = [p for p in tparams if p in _STATE_PARAMS]
        if not statey:
            continue
        returned = set()
        for n in ast.walk(target):
            if isinstance(n, ast.Return) and n.value is not None:
                vals = n.value.elts if isinstance(n.value, ast.Tuple) \
                    else [n.value]
                returned.update(v.id for v in vals
                                if isinstance(v, ast.Name))
        carried = [p for p in statey if p in returned]
        if carried:
            yield make_finding(
                missing_donation, module,
                f"jit of `{target.name}` carries {carried} through the "
                "update but declares no donate_argnums: each step pays "
                "a full extra copy of those buffers in HBM",
                node=node, symbol=idx.by_node[target].qualname
                if target in idx.by_node else target.name)
