"""SPK2xx — lock-discipline race checker for the threaded host side.

The solver loop shares host state with the watchdog monitor thread,
the prefetch workers, the metrics logger and the live monitor's tailer.
The discipline is annotation-driven, GuardedBy-style (ErrorProne /
Tricorder lineage):

  self._last = 0.0          # spk: guarded-by=_lock

declares that ``self._last`` may only be touched inside a
``with self._lock:`` block. A class-wide default exists for state
holders whose every field is shared:

  class MonitorState:
      # spk: guarded-by-default=_lock

(every field assigned in ``__init__`` becomes guarded, except the lock
itself, sync primitives, and lines annotated ``# spk: unguarded``).

Thread entry points are methods passed as ``target=self.m`` to
``threading.Thread`` plus methods annotated ``# spk: thread-entry``
(for cross-object handoffs the checker cannot see, e.g. a closure in
another function calling ``state.update``); reachability closes over
``self.m()`` calls.

Rules:
  SPK201 (error)  guarded field accessed without its lock in a method
                  reachable from a thread entry point — a data race
  SPK202 (warn)   guarded field accessed without its lock elsewhere
                  (the main-thread side of the same race; __init__ and
                  __del__ are exempt — the object isn't shared yet)
  SPK203 (warn)   guarded-by names a lock the class never creates —
                  a stale annotation to fix or narrow
  SPK204 (warn)   a field written both by thread-reachable and other
                  methods with no guarded-by at all — the checker's
                  "you have an unannotated shared field" tripwire

Known scope limits, on purpose: accesses through aliases
(``x = self.f``) and from *outside* the class are not tracked — the
annotation contract is that shared fields are touched via methods.
"""

import ast
import re

from .engine import (rule, make_finding, SEVERITY_ERROR, SEVERITY_WARN)

_GUARD_RE = re.compile(r"#\s*spk:\s*guarded-by\s*=\s*(\w+)")
_GUARD_DEFAULT_RE = re.compile(r"#\s*spk:\s*guarded-by-default\s*=\s*(\w+)")
_UNGUARDED_RE = re.compile(r"#\s*spk:\s*unguarded\b")
_THREAD_ENTRY_RE = re.compile(r"#\s*spk:\s*thread-entry\b")
_HOLDS_RE = re.compile(r"#\s*spk:\s*holds\s*=\s*(\w+)")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_SYNC_CTORS = _LOCK_CTORS | {"Event", "Semaphore", "BoundedSemaphore",
                             "Barrier", "Queue", "LifoQueue",
                             "PriorityQueue", "SimpleQueue",
                             "local", "Thread"}


def _ctor_basename(value):
    node = value
    while isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None
    return None


class ClassInfo:
    """Everything SPK201-204 need to know about one class."""

    def __init__(self, module, node):
        self.module = module
        self.node = node
        self.name = node.name
        self.guards = {}          # field -> lock attr name
        self.unguarded = set()    # fields explicitly opted out
        self.locks = set()        # lock attrs the class creates
        self.sync_fields = set()  # Lock/Event/Queue/... fields
        self.methods = {}         # name -> FunctionDef
        self.entries = set()      # thread entry method names
        self.holds = {}           # method -> lock it requires held
        self.guard_lines = {}     # field -> annotation line (for SPK203)
        self._collect()

    def _collect(self):
        default_guard = None
        for i in range(self.node.lineno,
                       self._end_line() + 1):
            m = _GUARD_DEFAULT_RE.search(self.module.line_text(i))
            if m:
                default_guard = m.group(1)
                break
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
                if _THREAD_ENTRY_RE.search(
                        self.module.line_text(item.lineno)):
                    self.entries.add(item.name)
                hm = _HOLDS_RE.search(self.module.line_text(item.lineno))
                if hm:
                    self.holds[item.name] = hm.group(1)
        # field discovery: every `self.X = ...` in any method (guards
        # usually sit in __init__ but setters re-assign too)
        for mname, mnode in self.methods.items():
            for n in ast.walk(mnode):
                if not isinstance(n, ast.Assign):
                    continue
                for t in n.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    field = t.attr
                    text = self.module.line_text(n.lineno)
                    ctor = _ctor_basename(n.value)
                    if ctor in _LOCK_CTORS:
                        self.locks.add(field)
                    if ctor in _SYNC_CTORS:
                        self.sync_fields.add(field)
                    gm = _GUARD_RE.search(text)
                    if gm:
                        self.guards[field] = gm.group(1)
                        self.guard_lines.setdefault(field, n.lineno)
                    elif _UNGUARDED_RE.search(text):
                        self.unguarded.add(field)
                    elif default_guard and mname == "__init__" \
                            and field != default_guard \
                            and ctor not in _SYNC_CTORS:
                        self.guards.setdefault(field, default_guard)
                        self.guard_lines.setdefault(field, n.lineno)
        self.unguarded -= set(self.guards)
        for f in self.unguarded:
            self.guards.pop(f, None)

    def _end_line(self):
        return getattr(self.node, "end_lineno", self.node.lineno)

    def thread_reachable(self):
        """Method names reachable from the thread entry points via
        self.m() calls (the intra-class call graph)."""
        reach = set(self.entries)
        changed = True
        while changed:
            changed = False
            for name in list(reach):
                mnode = self.methods.get(name)
                if mnode is None:
                    continue
                for n in ast.walk(mnode):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and isinstance(n.func.value, ast.Name) \
                            and n.func.value.id == "self" \
                            and n.func.attr in self.methods \
                            and n.func.attr not in reach:
                        reach.add(n.func.attr)
                        changed = True
        return reach


def _classes(module):
    cache = getattr(module, "_thread_classes", None)
    if cache is not None:
        return cache
    cache = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            cache.append(ClassInfo(module, node))
    # `target=self._run` thread creations can appear anywhere in the
    # module (even another class/function); attribute them by method
    # name to every class defining that method
    targets = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target" and \
                        isinstance(kw.value, ast.Attribute) and \
                        isinstance(kw.value.value, ast.Name) and \
                        kw.value.value.id == "self":
                    targets.add(kw.value.attr)
    for ci in cache:
        ci.entries |= {t for t in targets if t in ci.methods}
    module._thread_classes = cache
    return cache


def _held_locks_walk(method, visit, initial_held=frozenset()):
    """Walk ``method``'s body tracking the set of self.<lock> names
    held via `with self.<lock>:` blocks; calls visit(node, held) on
    every node. Nested function defs inherit the held set at their
    definition point only if they are immediately-invoked — otherwise
    they run later on an unknown thread, so they get an empty held set
    (conservative for closures handed to Thread(target=...))."""

    def lock_names(withnode):
        names = []
        for item in withnode.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and \
                    isinstance(e.value, ast.Name) and e.value.id == "self":
                names.append(e.attr)
        return names

    def walk(node, held):
        visit(node, held)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | set(lock_names(node))
            for item in node.items:
                walk(item.context_expr, held)
                if item.optional_vars:
                    walk(item.optional_vars, held)
            for b in node.body:
                walk(b, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not method:
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            for b in body:
                walk(b, frozenset())
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for b in method.body:
        walk(b, frozenset(initial_held))


@rule("SPK201", "lock-discipline", SEVERITY_ERROR)
def lock_discipline(module, ctx):
    """Guarded field accessed outside its `with <lock>:` block in a
    method reachable from a thread entry point — two threads can be in
    here at once, so this is a data race on the annotated field."""
    yield from _guard_findings(module, reachable_only=True,
                               fn=lock_discipline)


@rule("SPK202", "lock-discipline-main", SEVERITY_WARN)
def lock_discipline_main(module, ctx):
    """Guarded field accessed outside its lock in a method NOT on any
    thread path — the main-thread half of the same race (the other
    thread can still interleave). __init__/__del__ are exempt: the
    object isn't shared yet/anymore."""
    yield from _guard_findings(module, reachable_only=False,
                               fn=lock_discipline_main)


def _guard_findings(module, reachable_only, fn):
    for ci in _classes(module):
        if not ci.guards:
            continue
        reach = ci.thread_reachable()
        for mname, mnode in ci.methods.items():
            if mname in ("__init__", "__del__"):
                continue
            in_reach = mname in reach
            if reachable_only != in_reach:
                continue
            hits = []

            def visit(node, held, _hits=hits, _ci=ci):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and \
                        node.attr in _ci.guards and \
                        _ci.guards[node.attr] not in held:
                    _hits.append((node, node.attr,
                                  _ci.guards[node.attr], "field"))
                # calling a `# spk: holds=<lock>` helper without the
                # lock breaks its contract just like a naked access
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in _ci.holds and \
                        _ci.holds[node.func.attr] not in held:
                    _hits.append((node, node.func.attr,
                                  _ci.holds[node.func.attr], "holds"))

            # `# spk: holds=<lock>` on the def line: a private helper
            # whose contract is "only called with <lock> held" — the
            # checker trusts the annotation and verifies the callers
            # (they must wrap the call in `with self.<lock>:`)
            held0 = set()
            hm = _HOLDS_RE.search(module.line_text(mnode.lineno))
            if hm:
                held0.add(hm.group(1))
            _held_locks_walk(mnode, visit, initial_held=held0)
            for node, what, lock, kind in hits:
                where = "thread-reachable " if reachable_only else ""
                noun = "field" if kind == "field" else \
                    "lock-requiring helper"
                verb = "accessed" if kind == "field" else "called"
                yield make_finding(
                    fn, module,
                    f"{noun} `{what}` (guarded-by `{lock}`) "
                    f"{verb} without holding `self.{lock}` in "
                    f"{where}method `{ci.name}.{mname}`",
                    node=node, symbol=f"{ci.name}.{mname}")


@rule("SPK203", "stale-guard-annotation", SEVERITY_WARN)
def stale_guard_annotation(module, ctx):
    """A guarded-by annotation names a lock attribute the class never
    creates (threading.Lock/RLock/Condition assignment) — either the
    lock was renamed/removed or the annotation should be narrowed
    away."""
    for ci in _classes(module):
        for field, lock in sorted(ci.guards.items()):
            if lock not in ci.locks:
                yield make_finding(
                    stale_guard_annotation, module,
                    f"field `{field}` is guarded-by `{lock}` but "
                    f"`{ci.name}` never creates `self.{lock}` as a "
                    "Lock/RLock/Condition",
                    line=ci.guard_lines.get(field, ci.node.lineno),
                    symbol=f"{ci.name}")


@rule("SPK204", "unannotated-shared-write", SEVERITY_WARN)
def unannotated_shared_write(module, ctx):
    """A field written both from a thread-reachable method and from a
    non-thread method, with no guarded-by annotation: the checker can't
    prove anything about it, and that pattern is exactly how the
    watchdog's `_last` race looked. Annotate it (and lock the
    accesses) or mark it `# spk: unguarded` with a reason."""
    for ci in _classes(module):
        if not ci.entries:
            continue
        reach = ci.thread_reachable()
        writes_in, writes_out = {}, {}
        for mname, mnode in ci.methods.items():
            if mname in ("__init__", "__del__"):
                continue
            sink = writes_in if mname in reach else writes_out
            for n in ast.walk(mnode):
                targets = []
                if isinstance(n, ast.Assign):
                    targets = n.targets
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    targets = [n.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        sink.setdefault(t.attr, n)
        for field in sorted(set(writes_in) & set(writes_out)):
            if field in ci.guards or field in ci.unguarded \
                    or field in ci.sync_fields:
                continue
            node = writes_in[field]
            yield make_finding(
                unannotated_shared_write, module,
                f"field `{field}` of `{ci.name}` is written both from "
                "thread-reachable and main-thread methods with no "
                "guarded-by annotation — annotate it (spk: guarded-by="
                "<lock>) or mark it `spk: unguarded` with a reason",
                node=node, symbol=f"{ci.name}")


# -- SPK205-207: the cross-module deadlock family ---------------------------
#
# These three run on the ProjectIndex (ctx.project): lock-acquisition
# edges follow resolved call edges across methods and classes, so a
# cycle split between heartbeat and the consensus helper it calls is
# still one cycle.


def _lock_graph(ctx):
    """{(class, lock): {(class, lock): (relpath, line, via)}} — edge
    A->B when some method acquires B while holding A, directly or
    through a resolved callee. Built once per lint run."""
    proj = ctx.project
    cached = getattr(proj, "_lock_graph", None)
    if cached is not None:
        return cached
    edges = {}

    def add(src, dst, relpath, line, via):
        edges.setdefault(src, {}).setdefault(dst, (relpath, line, via))

    for fi in proj.functions.values():
        if fi.cls is None:
            continue
        cls = fi.cls
        module = proj.modules.get(fi.relpath)
        held0 = set()
        hm = _HOLDS_RE.search(module.line_text(fi.node.lineno))
        if hm:
            held0.add(hm.group(1))

        def visit(node, held, _cls=cls, _mod=module, _fi=fi):
            held_locks = {h for h in held if h in _cls.locks}
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute) and \
                            isinstance(e.value, ast.Name) and \
                            e.value.id == "self" and \
                            e.attr in _cls.locks:
                        for h in held_locks:
                            add((_cls.name, h), (_cls.name, e.attr),
                                _fi.relpath, node.lineno,
                                _fi.qualname)
            elif isinstance(node, ast.Call) and held_locks:
                target = proj.resolve_call(node, _mod, _fi.node)
                if target is None:
                    return
                for dst in proj.transitive_acquires(target.key):
                    for h in held_locks:
                        add((_cls.name, h), dst, _fi.relpath,
                            node.lineno, target.qualname)

        _held_locks_walk(fi.node, visit, initial_held=held0)
    proj._lock_graph = edges
    return edges


def _sccs(edges):
    """Tarjan SCCs of the lock graph (iterative)."""
    index, low, on_stack = {}, {}, set()
    stack, out, counter = [], [], [0]
    nodes = set(edges)
    for tgts in edges.values():
        nodes |= set(tgts)

    def strongconnect(v0):
        work = [(v0, iter(edges.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(edges.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return out


def _cycle_path(edges, start, comp):
    """One concrete simple cycle through ``start`` inside SCC ``comp``."""
    comp = set(comp)
    path, seen = [start], {start}
    v = start
    while True:
        nxt = None
        for w in sorted(edges.get(v, ())):
            if w == start and len(path) > 1:
                return path
            if w in comp and w not in seen:
                nxt = w
                break
        if nxt is None:
            return path
        path.append(nxt)
        seen.add(nxt)
        v = nxt


@rule("SPK205", "lock-order-cycle", SEVERITY_ERROR)
def lock_order_cycle(module, ctx):
    """Two locks are acquired in opposite orders on different paths
    (following resolved call edges across methods and classes), or a
    non-reentrant lock is re-acquired while already held — a deadlock
    waiting for the right interleaving. Fix by ordering every path the
    same way, or by narrowing one side to drop its lock first."""
    edges = _lock_graph(ctx)
    # self-edges: re-acquiring a non-reentrant lock you already hold
    for src in sorted(edges):
        info = edges[src].get(src)
        if info is None:
            continue
        relpath, line, via = info
        if relpath != module.relpath:
            continue
        cname, lock = src
        ctor = None
        for cf in ctx.project.classes_by_name.get(cname, []):
            ctor = cf.sync_ctors.get(lock, ctor)
        if ctor == "RLock":
            continue
        yield make_finding(
            lock_order_cycle, module,
            f"`{cname}.{lock}` ({ctor or 'Lock'}) is re-acquired via "
            f"`{via}` while already held — non-reentrant locks "
            "self-deadlock here",
            line=line, symbol=f"{cname}.{lock}")
    # multi-node SCCs: a genuine ordering cycle
    for comp in _sccs(edges):
        if len(comp) < 2:
            continue
        anchor = None          # smallest (relpath, line) edge in SCC
        cset = set(comp)
        for a in comp:
            for b, (relpath, line, _via) in edges.get(a, {}).items():
                if b in cset:
                    k = (relpath, line, a, b)
                    if anchor is None or k < anchor:
                        anchor = k
        if anchor is None or anchor[0] != module.relpath:
            continue
        path = _cycle_path(edges, anchor[2], comp)
        names = [f"`{c}.{l}`" for c, l in path] + \
            [f"`{path[0][0]}.{path[0][1]}`"]
        legs = []
        for i in range(len(path)):
            a = path[i]
            b = path[(i + 1) % len(path)]
            relpath, line, via = edges[a][b]
            legs.append(f"{relpath}:{line} (via `{via}`)")
        yield make_finding(
            lock_order_cycle, module,
            "lock-order cycle " + " -> ".join(names) +
            "; acquired at " + ", ".join(legs),
            line=anchor[1], symbol=f"{anchor[2][0]}.{anchor[2][1]}")


@rule("SPK206", "blocking-call-under-lock", SEVERITY_ERROR)
def blocking_call_under_lock(module, ctx):
    """A lock is held across a call that can block indefinitely —
    sleep, file I/O, a thread join, a queue get, an event wait — found
    transitively through resolved call edges. Every other thread
    touching that lock now stalls behind the slow operation (the
    heartbeat writer stalling the solver loop on a slow NFS fsync is
    the canonical case). Snapshot state under the lock, do the blocking
    work outside it."""
    proj = ctx.project
    for ci in _classes(module):
        if not ci.locks:
            continue
        for mname, mnode in ci.methods.items():
            fkey = (module.relpath, f"{ci.name}.{mname}")
            hits = []

            def visit(node, held, _ci=ci, _mnode=mnode, _hits=hits):
                held_locks = {h for h in held if h in _ci.locks}
                if not held_locks or not isinstance(node, ast.Call):
                    return
                # Condition.wait RELEASES the lock it is guarded by —
                # `with self._cv: self._cv.wait()` is the idiom, not a
                # stall
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in ("wait", "wait_for", "notify",
                                   "notify_all") and \
                        isinstance(f.value, ast.Attribute) and \
                        isinstance(f.value.value, ast.Name) and \
                        f.value.value.id == "self" and \
                        f.value.attr in held:
                    return
                lock = sorted(held_locks)[0]
                desc = proj.classify_blocking(node, module, _mnode)
                if desc is not None:
                    _hits.append((node, lock, desc))
                    return
                target = proj.resolve_call(node, module, _mnode)
                if target is not None:
                    sub = proj.transitively_blocking(target.key)
                    if sub is not None:
                        _hits.append(
                            (node, lock,
                             f"`{target.qualname}` → {sub}"))

            held0 = set()
            hm = _HOLDS_RE.search(module.line_text(mnode.lineno))
            if hm:
                held0.add(hm.group(1))
            _held_locks_walk(mnode, visit, initial_held=held0)
            for node, lock, desc in hits:
                yield make_finding(
                    blocking_call_under_lock, module,
                    f"`self.{lock}` is held across a blocking call: "
                    f"{desc} — snapshot under the lock, block outside "
                    "it",
                    node=node, symbol=f"{ci.name}.{mname}")


@rule("SPK207", "callback-under-lock", SEVERITY_ERROR)
def callback_under_lock(module, ctx):
    """A stored callback (``self.on_x = on_x``) is invoked while the
    emitter's own lock is held. The callback is arbitrary user code: if
    it calls back into this object (or logs through something that
    does) it deadlocks on the very lock we hold; and the dwell time
    under the lock is unbounded. Snapshot, release, then fire."""
    for ci in _classes(module):
        if not ci.locks:
            continue
        pcls = None
        for cf in ctx.project.classes_by_name.get(ci.name, []):
            if cf.relpath == module.relpath:
                pcls = cf
        callbacks = pcls.callback_fields if pcls is not None else set()
        if not callbacks:
            continue
        for mname, mnode in ci.methods.items():
            hits = []

            def visit(node, held, _ci=ci, _cb=callbacks, _hits=hits):
                held_locks = {h for h in held if h in _ci.locks}
                if not held_locks:
                    return
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in _cb:
                    _hits.append((node, node.func.attr,
                                  sorted(held_locks)[0]))

            held0 = set()
            hm = _HOLDS_RE.search(module.line_text(mnode.lineno))
            if hm:
                held0.add(hm.group(1))
            _held_locks_walk(mnode, visit, initial_held=held0)
            for node, cb, lock in hits:
                yield make_finding(
                    callback_under_lock, module,
                    f"callback `self.{cb}` invoked while holding "
                    f"`self.{lock}` — a callback that re-enters this "
                    "object deadlocks; snapshot under the lock and "
                    "fire after releasing it",
                    node=node, symbol=f"{ci.name}.{mname}")
