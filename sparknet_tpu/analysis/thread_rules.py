"""SPK2xx — lock-discipline race checker for the threaded host side.

The solver loop shares host state with the watchdog monitor thread,
the prefetch workers, the metrics logger and the live monitor's tailer.
The discipline is annotation-driven, GuardedBy-style (ErrorProne /
Tricorder lineage):

  self._last = 0.0          # spk: guarded-by=_lock

declares that ``self._last`` may only be touched inside a
``with self._lock:`` block. A class-wide default exists for state
holders whose every field is shared:

  class MonitorState:
      # spk: guarded-by-default=_lock

(every field assigned in ``__init__`` becomes guarded, except the lock
itself, sync primitives, and lines annotated ``# spk: unguarded``).

Thread entry points are methods passed as ``target=self.m`` to
``threading.Thread`` plus methods annotated ``# spk: thread-entry``
(for cross-object handoffs the checker cannot see, e.g. a closure in
another function calling ``state.update``); reachability closes over
``self.m()`` calls.

Rules:
  SPK201 (error)  guarded field accessed without its lock in a method
                  reachable from a thread entry point — a data race
  SPK202 (warn)   guarded field accessed without its lock elsewhere
                  (the main-thread side of the same race; __init__ and
                  __del__ are exempt — the object isn't shared yet)
  SPK203 (warn)   guarded-by names a lock the class never creates —
                  a stale annotation to fix or narrow
  SPK204 (warn)   a field written both by thread-reachable and other
                  methods with no guarded-by at all — the checker's
                  "you have an unannotated shared field" tripwire

Known scope limits, on purpose: accesses through aliases
(``x = self.f``) and from *outside* the class are not tracked — the
annotation contract is that shared fields are touched via methods.
"""

import ast
import re

from .engine import (rule, make_finding, SEVERITY_ERROR, SEVERITY_WARN)

_GUARD_RE = re.compile(r"#\s*spk:\s*guarded-by\s*=\s*(\w+)")
_GUARD_DEFAULT_RE = re.compile(r"#\s*spk:\s*guarded-by-default\s*=\s*(\w+)")
_UNGUARDED_RE = re.compile(r"#\s*spk:\s*unguarded\b")
_THREAD_ENTRY_RE = re.compile(r"#\s*spk:\s*thread-entry\b")
_HOLDS_RE = re.compile(r"#\s*spk:\s*holds\s*=\s*(\w+)")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_SYNC_CTORS = _LOCK_CTORS | {"Event", "Semaphore", "BoundedSemaphore",
                             "Barrier", "Queue", "LifoQueue",
                             "PriorityQueue", "SimpleQueue",
                             "local", "Thread"}


def _ctor_basename(value):
    node = value
    while isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None
    return None


class ClassInfo:
    """Everything SPK201-204 need to know about one class."""

    def __init__(self, module, node):
        self.module = module
        self.node = node
        self.name = node.name
        self.guards = {}          # field -> lock attr name
        self.unguarded = set()    # fields explicitly opted out
        self.locks = set()        # lock attrs the class creates
        self.sync_fields = set()  # Lock/Event/Queue/... fields
        self.methods = {}         # name -> FunctionDef
        self.entries = set()      # thread entry method names
        self.holds = {}           # method -> lock it requires held
        self.guard_lines = {}     # field -> annotation line (for SPK203)
        self._collect()

    def _collect(self):
        default_guard = None
        for i in range(self.node.lineno,
                       self._end_line() + 1):
            m = _GUARD_DEFAULT_RE.search(self.module.line_text(i))
            if m:
                default_guard = m.group(1)
                break
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
                if _THREAD_ENTRY_RE.search(
                        self.module.line_text(item.lineno)):
                    self.entries.add(item.name)
                hm = _HOLDS_RE.search(self.module.line_text(item.lineno))
                if hm:
                    self.holds[item.name] = hm.group(1)
        # field discovery: every `self.X = ...` in any method (guards
        # usually sit in __init__ but setters re-assign too)
        for mname, mnode in self.methods.items():
            for n in ast.walk(mnode):
                if not isinstance(n, ast.Assign):
                    continue
                for t in n.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    field = t.attr
                    text = self.module.line_text(n.lineno)
                    ctor = _ctor_basename(n.value)
                    if ctor in _LOCK_CTORS:
                        self.locks.add(field)
                    if ctor in _SYNC_CTORS:
                        self.sync_fields.add(field)
                    gm = _GUARD_RE.search(text)
                    if gm:
                        self.guards[field] = gm.group(1)
                        self.guard_lines.setdefault(field, n.lineno)
                    elif _UNGUARDED_RE.search(text):
                        self.unguarded.add(field)
                    elif default_guard and mname == "__init__" \
                            and field != default_guard \
                            and ctor not in _SYNC_CTORS:
                        self.guards.setdefault(field, default_guard)
                        self.guard_lines.setdefault(field, n.lineno)
        self.unguarded -= set(self.guards)
        for f in self.unguarded:
            self.guards.pop(f, None)

    def _end_line(self):
        return getattr(self.node, "end_lineno", self.node.lineno)

    def thread_reachable(self):
        """Method names reachable from the thread entry points via
        self.m() calls (the intra-class call graph)."""
        reach = set(self.entries)
        changed = True
        while changed:
            changed = False
            for name in list(reach):
                mnode = self.methods.get(name)
                if mnode is None:
                    continue
                for n in ast.walk(mnode):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and isinstance(n.func.value, ast.Name) \
                            and n.func.value.id == "self" \
                            and n.func.attr in self.methods \
                            and n.func.attr not in reach:
                        reach.add(n.func.attr)
                        changed = True
        return reach


def _classes(module):
    cache = getattr(module, "_thread_classes", None)
    if cache is not None:
        return cache
    cache = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            cache.append(ClassInfo(module, node))
    # `target=self._run` thread creations can appear anywhere in the
    # module (even another class/function); attribute them by method
    # name to every class defining that method
    targets = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target" and \
                        isinstance(kw.value, ast.Attribute) and \
                        isinstance(kw.value.value, ast.Name) and \
                        kw.value.value.id == "self":
                    targets.add(kw.value.attr)
    for ci in cache:
        ci.entries |= {t for t in targets if t in ci.methods}
    module._thread_classes = cache
    return cache


def _held_locks_walk(method, visit, initial_held=frozenset()):
    """Walk ``method``'s body tracking the set of self.<lock> names
    held via `with self.<lock>:` blocks; calls visit(node, held) on
    every node. Nested function defs inherit the held set at their
    definition point only if they are immediately-invoked — otherwise
    they run later on an unknown thread, so they get an empty held set
    (conservative for closures handed to Thread(target=...))."""

    def lock_names(withnode):
        names = []
        for item in withnode.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and \
                    isinstance(e.value, ast.Name) and e.value.id == "self":
                names.append(e.attr)
        return names

    def walk(node, held):
        visit(node, held)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | set(lock_names(node))
            for item in node.items:
                walk(item.context_expr, held)
                if item.optional_vars:
                    walk(item.optional_vars, held)
            for b in node.body:
                walk(b, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not method:
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            for b in body:
                walk(b, frozenset())
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for b in method.body:
        walk(b, frozenset(initial_held))


@rule("SPK201", "lock-discipline", SEVERITY_ERROR)
def lock_discipline(module, ctx):
    """Guarded field accessed outside its `with <lock>:` block in a
    method reachable from a thread entry point — two threads can be in
    here at once, so this is a data race on the annotated field."""
    yield from _guard_findings(module, reachable_only=True,
                               fn=lock_discipline)


@rule("SPK202", "lock-discipline-main", SEVERITY_WARN)
def lock_discipline_main(module, ctx):
    """Guarded field accessed outside its lock in a method NOT on any
    thread path — the main-thread half of the same race (the other
    thread can still interleave). __init__/__del__ are exempt: the
    object isn't shared yet/anymore."""
    yield from _guard_findings(module, reachable_only=False,
                               fn=lock_discipline_main)


def _guard_findings(module, reachable_only, fn):
    for ci in _classes(module):
        if not ci.guards:
            continue
        reach = ci.thread_reachable()
        for mname, mnode in ci.methods.items():
            if mname in ("__init__", "__del__"):
                continue
            in_reach = mname in reach
            if reachable_only != in_reach:
                continue
            hits = []

            def visit(node, held, _hits=hits, _ci=ci):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and \
                        node.attr in _ci.guards and \
                        _ci.guards[node.attr] not in held:
                    _hits.append((node, node.attr,
                                  _ci.guards[node.attr], "field"))
                # calling a `# spk: holds=<lock>` helper without the
                # lock breaks its contract just like a naked access
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in _ci.holds and \
                        _ci.holds[node.func.attr] not in held:
                    _hits.append((node, node.func.attr,
                                  _ci.holds[node.func.attr], "holds"))

            # `# spk: holds=<lock>` on the def line: a private helper
            # whose contract is "only called with <lock> held" — the
            # checker trusts the annotation and verifies the callers
            # (they must wrap the call in `with self.<lock>:`)
            held0 = set()
            hm = _HOLDS_RE.search(module.line_text(mnode.lineno))
            if hm:
                held0.add(hm.group(1))
            _held_locks_walk(mnode, visit, initial_held=held0)
            for node, what, lock, kind in hits:
                where = "thread-reachable " if reachable_only else ""
                noun = "field" if kind == "field" else \
                    "lock-requiring helper"
                verb = "accessed" if kind == "field" else "called"
                yield make_finding(
                    fn, module,
                    f"{noun} `{what}` (guarded-by `{lock}`) "
                    f"{verb} without holding `self.{lock}` in "
                    f"{where}method `{ci.name}.{mname}`",
                    node=node, symbol=f"{ci.name}.{mname}")


@rule("SPK203", "stale-guard-annotation", SEVERITY_WARN)
def stale_guard_annotation(module, ctx):
    """A guarded-by annotation names a lock attribute the class never
    creates (threading.Lock/RLock/Condition assignment) — either the
    lock was renamed/removed or the annotation should be narrowed
    away."""
    for ci in _classes(module):
        for field, lock in sorted(ci.guards.items()):
            if lock not in ci.locks:
                yield make_finding(
                    stale_guard_annotation, module,
                    f"field `{field}` is guarded-by `{lock}` but "
                    f"`{ci.name}` never creates `self.{lock}` as a "
                    "Lock/RLock/Condition",
                    line=ci.guard_lines.get(field, ci.node.lineno),
                    symbol=f"{ci.name}")


@rule("SPK204", "unannotated-shared-write", SEVERITY_WARN)
def unannotated_shared_write(module, ctx):
    """A field written both from a thread-reachable method and from a
    non-thread method, with no guarded-by annotation: the checker can't
    prove anything about it, and that pattern is exactly how the
    watchdog's `_last` race looked. Annotate it (and lock the
    accesses) or mark it `# spk: unguarded` with a reason."""
    for ci in _classes(module):
        if not ci.entries:
            continue
        reach = ci.thread_reachable()
        writes_in, writes_out = {}, {}
        for mname, mnode in ci.methods.items():
            if mname in ("__init__", "__del__"):
                continue
            sink = writes_in if mname in reach else writes_out
            for n in ast.walk(mnode):
                targets = []
                if isinstance(n, ast.Assign):
                    targets = n.targets
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    targets = [n.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        sink.setdefault(t.attr, n)
        for field in sorted(set(writes_in) & set(writes_out)):
            if field in ci.guards or field in ci.unguarded \
                    or field in ci.sync_fields:
                continue
            node = writes_in[field]
            yield make_finding(
                unannotated_shared_write, module,
                f"field `{field}` of `{ci.name}` is written both from "
                "thread-reachable and main-thread methods with no "
                "guarded-by annotation — annotate it (spk: guarded-by="
                "<lock>) or mark it `spk: unguarded` with a reason",
                node=node, symbol=f"{ci.name}")
