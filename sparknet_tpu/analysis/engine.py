"""The checker framework under `sparknet lint`.

One pass parses every target file into a :class:`Module` (source, AST,
inline suppressions); registered rules then visit each module and yield
:class:`Finding`s. The engine owns everything rule-independent:

  * per-line ``# spk: disable=CODE[,CODE]`` (and bare ``disable``)
    suppressions, plus file-level ``# spk: disable-file=CODE``
  * stable fingerprints — code + path + enclosing symbol + message
    (never the line number), so a committed baseline survives edits
    above a finding
  * rule registry + severity ("error" blocks, "warn" informs; --strict
    promotes everything)

Rules are plain functions ``rule(module, ctx) -> iterable[Finding]``
registered with :func:`rule`; ``ctx`` is the :class:`LintContext`
holding cross-module summaries (module-level string constants for axis
resolution, collective-helper signatures) built before any rule runs.

No jax imports anywhere in this package: the linter must run on hosts
with no accelerator stack at all.
"""

import ast
import hashlib
import os
import re
import tokenize


SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"

_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist",
              "node_modules", ".tox", ".venv"}

_SUPPRESS_RE = re.compile(
    r"#\s*spk:\s*disable(?:-file)?\s*(?:=\s*([A-Za-z0-9_,\s]+))?")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*spk:\s*disable-file\s*(?:=\s*([A-Za-z0-9_,\s]+))?")

ALL = "*"


class Finding:
    """One diagnostic: a rule code anchored to a file/line, with the
    enclosing symbol (function/class qualname) carried for baseline
    fingerprinting."""

    __slots__ = ("code", "message", "path", "line", "col", "severity",
                 "symbol", "rule_name", "_occurrence")

    def __init__(self, code, message, path, line, col=0,
                 severity=SEVERITY_ERROR, symbol="", rule_name=""):
        self.code = code
        self.message = message
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.severity = severity
        self.symbol = symbol
        self.rule_name = rule_name
        self._occurrence = 0            # disambiguates identical findings

    def fingerprint(self):
        """Stable identity for baseline matching: everything but the
        line/col — and with digit runs normalized out of the message,
        since some messages cite other lines ("consumed at line N") —
        so edits above the finding don't invalidate the baseline entry.
        Identical (code, path, symbol, message) repeats are
        disambiguated by an occurrence index in line order (set by the
        engine)."""
        h = hashlib.sha256()
        msg = re.sub(r"\d+", "#", self.message)
        for part in (self.code, self.path, self.symbol, msg,
                     str(self._occurrence)):
            h.update(part.encode("utf-8", "replace"))
            h.update(b"\0")
        return h.hexdigest()[:16]

    def sort_key(self):
        return (self.path, self.line, self.col, self.code, self.message)

    def render(self):
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.code} {self.severity}: {self.message}")

    def to_dict(self):
        return {"code": self.code, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "symbol": self.symbol, "message": self.message,
                "rule": self.rule_name,
                "fingerprint": self.fingerprint()}

    def __repr__(self):
        return f"<Finding {self.render()}>"


class Module:
    """One parsed source file: AST + the comment-derived metadata rules
    need (suppressions, per-line raw text for annotation comments)."""

    def __init__(self, path, relpath, source, tree):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self._suppress = None           # line -> set of codes (or ALL)
        self._suppress_file = None      # set of codes (or ALL)

    @classmethod
    def load(cls, path, root):
        with tokenize.open(path) as f:   # honors coding: declarations
            source = f.read()
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        tree = ast.parse(source, filename=path)
        return cls(path, relpath, source, tree)

    def _scan_suppressions(self):
        per_line, whole = {}, set()
        for i, text in enumerate(self.lines, start=1):
            if "spk:" not in text:
                continue
            fm = _SUPPRESS_FILE_RE.search(text)
            if fm:
                codes = _parse_codes(fm.group(1))
                whole |= codes
                continue
            m = _SUPPRESS_RE.search(text)
            if m:
                per_line.setdefault(i, set()).update(
                    _parse_codes(m.group(1)))
        self._suppress, self._suppress_file = per_line, whole

    def suppressed(self, code, line):
        """Is ``code`` suppressed at ``line`` (inline or file-level)?"""
        if self._suppress is None:
            self._scan_suppressions()
        if ALL in self._suppress_file or code in self._suppress_file:
            return True
        codes = self._suppress.get(line)
        return bool(codes) and (ALL in codes or code in codes)

    def line_text(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


def _parse_codes(group):
    if not group or not group.strip():
        return {ALL}
    return {c.strip().upper() for c in group.split(",") if c.strip()}


# -- rule registry ----------------------------------------------------------

_RULES = []
ALL_CODES = {}


def rule(code, name, severity=SEVERITY_ERROR):
    """Register a rule function ``fn(module, ctx) -> iter[Finding]``.
    The decorator stamps code/name/severity so the rule only yields
    (message, node-or-line[, col]) tuples or full Findings."""
    def deco(fn):
        fn.code, fn.rule_name, fn.severity = code, name, severity
        _RULES.append(fn)
        ALL_CODES[code] = (name, severity, (fn.__doc__ or "").strip())
        return fn
    return deco


def all_rules():
    _load_rules()
    return list(_RULES)


_loaded = False


def _load_rules():
    global _loaded
    if _loaded:
        return
    _loaded = True
    # engine-emitted, not a visitor rule: a file that does not parse
    # cannot be checked at all, which is itself a finding
    ALL_CODES.setdefault(
        "SPK001", ("parse-error", SEVERITY_ERROR,
                   "File does not parse; nothing else can be checked."))
    from . import (jax_rules, thread_rules, protocol_rules,  # noqa: F401
                   metrics_rules)                            # (registration)


# -- helpers rules share ----------------------------------------------------

def make_finding(fn, module, message, node=None, line=None, col=None,
                 symbol="", severity=None):
    """Build a Finding for rule ``fn`` anchored at ``node`` (or an
    explicit line/col)."""
    if node is not None:
        line = getattr(node, "lineno", line or 1)
        col = getattr(node, "col_offset", col or 0)
    return Finding(fn.code, message, module.relpath, line or 1, col or 0,
                   severity=severity or fn.severity, symbol=symbol,
                   rule_name=fn.rule_name)


def qualname_of(stack):
    """Dotted symbol for a scope stack of ast nodes (class/function
    names, '<lambda>' for lambdas)."""
    parts = []
    for n in stack:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            parts.append(n.name)
        elif isinstance(n, ast.Lambda):
            parts.append("<lambda>")
    return ".".join(parts)


class LintContext:
    """Cross-module facts built before any rule runs.

    str_constants: UPPERCASE module-level string assignments from every
        scanned module (``DATA_AXIS = "data"``), keyed by bare name —
        the linter's one-level constant propagation for axis names.
        Name collisions keep the first value seen and mark the name
        ambiguous (resolution then declines to answer).
    axis_helpers: {function basename: set of parameter indices that the
        function forwards as a collective axis argument} — lets a call
        like ``masked_consensus(tree, valid, "data")`` be checked
        against the caller's declared mesh axes even though the psum
        lives in another module (resilience/elastic.py).
    project: the :class:`~.project.ProjectIndex` — module graph,
        class/method + call-edge resolution, expression-fragment
        constant propagation, event/kind registries, exit table.
        The SPK2xx/3xx/4xx cross-module families query this.
    """

    def __init__(self, modules):
        self.modules = modules
        self.str_constants = {}
        self._ambiguous = set()
        self.axis_helpers = {}
        for m in modules:
            self._collect_constants(m)
        _load_rules()
        from .jax_rules import collect_axis_helpers
        from .project import ProjectIndex
        for m in modules:
            for name, idxs in collect_axis_helpers(m).items():
                self.axis_helpers.setdefault(name, set()).update(idxs)
        self.project = ProjectIndex(modules)

    def _collect_constants(self, module):
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                name = node.targets[0].id
                if not name.isupper():
                    continue
                if name in self.str_constants and \
                        self.str_constants[name] != node.value.value:
                    self._ambiguous.add(name)
                else:
                    self.str_constants.setdefault(name, node.value.value)

    def resolve_str_constant(self, name):
        if name in self._ambiguous:
            return None
        return self.str_constants.get(name)


def _lint_module(module, ctx, select):
    """All unsuppressed findings for one module — the per-file unit of
    work the cache stores and the worker pool executes."""
    out = []
    for fn in all_rules():
        if select and fn.code not in select:
            continue
        try:
            found = list(fn(module, ctx))
        except RecursionError:              # pathological nesting: skip
            continue                        # the rule, not the run
        for f in found:
            if not module.suppressed(f.code, f.line):
                out.append(f)
    return out


# fork-pool plumbing: children inherit this via fork, so the parsed
# modules and the ProjectIndex are shared copy-on-write instead of
# pickled per task
_POOL_STATE = {}


def _pool_lint(i):
    ctx, select = _POOL_STATE["ctx"], _POOL_STATE["select"]
    return i, _lint_module(_POOL_STATE["modules"][i], ctx, select)


def _analysis_version():
    """Hash of this package's sources — cached results die with any
    rule change."""
    h = hashlib.sha256()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(pkg)):
        if fn.endswith(".py"):
            with open(os.path.join(pkg, fn), "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:16]


class LintEngine:
    """Parse targets, run every registered rule (optionally across a
    worker pool, with a content-hash result cache), apply suppressions,
    stamp occurrence indices for stable fingerprints."""

    def __init__(self, select=None, exclude=None, jobs=1,
                 cache_path=None):
        self.select = set(select) if select else None
        self.exclude = list(exclude) if exclude else []
        self.jobs = max(1, int(jobs or 1))
        self.cache_path = cache_path

    def _excluded(self, path):
        norm = path.replace(os.sep, "/")
        import fnmatch
        for pat in self.exclude:
            if pat in norm or fnmatch.fnmatch(norm, pat) or \
                    any(fnmatch.fnmatch(part, pat)
                        for part in norm.split("/")):
                return True
        return False

    def collect_files(self, paths):
        files = []
        for p in paths:
            if os.path.isfile(p):
                if not self._excluded(p):
                    files.append(p)
                continue
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith(".")
                                     and not self._excluded(
                                         os.path.join(dirpath, d)))
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not self._excluded(full):
                        files.append(full)
        return files

    # -- result cache ------------------------------------------------------

    def _load_cache(self):
        import json
        try:
            with open(self.cache_path) as f:
                data = json.load(f)
            if isinstance(data, dict) and \
                    isinstance(data.get("entries"), dict):
                return data["entries"]
        except (OSError, ValueError):
            pass
        return {}

    def _save_cache(self, entries):
        import json
        tmp = self.cache_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"entries": entries}, f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass

    @staticmethod
    def _file_key(module, ctx_fp):
        h = hashlib.sha256()
        h.update(module.source.encode("utf-8", "replace"))
        h.update(ctx_fp.encode())
        return h.hexdigest()[:24]

    @staticmethod
    def _finding_from_dict(d):
        return Finding(d["code"], d["message"], d["path"], d["line"],
                       d.get("col", 0), severity=d.get("severity",
                                                       SEVERITY_ERROR),
                       symbol=d.get("symbol", ""),
                       rule_name=d.get("rule", ""))

    def run(self, paths, root=None):
        """Lint ``paths`` (files or directories). Returns the sorted,
        unsuppressed findings. Unparseable files become SPK001 findings
        rather than crashes — a file that won't parse can't be checked,
        which is itself a finding."""
        root = root or os.getcwd()
        modules, findings = [], []
        for path in self.collect_files(paths):
            try:
                modules.append(Module.load(path, root))
            except (SyntaxError, ValueError, UnicodeDecodeError) as e:
                line = getattr(e, "lineno", 1) or 1
                findings.append(Finding(
                    "SPK001", f"file does not parse: {e}",
                    os.path.relpath(path, root).replace(os.sep, "/"),
                    line, severity=SEVERITY_ERROR,
                    symbol="<module>", rule_name="parse-error"))
        ctx = LintContext(modules)

        # cache key: file content + every cross-module input a rule can
        # see (project summaries, rule sources, selection) — editing one
        # file invalidates others only when a shared summary changed
        cache, ctx_fp = None, ""
        if self.cache_path:
            ctx_fp = "|".join([_analysis_version(),
                               ctx.project.fingerprint(),
                               ",".join(sorted(self.select or ()))])
            cache = self._load_cache()
        pending = []
        for i, module in enumerate(modules):
            if cache is not None:
                key = self._file_key(module, ctx_fp)
                hit = cache.get(module.relpath)
                if hit and hit.get("key") == key:
                    findings.extend(self._finding_from_dict(d)
                                    for d in hit.get("findings", ()))
                    continue
            pending.append(i)

        results = None
        if self.jobs > 1 and len(pending) > 1:
            results = self._run_pool(modules, ctx, pending)
        if results is None:
            results = {i: _lint_module(modules[i], ctx, self.select)
                       for i in pending}
        for i in pending:
            found = results.get(i, [])
            findings.extend(found)
            if cache is not None:
                cache[modules[i].relpath] = {
                    "key": self._file_key(modules[i], ctx_fp),
                    "findings": [f.to_dict() for f in found]}
        if cache is not None:
            live = {m.relpath for m in modules}
            self._save_cache({k: v for k, v in cache.items()
                              if k in live})

        findings.sort(key=Finding.sort_key)
        seen = {}
        for f in findings:
            # same normalization as Finding.fingerprint, so findings
            # that differ only in a cited line number still get
            # distinct occurrence indices
            key = (f.code, f.path, f.symbol,
                   re.sub(r"\d+", "#", f.message))
            f._occurrence = seen.get(key, 0)
            seen[key] = f._occurrence + 1
        return findings

    def _run_pool(self, modules, ctx, pending):
        """Fan pending modules over a fork pool; the children inherit
        the parsed modules and ProjectIndex copy-on-write. Returns
        {index: findings} or None when fork isn't available."""
        import multiprocessing
        try:
            mp = multiprocessing.get_context("fork")
        except ValueError:
            return None
        _POOL_STATE.update(modules=modules, ctx=ctx, select=self.select)
        try:
            with mp.Pool(min(self.jobs, len(pending))) as pool:
                return dict(pool.map(_pool_lint, pending))
        except Exception:
            return None                     # fall back to serial
        finally:
            _POOL_STATE.clear()


def lint_paths(paths, root=None, select=None):
    """Convenience wrapper: lint and return sorted findings."""
    return LintEngine(select=select).run(paths, root=root)
