"""`sparknet lint` — JAX-aware static analysis for the codebase's own
bug classes.

The runtime machinery of PRs 1-4 (obs, resilience, elastic rounds)
catches failures while they happen; this subsystem catches the bug
classes this codebase is most exposed to *before* anything runs, in the
spirit of always-on program-analysis platforms (Tricorder, Sadowski et
al., ICSE 2015): build the analyzers once, run them on every commit.

Two analyzer families over a shared AST engine (engine.py):

  jax_rules.py     SPK1xx — compiled-code hazards: host syncs reachable
                   from jit/pmap/shard_map roots (which would erase the
                   local-SGD comms savings the SparkNet design exists
                   to deliver), recompile hazards, PRNG-key reuse,
                   collective axis-name mismatches, missing buffer
                   donation in update loops.
  thread_rules.py  SPK2xx — lock discipline for the threaded host side
                   (watchdog, metrics, prefetch, monitor): fields
                   annotated ``# spk: guarded-by=<lock>`` are flagged
                   when read/written outside a ``with <lock>:`` block
                   in any method reachable from a thread entry point.

Findings can be suppressed inline (``# spk: disable=CODE``) or accepted
into a committed baseline file with a written justification
(baseline.py), so legacy findings never block CI while new ones do.
CLI: ``sparknet lint [--strict] [paths...]`` (cli.py) — wired into
scripts/lint.sh / scripts/ci.sh / .github/workflows/ci.yml.

Import discipline: nothing in this package imports jax (or any other
heavyweight dependency) — linting runs on checkout hosts with no
accelerator stack, exactly like ``sparknet monitor``.
"""

from .engine import (Finding, Module, LintEngine, lint_paths,
                     all_rules, ALL_CODES)
from .baseline import Baseline

__all__ = ["Finding", "Module", "LintEngine", "lint_paths",
           "all_rules", "ALL_CODES", "Baseline"]
