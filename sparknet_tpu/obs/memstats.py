"""Memory & compile-cache accounting: why did step time regress?

The two silent step-time killers on XLA backends are recompiles (a shape
change retraces mid-run) and memory growth (live arrays accumulating
until allocator pressure or an OOM). stepstats.py already *detects*
recompiles from the jitted callable's cache growth; this module samples
the surrounding state on the same cadence so a regression is
explainable from the metrics stream alone:

  live_arrays       count + total bytes of every jax.Array the process
                    holds (leaks show up as a monotonic climb)
  device memory     bytes_in_use / peak_bytes_in_use where the backend
                    reports them (TPU/GPU; absent on CPU)
  compile cache     executable count across the solver's tracked jitted
                    fns — growth beyond the expected warmup is the
                    recompile storm stepstats flags per event
  host rss          ru_maxrss, the host-side twin (prefetch buffers,
                    snapshot staging)

Emitted as ``memstats`` events next to each sampled ``step``/round, so
`sparknet report` and `sparknet monitor` can show memory next to step
time.
"""


def live_array_stats():
    """(count, total_bytes) over the process's live jax arrays; (None,
    None) when jax can't enumerate them (old vintage / torn-down
    backend)."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:
        return None, None
    total = 0
    for a in arrs:
        try:
            total += int(a.nbytes)
        except Exception:
            pass
    return len(arrs), total


def host_rss_bytes():
    """Peak host RSS in bytes (linux ru_maxrss is KiB), or None."""
    try:
        import resource
        kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(kb) * 1024
    except Exception:
        return None


def compile_cache_size(jit_fns):
    """Total executable-cache entries across jitted callables (None when
    none expose _cache_size)."""
    total, seen = 0, False
    for fn in jit_fns or ():
        if fn is None:
            continue
        try:
            total += int(fn._cache_size())
            seen = True
        except Exception:
            continue
    return total if seen else None


class MemoryMonitor:
    """sample(it, jit_fns=...) on the solver's step-sample cadence; each
    sample emits one ``memstats`` event. Tracks peaks so flush() can
    summarize even if the JSONL tail is lost."""

    def __init__(self, sink, sample_every=1):
        self.sink = sink
        self.sample_every = max(1, int(sample_every))
        self._n = 0
        self._last_cache = None
        self.peak_live_bytes = 0
        self.samples = 0

    def sample(self, it, jit_fns=(), force=False, **extra):
        self._n += 1
        if not force and (self._n - 1) % self.sample_every:
            return None
        count, nbytes = live_array_stats()
        ev = {"iter": it}
        if count is not None:
            ev["live_arrays"] = count
            ev["live_bytes"] = nbytes
            self.peak_live_bytes = max(self.peak_live_bytes, nbytes or 0)
        from .stepstats import device_memory
        mem = device_memory()
        if mem:
            ev.update({f"hbm_{k}": v for k, v in mem.items()})
        cache = compile_cache_size(jit_fns)
        if cache is not None:
            ev["compile_cache"] = cache
            if self._last_cache is not None and cache > self._last_cache:
                ev["compile_cache_grew"] = cache - self._last_cache
            self._last_cache = cache
        rss = host_rss_bytes()
        if rss is not None:
            ev["host_rss_bytes"] = rss
        ev.update({k: v for k, v in extra.items() if v is not None})
        self.samples += 1
        if self.sink is not None:
            self.sink.log("memstats", **ev)
        return ev
