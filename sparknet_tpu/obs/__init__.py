"""Observability subsystem: tracing, step accounting, comms metering.

The SparkNet paper's central result is a communication/compute tradeoff
(tau local steps vs. broadcast/collect cost), but the reference had no
structured way to measure it — loss and timing went to glog and ad-hoc
prints (SURVEY.md section 5). This package is the measurement layer every
perf PR reports against:

  trace.py      nested span tracer (JSONL events + Chrome trace_event
                export) and the steady-state jax.profiler toggle
  stepstats.py  host-dispatch vs device-wall step accounting, recompile
                detection, p50/p95/p99 step-time histograms
  comms.py      bytes moved per sync round (ring-allreduce cost model,
                mapped to the paper's broadcast/collect model), plus
                host->device feed byte counters
  divergence.py per-sync-round worker-weight divergence measured
                on-device before the averaging pmean (the paper's tau
                drift, plus a gradient-noise-scale proxy)
  health.py     rolling anomaly detectors over the round signals —
                stragglers, loss skew, divergence trends — emitting
                structured ``health`` alarms that can arm recovery
  memstats.py   live-array/HBM/compile-cache/rss sampling so step-time
                regressions decompose into recompile vs memory pressure
  report.py     `sparknet report`: aggregate a metrics JSONL into a
                human-readable run report + machine-readable JSON
  monitor.py    `sparknet monitor`: tail a live metrics JSONL and
                render an in-place terminal summary of the run

Everything writes through one utils.metrics.MetricsLogger, so a single
JSONL stream carries spans, steps, comms, recompiles, watchdog barks,
prefetch gauges, and the training curve together.
"""

from .trace import Tracer, JaxProfiler, chrome_from_spans, export_chrome
from .stepstats import StepAccounting, percentiles, device_memory
from .comms import (CommsMeter, tree_bytes, ring_allreduce_bytes,
                    broadcast_collect_bytes, all_to_all_bytes)
from .divergence import DivergenceMeter, consensus_stats, tree_sq_dist
from .health import HealthMonitor
from .memstats import MemoryMonitor

__all__ = [
    "Tracer", "JaxProfiler", "chrome_from_spans", "export_chrome",
    "StepAccounting", "percentiles", "device_memory",
    "CommsMeter", "tree_bytes", "ring_allreduce_bytes",
    "broadcast_collect_bytes", "all_to_all_bytes",
    "DivergenceMeter", "consensus_stats", "tree_sq_dist",
    "HealthMonitor", "MemoryMonitor",
]
