"""Span tracer: nested monotonic-clock spans over the JSONL metrics
stream.

Each completed span emits one ``span`` event (name, start/duration in ms
relative to the tracer epoch, nesting depth, parent span name, thread id,
plus caller attrs). Spans nest per-thread via a thread-local stack, so a
prefetch worker's spans interleave correctly with the training loop's.
The tracer also keeps a bounded in-memory buffer of completed spans for
Chrome ``trace_event`` export — load the file in chrome://tracing or
Perfetto next to a jax.profiler device trace.

Timestamps come from the injectable time seam (resilience.seam.Clock),
on its MONOTONIC source: an NTP step or suspend/resume mid-run cannot
fold spans over each other (the same fix PR 15 applied to lease ages),
and a simulated run can hand the tracer a SimClock so spans land on the
virtual timeline the fleet merger aligns against.

``JaxProfiler`` packages the steady-state one-block device-trace toggle
that used to live inline in cli.cmd_train.
"""

import json
import os
import threading
from contextlib import contextmanager

from ..resilience.seam import WALL_CLOCK


class Tracer:
    """Nested spans over a MetricsLogger sink (sink=None -> spans still
    nest and buffer for Chrome export, nothing hits the JSONL)."""

    def __init__(self, sink=None, max_buffer=100_000, clock=None):
        self.sink = sink
        self.clock = clock if clock is not None else WALL_CLOCK
        self.t0 = self.clock.monotonic()
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._buf = []              # spk: guarded-by=_lock
        self.dropped = 0            # spk: guarded-by=_lock
        self.max_buffer = max_buffer

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextmanager
    def span(self, name, **attrs):
        """Context manager timing one phase; yields the attrs dict so the
        body can attach fields discovered mid-span (attrs["n"] = ...)."""
        st = self._stack()
        parent = st[-1] if st else None
        st.append(name)
        start = self.clock.monotonic() - self.t0
        try:
            yield attrs
        finally:
            st.pop()
            end = self.clock.monotonic() - self.t0
            rec = {"name": name, "start_ms": round(start * 1e3, 3),
                   "dur_ms": round((end - start) * 1e3, 3),
                   "depth": len(st), "parent": parent,
                   "tid": threading.get_ident()}
            rec.update(attrs)
            self._record(rec)

    def instant(self, name, **attrs):
        """A zero-duration mark (Chrome 'instant' event)."""
        rec = {"name": name,
               "start_ms": round((self.clock.monotonic() - self.t0) * 1e3, 3),
               "dur_ms": 0.0, "depth": len(self._stack()),
               "parent": self._stack()[-1] if self._stack() else None,
               "tid": threading.get_ident()}
        rec.update(attrs)
        self._record(rec)

    def _record(self, rec):
        with self._lock:
            if len(self._buf) < self.max_buffer:
                self._buf.append(rec)
            else:
                self.dropped += 1
        if self.sink is not None:
            self.sink.log("span", **rec)

    def spans(self):
        with self._lock:
            return list(self._buf)

    def export_chrome(self, path):
        """Write buffered spans as a Chrome trace_event JSON file."""
        with self._lock:
            # one consistent snapshot: buffer and its drop count
            spans, dropped = list(self._buf), self.dropped
        return export_chrome(path, spans, dropped=dropped)


def chrome_from_spans(spans, pid=None):
    """span records (start_ms/dur_ms/name/tid + attrs) -> trace_event
    'X' (complete) events, timestamps in microseconds."""
    pid = pid if pid is not None else os.getpid()
    skip = {"name", "start_ms", "dur_ms", "tid", "depth", "parent",
            "event", "t", "run"}
    evs = []
    for s in spans:
        args = {k: v for k, v in s.items() if k not in skip}
        if s.get("parent"):
            args["parent"] = s["parent"]
        evs.append({"name": str(s.get("name", "?")),
                    "ph": "X" if s.get("dur_ms", 0) else "i",
                    "ts": round(float(s.get("start_ms", 0.0)) * 1e3, 1),
                    "dur": round(float(s.get("dur_ms", 0.0)) * 1e3, 1),
                    "pid": pid, "tid": int(s.get("tid", 0)) % (1 << 31),
                    "cat": "span", "args": args})
    return evs


def export_chrome(path, spans, pid=None, dropped=0):
    """Write span records to ``path`` in Chrome trace_event format."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    doc = {"traceEvents": chrome_from_spans(spans, pid=pid),
           "displayTimeUnit": "ms"}
    if dropped:
        doc["otherData"] = {"dropped_spans": dropped}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


class JaxProfiler:
    """The steady-state one-block jax.profiler toggle (formerly inline in
    cli.cmd_train): skip the compile-heavy first block of THIS process
    (fresh start or snapshot resume alike) so the trace shows steady-state
    device time (XLA ops, HBM, infeed); runs short enough to have only one
    block trace that block."""

    def __init__(self, logdir, log=print, block_iters=100):
        self.logdir = logdir
        self.log = log or (lambda *a: None)
        self.block_iters = block_iters
        self.active = False
        self.done = False

    def maybe_start(self, blocks_done, iters_remaining):
        if not self.logdir or self.done or self.active:
            return False
        if blocks_done >= 1 or iters_remaining <= self.block_iters:
            import jax
            jax.profiler.start_trace(self.logdir)
            self.active = True
        return self.active

    def maybe_stop(self):
        if not self.active:
            return
        import jax
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        self.log(f"Wrote profiler trace to {self.logdir} "
                 "(view with tensorboard or xprof)")

    def abort(self):
        """Flush the trace of a block that raised — it's the one most
        worth looking at."""
        if self.active:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
