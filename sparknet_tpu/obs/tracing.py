"""Request-level tracing primitives for the serving fleet (ISSUE 18).

The router mints one trace id per request and propagates it to the
replica via the ``X-Sparknet-Trace`` header; the replica stamps every
batcher stage (admission, enqueue, dispatch, forward start/end,
fulfill) and echoes a compact ``X-Sparknet-Stages`` breakdown back, so
the router can close the loop with network time = total − server-
reported. Three pieces live here because BOTH the real tier
(serve/server.py, serve/fleet.py) and the simulated one
(sim/servefleet.py) use them unchanged:

TraceSampler     head sampling + always-keep-the-tail exemplars: at
                 fleet QPS the per-request emit is a metrics-file hot
                 spot, but the tail is exactly what must never be
                 sampled away — any request slower than ``tail_ms`` is
                 kept regardless of the head-sampling stride. The
                 stride is deterministic (every k-th request), so event
                 volume under load is bounded and testable.
StageReservoir   bounded per-stage latency reservoirs (a sliding
                 window of the most recent samples) feeding the
                 router's /metrics percentile snapshot and the
                 "where did the p99 go" decomposition.
BurnRateLedger   SLO error-budget accounting with multi-window burn-
                 rate alerts (the SRE-workbook recipe): page when the
                 fast pair (5m AND 1h) both burn above ``fast_x``,
                 ticket when the slow pair (1h AND 6h) both burn above
                 ``slow_x``. Windows scale by one knob so a simulated
                 fleet (sim seconds) and a smoke run exercise the same
                 code path as a week of wall clock. Time is always
                 CALLER-provided (the router's injected clock), never
                 read here — the same ledger runs real and simulated.
"""

import collections
import threading

#: the canonical per-request stage decomposition, in causal order.
#: ``net`` is router-measured (total − server-reported); the rest are
#: replica-side batcher stamps. Sum ≈ router total (the residual is
#: handler overhead outside the stamped region).
STAGES = ("net", "queue", "batch", "infer", "fulfill")

TRACE_HEADER = "X-Sparknet-Trace"
STAGES_HEADER = "X-Sparknet-Stages"


def encode_stages(stages):
    """Stage breakdown dict -> the compact header value
    (``total=12.3;queue=4.5;infer=7.1`` — ms, 3 decimals, Nones
    dropped)."""
    parts = []
    for k, v in stages.items():
        if v is None:
            continue
        parts.append(f"{k}={round(float(v), 3):g}")
    return ";".join(parts)


def decode_stages(text):
    """Header value -> {stage: ms} (None on anything unparseable — a
    replica without tracing simply reports no breakdown)."""
    if not text:
        return None
    out = {}
    for part in str(text).split(";"):
        if "=" not in part:
            continue
        k, _, v = part.partition("=")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            continue
    return out or None


class TraceSampler:
    """Head sampling with unconditional tail exemplars.

    ``sample`` is the kept fraction (1.0 = keep everything, the
    default, so tests and smoke keep today's behavior); ``tail_ms``
    is the exemplar threshold — a request at least that slow is ALWAYS
    kept (verdict "tail"), because the tail is the part of the
    distribution sampling must never erase."""
    # spk: guarded-by-default=_lock

    def __init__(self, sample=1.0, tail_ms=None):
        # spk: unguarded (set once in __init__, immutable after)
        self.sample = min(1.0, max(0.0, float(sample)))
        self.tail_ms = None if tail_ms is None else float(tail_ms)  # spk: unguarded (immutable)
        self._stride = (0 if self.sample <= 0  # spk: unguarded (immutable)
                        else max(1, int(round(1.0 / self.sample))))
        self._lock = threading.Lock()
        self._n = 0

    def decide(self, latency_ms):          # spk: thread-entry
        """-> "tail" | "head" | None (drop). Deterministic stride head
        sampling; the tail threshold wins over the stride."""
        if self.tail_ms is not None and latency_ms is not None \
                and float(latency_ms) >= self.tail_ms:
            return "tail"
        if self._stride == 0:
            return None
        with self._lock:
            self._n += 1
            keep = self._n % self._stride == 0
        return "head" if keep else None


class StageReservoir:
    """Sliding-window per-stage latency samples for percentile
    snapshots (``cap`` most recent per stage — serving wants the
    recent window, not the run mean)."""
    # spk: guarded-by-default=_lock

    def __init__(self, cap=4096):
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._samples = {}                # spk: guarded-by=_lock

    def add(self, stages):                # spk: thread-entry
        with self._lock:
            for k, v in stages.items():
                if v is None:
                    continue
                d = self._samples.get(k)
                if d is None:
                    d = self._samples[k] = collections.deque(
                        maxlen=self.cap)
                d.append(float(v))

    def snapshot(self):                   # spk: thread-entry
        """{stage: {"p50","p95","p99","n"}} over the current window."""
        from .stepstats import percentiles
        with self._lock:
            samples = {k: list(d) for k, d in self._samples.items()}
        out = {}
        for k, vals in sorted(samples.items()):
            if not vals:
                continue
            out[k] = {q: round(v, 3)
                      for q, v in percentiles(vals).items()}
            out[k]["n"] = len(vals)
        return out

    def p99(self):                        # spk: thread-entry
        return {k: rec["p99"] for k, rec in self.snapshot().items()}


class BurnRateLedger:
    """Error-budget ledger with multi-window burn-rate alerts.

    A request is GOOD when it met the SLO (the caller decides: 200
    within ``slo_ms``). Burn rate over a window = bad_fraction /
    (1 - objective): x1 spends the budget exactly at the objective's
    allowed pace, x14.4 exhausts a 30-day budget in ~2 days. Alerts
    follow the two-window rule — both the long window (real spend) and
    its short confirmation window (still burning NOW) must breach:

      page    fast pair  (fast_s = 5m, 1h)  both > fast_x (14.4)
      ticket  slow pair  (slow_s = 1h, 6h)  both > slow_x (6.0)

    ``scale`` multiplies every window so sim seconds and smoke runs
    drive the same ladder. Events are bucketed into bins of the
    shortest window / 30, so memory is bounded at any QPS."""
    # spk: guarded-by-default=_lock

    def __init__(self, slo_ms=500.0, objective=0.999,
                 fast_s=(300.0, 3600.0), slow_s=(3600.0, 21600.0),
                 fast_x=14.4, slow_x=6.0, scale=1.0, metrics=None,
                 log_fn=None):
        self.slo_ms = float(slo_ms)  # spk: unguarded (immutable)
        self.objective = min(0.999999, max(0.0, float(objective)))  # spk: unguarded (immutable)
        s = float(scale)
        self.fast_s = (float(fast_s[0]) * s, float(fast_s[1]) * s)  # spk: unguarded (immutable)
        self.slow_s = (float(slow_s[0]) * s, float(slow_s[1]) * s)  # spk: unguarded (immutable)
        self.fast_x = float(fast_x)  # spk: unguarded (immutable)
        self.slow_x = float(slow_x)  # spk: unguarded (immutable)
        self.metrics = metrics    # spk: unguarded (append-only sink)
        self.log = log_fn or (lambda *a: None)  # spk: unguarded (immutable)
        self._bin_s = max(self.fast_s[0] / 30.0, 1e-6)  # spk: unguarded (immutable)
        self._lock = threading.Lock()
        self._bins = collections.deque()  # spk: guarded-by=_lock
        self._good = 0                    # spk: guarded-by=_lock
        self._bad = 0                     # spk: guarded-by=_lock
        self._alert = None                # spk: guarded-by=_lock
        self.last = None                  # spk: guarded-by=_lock

    def good(self, code, latency_ms):
        """The SLI: did this response meet the latency SLO?"""
        return code == 200 and latency_ms is not None \
            and float(latency_ms) <= self.slo_ms

    def record(self, now, good):          # spk: thread-entry
        """One terminal response at caller-clock time ``now``."""
        b = int(now / self._bin_s)
        with self._lock:
            if self._bins and self._bins[-1][0] == b:
                rec = self._bins[-1]
            else:
                rec = [b, 0, 0]           # [bin, total, bad]
                self._bins.append(rec)
            rec[1] += 1
            if not good:
                rec[2] += 1
            if good:
                self._good += 1
            else:
                self._bad += 1
            # prune past the longest window (+1 bin of slack)
            horizon = b - int(self.slow_s[1] / self._bin_s) - 1
            while self._bins and self._bins[0][0] < horizon:
                self._bins.popleft()

    def _burn(self, bins, now, window_s):
        lo = int((now - window_s) / self._bin_s)
        total = bad = 0
        for b, t, n_bad in bins:
            if b >= lo:
                total += t
                bad += n_bad
        if total == 0:
            return None
        return (bad / total) / (1.0 - self.objective)

    def evaluate(self, now):              # spk: thread-entry
        """Window-loop entry: burn rates, alert verdict, budget left.
        Emits one ``slo_burn`` event per evaluation (bounded by the
        window cadence, not QPS) and logs alert transitions."""
        with self._lock:
            bins = [tuple(b) for b in self._bins]
            good, bad = self._good, self._bad
            prev = self._alert
        fast = self._burn(bins, now, self.fast_s[0])
        fast_long = self._burn(bins, now, self.fast_s[1])
        slow = self._burn(bins, now, self.slow_s[0])
        slow_long = self._burn(bins, now, self.slow_s[1])
        alert = None
        if fast is not None and fast_long is not None \
                and fast > self.fast_x and fast_long > self.fast_x:
            alert = "page"
        elif slow is not None and slow_long is not None \
                and slow > self.slow_x and slow_long > self.slow_x:
            alert = "ticket"
        # budget left over the slow long window: 1 - spend/allowance
        lo = int((now - self.slow_s[1]) / self._bin_s)
        total = sum(t for b, t, _ in bins if b >= lo)
        w_bad = sum(n for b, _, n in bins if b >= lo)
        allowed = total * (1.0 - self.objective)
        budget = None if total == 0 else \
            max(0.0, min(1.0, 1.0 - (w_bad / allowed if allowed > 0
                                     else (1.0 if w_bad else 0.0))))
        out = {"alert": alert,
               "fast": None if fast is None else round(fast, 3),
               "fast_long": (None if fast_long is None
                             else round(fast_long, 3)),
               "slow": None if slow is None else round(slow, 3),
               "slow_long": (None if slow_long is None
                             else round(slow_long, 3)),
               "budget_left": (None if budget is None
                               else round(budget, 4)),
               "good": good, "bad": bad}
        with self._lock:
            self._alert = alert
            self.last = dict(out)
        if alert != prev:
            self.log(f"slo: burn-rate alert -> {alert or 'clear'} "
                     f"(fast x{out['fast']}/{out['fast_long']}, "
                     f"slow x{out['slow']}/{out['slow_long']}, "
                     f"budget left {out['budget_left']})")
        if self.metrics is not None and (good or bad):
            self.metrics.log("slo_burn", alert=alert,
                             fast=out["fast"],
                             fast_long=out["fast_long"],
                             slow=out["slow"],
                             slow_long=out["slow_long"],
                             budget_left=out["budget_left"],
                             good=good, bad=bad)
        return out

    def snapshot(self):                   # spk: thread-entry
        """The last evaluate() verdict (for /healthz), or None."""
        with self._lock:
            return None if self.last is None else dict(self.last)
