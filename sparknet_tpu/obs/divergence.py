"""Worker-weight divergence: the paper's tau knob, measured.

SparkNet's central tradeoff is sync interval tau: more local steps per
round cut communication but let per-worker replicas drift apart before
the average (PAPER.md; Stich's local-SGD analysis bounds exactly this
drift term). The repo could *set* tau but never *see* the drift — this
module measures it where it is cheap: INSIDE the compiled sync round,
before the averaging pmean, so the cost is one elementwise pass over the
tree plus a handful of scalar collectives, never a host gather of
weights.

Two halves:

  consensus_stats / tree_sq_dist   pure jnp, called inside shard_map by
      the sharded solvers: average the tree across the axis (the sync
      the solver was doing anyway), then measure each worker's squared
      L2 distance to that consensus — total, per top-level key (layer),
      per worker (an all_gather of ONE scalar each).
  DivergenceMeter   host side: takes the fetched aux dict once per
      sampled round, emits a ``divergence`` JSONL event (mean/max/
      per-worker distance, top offender layers, update norm, a
      gradient-noise-scale proxy) and returns the summary for the
      health detectors (obs/health.py).

The gradient-noise-scale proxy follows McCandlish et al.'s B_simple
estimator shape: with N workers' updates u_w around consensus u,
``gns_proxy = N/(N-1) * E||u_w - u||^2 / ||u||^2`` — the between-worker
update variance in units of the squared mean update. It is a *proxy*
(per-worker updates are tau-step paths, not single gradients); its value
is the trend: rising means the per-round average is absorbing more noise
relative to signal, i.e. tau (or lr) is too large for this phase of
training.
"""

import math

import numpy as np


def _sq_sum(tree):
    """Sum of squares over every leaf, accumulated in f32."""
    import jax
    import jax.numpy as jnp
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0)
    total = jnp.float32(0)
    for leaf in leaves:
        total = total + jnp.sum(
            jnp.square(jnp.asarray(leaf, jnp.float32)))
    return total


def tree_sq_dist(a, b):
    """Squared L2 distance between two same-structure trees, grouped by
    top-level key (the per-layer param dict) -> ({key: sq}, total_sq).
    Non-dict trees are treated as one group named "all"."""
    import jax
    import jax.numpy as jnp

    def diff(x, y):
        return jnp.asarray(x, jnp.float32) - jnp.asarray(y, jnp.float32)

    if not isinstance(a, dict):
        s = _sq_sum(jax.tree_util.tree_map(diff, a, b))
        return {"all": s}, s
    per, total = {}, None
    for k in a:
        s = _sq_sum(jax.tree_util.tree_map(diff, a[k], b[k]))
        per[k] = s
        total = s if total is None else total + s
    if total is None:
        total = jnp.float32(0)
    return per, total


def consensus_stats(tree, axis):
    """INSIDE shard_map over ``axis``: average ``tree`` across workers
    and measure each worker's drift from that consensus.

    Returns (consensus, aux) where consensus == pmean(tree, axis) — the
    sync the caller was going to do anyway, so the extra cost is the
    squared-distance pass plus scalar collectives — and aux holds
    replicated f32 scalars/vectors safe for a P() out_spec:

      div_mean_sq    E_w ||tree_w - consensus||^2
      div_max_sq     max_w ...
      div_worker_sq  (N,) all_gather of each worker's squared distance
      layer_div_sq   {layer: E_w per-layer squared distance}
    """
    import jax
    consensus = jax.lax.pmean(tree, axis)
    per_layer, local_sq = tree_sq_dist(tree, consensus)
    aux = {
        "div_mean_sq": jax.lax.pmean(local_sq, axis),
        "div_max_sq": jax.lax.pmax(local_sq, axis),
        "div_worker_sq": jax.lax.all_gather(local_sq, axis),
        "layer_div_sq": {k: jax.lax.pmean(v, axis)
                         for k, v in per_layer.items()},
    }
    return consensus, aux


def gather_worker_scalar(x, axis):
    """all_gather one replicated-output scalar per worker along ``axis``
    (inside shard_map) — the per-worker loss vector costs N floats."""
    import jax
    import jax.numpy as jnp
    return jax.lax.all_gather(jnp.asarray(x, jnp.float32), axis)


class DivergenceMeter:
    """Host side: turn one sync round's fetched aux dict into a
    ``divergence`` event + a plain-float summary for the detectors.

    kind: what the distances are over — "params" (local SGD: tau-step
    weight drift) or "grads" (per-step DP: gradient noise across the
    batch shards). ``ref_sq`` in the aux is the squared norm of the
    consensus update (local SGD) or mean gradient (DP) — the
    denominator of the relative drift and the GNS proxy.
    """

    def __init__(self, sink, topk=3):
        self.sink = sink
        self.topk = max(1, int(topk))
        self.last = None
        self.samples = 0

    @staticmethod
    def _f(v):
        try:
            return float(np.asarray(v))
        except Exception:
            return None

    def observe(self, it, aux, kind="params", tau=None, round_idx=None,
                emit=True):
        """aux: host-fetched dict from consensus_stats (plus optional
        ref_sq / worker_loss). Returns the summary dict (floats), or
        None when aux carries no divergence fields."""
        if not aux or "div_mean_sq" not in aux:
            return None
        mean_sq = self._f(aux["div_mean_sq"]) or 0.0
        max_sq = self._f(aux.get("div_max_sq")) or 0.0
        ev = {"iter": it, "kind": kind,
              "mean": round(math.sqrt(max(mean_sq, 0.0)), 8),
              "max": round(math.sqrt(max(max_sq, 0.0)), 8)}
        if tau is not None:
            ev["tau"] = int(tau)
        if round_idx is not None:
            ev["round"] = int(round_idx)
        workers = aux.get("div_worker_sq")
        if workers is not None:
            w = np.sqrt(np.maximum(
                np.asarray(workers, np.float64).ravel(), 0.0))
            ev["per_worker"] = [round(float(x), 8) for x in w]
        layers = aux.get("layer_div_sq") or {}
        ranked = sorted(((k, self._f(v) or 0.0) for k, v in layers.items()),
                        key=lambda kv: -kv[1])
        if ranked:
            ev["top_layers"] = [
                [k, round(math.sqrt(max(v, 0.0)), 8)]
                for k, v in ranked[:self.topk] if v > 0.0] or \
                [[ranked[0][0], 0.0]]
        ref_sq = self._f(aux.get("ref_sq"))
        if ref_sq is not None:
            ev["update_norm"] = round(math.sqrt(max(ref_sq, 0.0)), 8)
            denom = max(ref_sq, 1e-20)
            ev["rel"] = round(math.sqrt(max(mean_sq, 0.0) / denom), 6)
            n = len(ev.get("per_worker", ())) or 0
            if n > 1:
                ev["gns_proxy"] = round(
                    n / (n - 1) * mean_sq / denom, 6)
        wl = aux.get("worker_loss")
        if wl is not None:
            wl = np.asarray(wl, np.float64).ravel()
            ev["worker_loss"] = [round(float(x), 6) for x in wl]
        # elastic membership (resilience/elastic.py): how many workers
        # the masked consensus actually averaged over this round
        nl = self._f(aux.get("n_live"))
        if nl is not None:
            ev["live"] = int(nl)
            n = len(ev.get("per_worker", ()))
            if n and nl < n:
                v = aux.get("valid")
                if v is not None:
                    ev["valid"] = [int(x > 0) for x in
                                   np.asarray(v, np.float64).ravel()]
        # bounded staleness (async mode): the per-worker version lag and
        # park state ride along, and the drift is ATTRIBUTED — how much
        # of this round's worker divergence sits on stale workers vs on
        # membership holes vs plain tau drift. The attribution is what
        # lets an operator tell "s is too loose" from "tau is too big".
        lag = aux.get("lag")
        if lag is not None:
            lag = [int(x) for x in np.asarray(lag, np.float64).ravel()]
            ev["lag"] = lag
            if aux.get("parked") is not None:
                ev["parked"] = [int(w) for w in aux["parked"]]
            w = aux.get("weight")
            if w is not None:
                ev["weight"] = [round(float(x), 4) for x in
                                np.asarray(w, np.float64).ravel()]
            workers = aux.get("div_worker_sq")
            if workers is not None:
                sq = np.asarray(workers, np.float64).ravel()
                total = float(sq.sum())
                stale = float(sum(s for s, l in zip(sq, lag) if l > 0))
                if total > 0:
                    ev["drift_stale_frac"] = round(stale / total, 4)
            valid = aux.get("valid")
            invalid_holes = valid is not None and \
                bool((np.asarray(valid, np.float64).ravel() <= 0).any())
            ev["drift_cause"] = (
                "staleness" if any(l > 0 for l in lag)
                and ev.get("drift_stale_frac", 0) >= 0.5
                else "membership" if invalid_holes or aux.get("parked")
                else "tau")
        self.samples += 1
        self.last = ev
        if emit and self.sink is not None:
            self.sink.log("divergence", **ev)
        return ev
