"""`sparknet report` — aggregate a metrics JSONL into a run report.

Input: the JSONL a run writes via --metrics (spans, steps, comms,
recompiles, train/test curve, watchdog, prefetch, bench rows — see the
obs package docstring). Output: a human-readable per-phase breakdown on
stdout and, with --json, a machine-readable report suitable for
BENCH_*.json-style comparison across runs.

Aggregation is pure dict-munging over parsed lines — no jax, no solver
imports — so the report verb works on any machine, including ones
without an accelerator stack.
"""

import collections
import json

from .stepstats import percentiles


class MetricsFileError(RuntimeError):
    """A metrics JSONL that can't be reported on (missing/unreadable/
    empty) — the CLI turns this into a one-line error, not a traceback."""


def load_events(path):
    """Parse a JSONL file -> (events, malformed_line_count). Bad lines
    (truncated writes, garbage) are skipped and counted, never fatal.
    Raises MetricsFileError when the file itself can't be read."""
    events, bad = [], 0
    try:
        f = open(path, errors="replace")
    except OSError as e:
        raise MetricsFileError(
            f"cannot read metrics file {path}: {e.strerror or e}")
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(ev, dict):
                events.append(ev)
            else:
                bad += 1
    return events, bad


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def aggregate(events):
    """Events -> report dict (all keys optional except counts)."""
    by_type = collections.Counter(e.get("event", "?") for e in events)
    rep = {"num_events": len(events), "events_by_type": dict(by_type)}

    # -- spans: per-name rollup + top-level phase breakdown ----------------
    spans = [e for e in events if e.get("event") == "span"]
    if spans:
        names = collections.defaultdict(lambda: {"count": 0, "total_ms": 0.0,
                                                 "max_ms": 0.0})
        for s in spans:
            d = float(s.get("dur_ms") or 0.0)
            r = names[s.get("name", "?")]
            r["count"] += 1
            r["total_ms"] += d
            r["max_ms"] = max(r["max_ms"], d)
        for r in names.values():
            r["total_ms"] = round(r["total_ms"], 3)
            r["mean_ms"] = round(r["total_ms"] / r["count"], 3)
            r["max_ms"] = round(r["max_ms"], 3)
        rep["spans"] = dict(names)
        top = [s for s in spans if not s.get("depth")]
        total_top = sum(float(s.get("dur_ms") or 0.0) for s in top) or 1.0
        phases = collections.defaultdict(float)
        for s in top:
            phases[s.get("name", "?")] += float(s.get("dur_ms") or 0.0)
        rep["phases"] = [
            {"phase": k, "total_ms": round(v, 3),
             "pct": round(100.0 * v / total_top, 1)}
            for k, v in sorted(phases.items(), key=lambda kv: -kv[1])]

    # -- steps: prefer the flushed full-histogram summary ------------------
    summaries = [e for e in events if e.get("event") == "step_summary"]
    steps = [e for e in events if e.get("event") == "step"]
    if summaries:
        s = dict(summaries[-1])
        s.pop("event", None)
        s.pop("t", None)
        rep["steps"] = s
    elif steps:
        host = [e["host_ms"] for e in steps if _num(e.get("host_ms"))]
        dev = [e["device_ms"] for e in steps if _num(e.get("device_ms"))]
        st = {"sampled_steps": len(steps)}
        st.update({f"host_ms_{k}": round(v, 3)
                   for k, v in percentiles(host).items()})
        st.update({f"device_ms_{k}": round(v, 3)
                   for k, v in percentiles(dev).items()})
        rep["steps"] = st

    recompiles = [e for e in events if e.get("event") == "recompile"]
    if recompiles:
        rep["recompiles"] = {
            "count": sum(1 for e in recompiles if not e.get("first")),
            "first_compile_iters": [e.get("iter") for e in recompiles
                                    if e.get("first")],
            "unexpected": [{"iter": e.get("iter"),
                            "reason": e.get("reason")}
                           for e in recompiles if not e.get("first")][:50]}

    # -- comms -------------------------------------------------------------
    comms = [e for e in events if e.get("event") == "comms"]
    if comms:
        last = comms[-1]
        c = {"h2d_bytes_total": last.get("h2d_bytes_total"),
             "collective_bytes_per_step":
                 last.get("collective_bytes_per_step"),
             "collectives": last.get("collectives", [])}
        for k in ("strategy", "n_devices", "axes", "param_bytes",
                  "overlapped_bytes_per_step", "exposed_bytes_per_step",
                  "overlap_ceiling"):
            if k in last:
                c[k] = last[k]
        rep["comms"] = c

    # -- training curve ----------------------------------------------------
    train = [e for e in events if e.get("event") == "train"
             and _num(e.get("loss"))]
    if train:
        losses = [e["loss"] for e in train]
        t = {"points": len(train),
             "first_loss": round(losses[0], 6),
             "final_loss": round(losses[-1], 6),
             "min_loss": round(min(losses), 6)}
        its = [e.get("iter") for e in train if _num(e.get("iter"))]
        if its:
            t["first_iter"], t["last_iter"] = its[0], its[-1]
        for rate in ("images_per_sec", "tokens_per_sec", "images_per_s"):
            vals = [e[rate] for e in train if _num(e.get(rate))]
            if vals:
                t[rate] = {"mean": round(sum(vals) / len(vals), 1),
                           "last": round(vals[-1], 1)}
        rep["train"] = t
    tests = [e for e in events if e.get("event") == "test"]
    if tests:
        last = tests[-1]
        rep["test"] = {k: v for k, v in last.items()
                       if k not in ("event", "t", "run")}
    summary = [e for e in events if e.get("event") == "summary"]
    if summary:
        rep["summary"] = {k: v for k, v in summary[-1].items()
                          if k not in ("event", "t", "run")}

    # -- resilience (sparknet_tpu.resilience) ------------------------------
    rec = [e for e in events if e.get("event") == "recovery"]
    if rec:
        rep["recovery"] = {
            "kinds": dict(collections.Counter(e.get("kind", "?")
                                              for e in rec)),
            "rollback_iters": [e.get("to_iter") for e in rec
                               if e.get("kind") == "rollback"][:20],
            "last_reason": rec[-1].get("reason")}
    ch = [e for e in events if e.get("event") == "chaos"]
    if ch:
        rep["chaos"] = dict(collections.Counter(e.get("kind", "?")
                                                for e in ch))
    rt = [e for e in events if e.get("event") == "retry"]
    if rt:
        rep["retries"] = {
            "count": len(rt),
            "exhausted": sum(1 for e in rt if e.get("exhausted")),
            "by_where": dict(collections.Counter(
                str(e.get("where", "?")) for e in rt))}
    # elastic membership (resilience/elastic.py)
    ev = [e for e in events if e.get("event") == "eviction"]
    rd = [e for e in events if e.get("event") == "readmission"]
    mem = [e for e in events if e.get("event") == "membership"]
    adm = [e for e in mem if e.get("kind") == "admission"]
    if ev or rd or mem:
        el = {"evictions": len(ev), "readmissions": len(rd)}
        if adm:
            el["admissions"] = len(adm)
            el["admission_records"] = [
                {"worker": e.get("worker"), "round": e.get("round"),
                 "via": e.get("via"),
                 "unit": e.get("unit", "worker")} for e in adm][:20]
        if ev:
            el["evictions_by_worker"] = {
                str(k): v for k, v in collections.Counter(
                    e.get("worker") for e in ev).items()}
            el["eviction_records"] = [
                {"worker": e.get("worker"), "round": e.get("round"),
                 "reason": e.get("reason"),
                 "unit": e.get("unit", "worker")} for e in ev][:20]
        lives = [e["live"] for e in (ev + rd + mem)
                 if _num(e.get("live"))]
        if lives:
            el["last_live"] = lives[-1]
            el["min_live"] = min(lives)
        if any(e.get("kind") == "quorum_lost" for e in mem):
            ql = next(e for e in mem if e.get("kind") == "quorum_lost")
            el["quorum_lost"] = {k: ql.get(k) for k in
                                 ("round", "live", "quorum")}
        if any(e.get("kind") == "mesh_shrunk" for e in mem):
            ms = [e for e in mem if e.get("kind") == "mesh_shrunk"][-1]
            el["mesh_shrunk"] = {"from": ms.get("from_world"),
                                 "to": ms.get("to_world")}
        rep["elasticity"] = el
    # multi-host fault domains (resilience/heartbeat.py): per-host
    # liveness transitions, lease ages, and the cross-host round gate
    ha = [e for e in events if e.get("event") == "host_alive"]
    hr = [e for e in events if e.get("event") == "host_round"]
    he = [e for e in events if e.get("event") == "host_evicted"]
    hj = [e for e in events if e.get("event") == "host_joined"]
    cr = [e for e in mem if e.get("kind") == "coordinated_restart"]
    if ha or hr or he or hj or cr:
        mh = {}
        if ha:
            last = {}
            for e in ha:
                if e.get("host") is not None:
                    last[int(e["host"])] = bool(e.get("alive"))
            mh["liveness_transitions"] = len(ha)
            mh["hosts_seen"] = sorted(last)
            mh["hosts_down"] = sorted(h for h, a in last.items() if not a)
            ages = [e["lease_age_s"] for e in ha
                    if _num(e.get("lease_age_s"))]
            if ages:
                mh["max_lease_age_s"] = round(max(ages), 3)
        if hr:
            waits = [e["wait_s"] for e in hr if _num(e.get("wait_s"))]
            g = {"rounds_gated": len(hr)}
            g.update({f"wait_s_{k}": round(v, 4)
                      for k, v in percentiles(waits).items()})
            lastages = hr[-1].get("lease_age_s")
            if isinstance(lastages, list):
                g["last_lease_age_s"] = lastages
            mh["round_gate"] = g
        if he:
            mh["host_evictions"] = [
                {"host": e.get("host"), "round": e.get("round"),
                 "reason": e.get("reason")} for e in he][:20]
        if hj:
            mh["host_joins"] = [
                {"host": e.get("host"), "round": e.get("round"),
                 "via": e.get("via"), "world": e.get("world")}
                for e in hj][:20]
        if cr:
            last = cr[-1]
            mh["coordinated_restart"] = {
                "agreed": last.get("agreed"),
                "sha": (str(last.get("sha"))[:12] + "…")
                if last.get("sha") else None,
                "hosts": last.get("hosts")}
        rep["multihost"] = mh
    # fleet simulation (sparknet_tpu/sim): the per-round closed summary
    # a simulated fleet emits beside the standard host_* stream — fleet
    # size, the live-count trajectory, and the gate-wait tail the
    # lease/quorum sweeps tune against
    sm = [e for e in events if e.get("event") == "sim"]
    if sm:
        waits = [e["wait_s"] for e in sm if _num(e.get("wait_s"))]
        lives = [e["live"] for e in sm if _num(e.get("live"))]
        fl = {"rounds": len(sm), "hosts": sm[-1].get("hosts"),
              "sim_s": sm[-1].get("t_s"),
              "live_final": lives[-1] if lives else None,
              "live_min": min(lives) if lives else None,
              "evictions": sm[-1].get("evictions"),
              "readmissions": sm[-1].get("readmissions"),
              "admissions": sm[-1].get("admissions"),
              "parked_max": max((e.get("parked") or 0) for e in sm)}
        fl.update({f"wait_s_{k}": round(v, 4)
                   for k, v in percentiles(waits).items()})
        rep["simulation"] = fl
    # fleet timeline (obs/fleettrace + obs/critpath): clock-beacon
    # alignment plus per-round critical-path attribution whenever the
    # stream carries mono-stamped events (trace_align beacons or
    # mono-bearing host_round gate exits)
    ta = [e for e in events if e.get("event") == "trace_align"]
    if ta or any(_num(e.get("mono")) for e in hr):
        from . import critpath as _critpath
        from . import fleettrace as _fleettrace
        ft = _fleettrace.merge_streams([events])
        fleet = _fleettrace.align_summary(ft)
        fleet["critpath"] = _critpath.compute(ft)["summary"]
        rep["fleet"] = fleet
    # bounded staleness (the async local-SGD mode): per-worker version
    # lag / park-time accounting + drift attribution
    st = [e for e in events if e.get("event") == "staleness"]
    pk = [e for e in events if e.get("event") == "parked"]
    up = [e for e in events if e.get("event") == "unparked"]
    if st or pk or up:
        sa = {"parks": len(pk), "unparks": len(up)}
        if pk:
            sa["parks_by_worker"] = {
                str(k): v for k, v in collections.Counter(
                    e.get("worker") for e in pk).items()}
        if up:
            sa["park_rounds_total"] = sum(
                e.get("parked_rounds") or 0 for e in up)
        if st:
            last = st[-1]
            if last.get("s") is not None:
                sa["s"] = last["s"]
            if isinstance(last.get("lag"), list):
                sa["last_lag"] = last["lag"]
            if isinstance(last.get("version"), list):
                sa["last_version"] = last["version"]
            if isinstance(last.get("park_rounds"), list):
                sa["park_rounds_by_worker"] = {
                    str(w): r for w, r in enumerate(last["park_rounds"])
                    if r}
            lags = [max(e["lag"]) for e in st
                    if isinstance(e.get("lag"), list) and e["lag"]]
            if lags:
                sa["max_lag"] = max(lags)
        div = [e for e in events if e.get("event") == "divergence"
               and e.get("drift_cause")]
        if div:
            sa["drift_cause"] = dict(collections.Counter(
                e["drift_cause"] for e in div))
            fracs = [e["drift_stale_frac"] for e in div
                     if _num(e.get("drift_stale_frac"))]
            if fracs:
                sa["drift_stale_frac_last"] = fracs[-1]
        rep["staleness"] = sa
    cp = [e for e in events if e.get("event") == "checkpoint"]
    if cp:
        writes = [e for e in cp if e.get("kind") != "resume"]
        resumes = [e for e in cp if e.get("kind") == "resume"]
        c = {"count": len(writes)}
        if writes:
            c["last_iter"] = writes[-1].get("iter")
            c["last_bytes"] = writes[-1].get("bytes")
        if resumes:
            c["resumed_from_iter"] = resumes[-1].get("iter")
            c["resume_refused"] = resumes[-1].get("refused")
        rep["checkpoints"] = c
    rs = [e for e in events if e.get("event") == "reshard"]
    if rs:
        last = rs[-1]
        rep.setdefault("checkpoints", {})["reshard"] = {
            "count": len(rs),
            "from_world": last.get("from_world"),
            "to_world": last.get("to_world"),
            "direction": last.get("direction"),
            "iter": last.get("iter")}

    # -- training health (obs divergence/health/memstats) ------------------
    div = [e for e in events if e.get("event") == "divergence"]
    if div:
        means = [e["mean"] for e in div if _num(e.get("mean"))]
        d = {"samples": len(div)}
        if means:
            d.update(first_mean=means[0], last_mean=means[-1],
                     peak_mean=max(means))
            if means[0] > 0:
                d["trend"] = round(means[-1] / means[0], 3)
        maxes = [e["max"] for e in div if _num(e.get("max"))]
        if maxes:
            d["peak_worker"] = max(maxes)
        last = div[-1]
        for k in ("kind", "tau", "rel", "gns_proxy", "update_norm",
                  "top_layers"):
            if last.get(k) is not None:
                d[k] = last[k]
        # the per-round curve itself (capped): iter -> mean divergence
        pts = [(e.get("round", e.get("iter")), e.get("mean"))
               for e in div if _num(e.get("mean"))]
        d["per_round"] = [[r, m] for r, m in pts[-50:]]
        rep["divergence"] = d
    hl = [e for e in events if e.get("event") == "health"]
    if hl:
        h = {"alarms": len(hl),
             "by_kind": dict(collections.Counter(
                 e.get("kind", "?") for e in hl))}
        stragglers = collections.Counter(
            e.get("worker") for e in hl
            if e.get("kind") == "straggler" and e.get("worker") is not None)
        if stragglers:
            h["stragglers_by_worker"] = {str(k): v
                                         for k, v in stragglers.items()}
            h["worst_straggler"] = stragglers.most_common(1)[0][0]
        last = hl[-1]
        h["last_alarm"] = {k: v for k, v in last.items()
                           if k not in ("event", "t", "run")}
        taus = [e["suggest_tau"] for e in hl if _num(e.get("suggest_tau"))]
        if taus:
            h["suggest_tau"] = taus[-1]
        esses = [e["suggest_s"] for e in hl if _num(e.get("suggest_s"))]
        if esses:
            h["suggest_s"] = esses[-1]
        rep["health"] = h
    mem = [e for e in events if e.get("event") == "memstats"]
    if mem:
        m = {"samples": len(mem)}
        live = [e["live_bytes"] for e in mem if _num(e.get("live_bytes"))]
        if live:
            m["live_bytes_last"] = live[-1]
            m["live_bytes_peak"] = max(live)
        caches = [e["compile_cache"] for e in mem
                  if _num(e.get("compile_cache"))]
        if caches:
            m["compile_cache_last"] = caches[-1]
        rss = [e["host_rss_bytes"] for e in mem
               if _num(e.get("host_rss_bytes"))]
        if rss:
            m["host_rss_peak"] = max(rss)
        hbm_keys = [e["hbm_peak_bytes_in_use"] for e in mem
                    if _num(e.get("hbm_peak_bytes_in_use"))]
        if hbm_keys:
            m["hbm_peak_bytes_in_use"] = max(hbm_keys)
        rep["memstats"] = m
    dc = [e for e in events if e.get("event") == "device_cache"]
    if dc:
        last = dc[-1]
        rep["device_cache"] = {k: v for k, v in last.items()
                               if k not in ("event", "t", "run")}

    # -- auxiliary streams -------------------------------------------------
    wd = [e for e in events if e.get("event") == "watchdog"]
    if wd:
        rep["watchdog"] = dict(collections.Counter(
            e.get("kind", "?") for e in wd))
    pf = [e for e in events if e.get("event") == "prefetch"]
    if pf:
        last = pf[-1]
        rep["prefetch"] = {k: v for k, v in last.items()
                           if k not in ("event", "t", "run")}
    ing = [e for e in events if e.get("event") == "ingest"]
    h2d = [e for e in events if e.get("event") == "h2d_stage"]
    if ing or h2d:
        ip = {}
        if ing:
            hosts = {}
            for e in ing:
                hosts[e.get("host", "?")] = {
                    k: e.get(k) for k in
                    ("hosts", "partitions", "records", "lo", "hi", "reads")}
            ip["ingest"] = {
                "hosts": hosts,
                "respreads": sum(1 for e in ing
                                 if e.get("kind") == "respread"),
            }
        if h2d:
            last = h2d[-1]
            ip["h2d_stage"] = {k: v for k, v in last.items()
                               if k not in ("event", "t", "run")}
        rep["input_pipeline"] = ip
    hbm = [e for e in events if e.get("event") == "hbm"]
    if hbm:
        peaks = [e.get("peak_bytes_in_use") or e.get("bytes_in_use") or 0
                 for e in hbm]
        rep["hbm"] = {"samples": len(hbm),
                      "peak_bytes_in_use": max(peaks)}
    bench = [e for e in events if e.get("event") == "bench"]
    if bench:
        rep["bench"] = [{k: v for k, v in e.items()
                         if k not in ("event", "t", "run")} for e in bench]

    # -- serving (sparknet_tpu.serve) --------------------------------------
    sreq = [e for e in events if e.get("event") == "serve_request"]
    sbat = [e for e in events if e.get("event") == "serve_batch"]
    srej = [e for e in events if e.get("event") == "serve_reject"]
    srel = [e for e in events if e.get("event") == "serve_reload"]
    ssum = [e for e in events if e.get("event") == "serve_summary"]
    if sreq or sbat or srej or srel or ssum:
        sv = {"requests": len(sreq), "batches": len(sbat),
              "rejects": len(srej), "reloads": len(srel)}
        lats = [e["latency_ms"] for e in sreq if _num(e.get("latency_ms"))]
        if lats:
            sv.update({f"latency_ms_{k}": round(v, 3)
                       for k, v in percentiles(lats).items()})
        waits = [e["wait_ms"] for e in sreq if _num(e.get("wait_ms"))]
        if waits:
            sv["queue_wait_ms_p99"] = round(percentiles(waits)["p99"], 3)
        fills = [e["fill"] for e in sbat if _num(e.get("fill"))]
        if fills:
            sv["batch_fill_mean"] = round(sum(fills) / len(fills), 4)
        depths = [e["queue_depth"] for e in sbat
                  if _num(e.get("queue_depth"))]
        if depths:
            sv["queue_depth_max"] = max(depths)
        if sbat:
            sv["buckets_used"] = sorted(
                {e.get("bucket") for e in sbat if _num(e.get("bucket"))})
        if srej:
            sv["rejects_by_reason"] = dict(collections.Counter(
                str(e.get("reason", "?")) for e in srej))
        if srel:
            sv["reload_iters"] = [e.get("iter") for e in srel][-10:]
        if ssum:
            # the drain-time flush aggregates the WHOLE run (the
            # per-request stream caps its ring); prefer its totals
            last = ssum[-1]
            for k in ("requests", "rows", "rps", "batch_fill",
                      "uptime_s", "drained", "latency_ms_p50",
                      "latency_ms_p95", "latency_ms_p99"):
                if last.get(k) is not None:
                    sv[k] = last[k]
        rep["serving"] = sv

    # -- routing fleet (serve/fleet.py: `sparknet route`) ------------------
    rt = [e for e in events if e.get("event") == "route"]
    sc = [e for e in events if e.get("event") == "scale"]
    cn = [e for e in events if e.get("event") == "canary"]
    if rt or sc or cn:
        fl = {"dispatches": len(rt)}
        if rt:
            codes = collections.Counter(
                int(e["code"]) for e in rt if _num(e.get("code")))
            fl["by_code"] = {str(k): v for k, v in sorted(codes.items())}
            fl["availability"] = round(codes.get(200, 0) / len(rt), 4)
            fl["retried"] = sum(1 for e in rt if e.get("retried"))
            lats = [e["latency_ms"] for e in rt
                    if _num(e.get("latency_ms"))]
            if lats:
                fl.update({f"latency_ms_{k}": round(v, 3)
                           for k, v in percentiles(lats).items()})
            fl["by_replica"] = dict(collections.Counter(
                str(e.get("replica")) for e in rt
                if e.get("replica") is not None))
        if sc:
            fl["scale_events"] = [
                {k: e.get(k) for k in ("action", "reason", "live",
                                       "p99_ms", "queue_depth")}
                for e in sc]
        if cn:
            fl["canary_events"] = [
                {k: e.get(k) for k in ("action", "sha", "baseline_sha",
                                       "reason", "err_rate",
                                       "base_err_rate", "requests")}
                for e in cn]
            fl["canary_rollbacks"] = sum(
                1 for e in cn if e.get("action") == "rollback")
        rep["routing"] = fl

    # -- request tracing (obs/tracing.py: serve_trace) ---------------------
    trc = [e for e in events if e.get("event") == "serve_trace"]
    if trc:
        # prefer the router's view (it closes the loop with net time);
        # replica-only streams still decompose their own stages
        rows = [e for e in trc if e.get("src") == "router"] or trc
        tr = {"traces": len(trc),
              "tails": sum(1 for e in trc if e.get("tail")),
              "retried": sum(1 for e in rows if e.get("retried"))}
        stage_keys = ("net", "queue", "batch", "infer", "fulfill")
        stages = {}
        for k in stage_keys:
            vals = [e[f"{k}_ms"] for e in rows
                    if _num(e.get(f"{k}_ms"))]
            if vals:
                stages[k] = {q: round(v, 3)
                             for q, v in percentiles(vals).items()}
        if stages:
            tr["stages"] = stages
        totals = [e["total_ms"] for e in rows
                  if _num(e.get("total_ms"))]
        if totals:
            tr["p99_total_ms"] = round(percentiles(totals)["p99"], 3)
            # "where did the p99 go": per-stage MEANS over the tail
            # cohort (total >= p99 threshold). Means over one cohort
            # sum to the cohort's mean total — unlike per-stage p99s,
            # which need not sum to anything — so the attribution is
            # checkable: sum(stages) ≈ cohort total
            thresh = percentiles(totals)["p99"]
            cohort = [e for e in rows if _num(e.get("total_ms"))
                      and e["total_ms"] >= thresh]
            attr = {}
            for k in stage_keys:
                vals = [e[f"{k}_ms"] for e in cohort
                        if _num(e.get(f"{k}_ms"))]
                if vals:
                    attr[k] = round(sum(vals) / len(vals), 3)
            if attr:
                tr["p99_attribution"] = attr
                tr["p99_cohort_ms"] = round(
                    sum(e["total_ms"] for e in cohort) / len(cohort), 3)
                tr["top_stage"] = max(attr.items(),
                                      key=lambda kv: kv[1])[0]
        rep["tracing"] = tr

    # -- SLO error budget (obs/tracing.py: slo_burn) -----------------------
    brn = [e for e in events if e.get("event") == "slo_burn"]
    if brn:
        alerts = collections.Counter(
            str(e.get("alert")) for e in brn if e.get("alert"))
        peak = max((e["fast"] for e in brn if _num(e.get("fast"))),
                   default=None)
        last = brn[-1]
        rep["slo_burn"] = {
            "evaluations": len(brn),
            "alerts": dict(alerts),
            "peak_fast_burn": None if peak is None else round(peak, 3),
            "last": {k: last.get(k) for k in
                     ("alert", "fast", "fast_long", "slow",
                      "slow_long", "budget_left", "good", "bad")}}
    return rep


def _fmt_bytes(n):
    if not _num(n):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return "?"


def render(rep):
    """Report dict -> human-readable text."""
    L = []

    def hdr(s):
        L.append("")
        L.append(s)
        L.append("-" * len(s))

    L.append(f"run report: {rep.get('num_events', 0)} events "
             f"({', '.join(f'{k}:{v}' for k, v in sorted(rep.get('events_by_type', {}).items()))})")
    if rep.get("malformed_lines"):
        L.append(f"WARNING: {rep['malformed_lines']} malformed JSONL lines "
                 "skipped")

    if rep.get("phases"):
        hdr("per-phase time breakdown (top-level spans)")
        for p in rep["phases"]:
            L.append(f"  {p['phase']:<24} {p['total_ms']:>12.1f} ms "
                     f"{p['pct']:>5.1f}%")

    st = rep.get("steps")
    if st:
        hdr("step times")
        L.append(f"  steps observed: {st.get('steps', st.get('sampled_steps', '?'))}")
        for kind in ("host", "device"):
            ps = {q: st.get(f"{kind}_ms_{q}") for q in ("p50", "p95", "p99")}
            if any(_num(v) for v in ps.values()):
                L.append(f"  {kind + ' ms':<10} " + "  ".join(
                    f"{q}={ps[q]:.3f}" for q in ("p50", "p95", "p99")
                    if _num(ps[q])))
        if _num(st.get("recompiles")):
            L.append(f"  recompiles (beyond first): {st['recompiles']}")
    rc = rep.get("recompiles")
    if rc:
        hdr("recompiles")
        L.append(f"  first compiles at iters: {rc.get('first_compile_iters')}")
        L.append(f"  unexpected recompiles: {rc.get('count', 0)}")
        for u in rc.get("unexpected", [])[:10]:
            L.append(f"    iter {u.get('iter')}: {u.get('reason')}")

    c = rep.get("comms")
    if c:
        hdr("communication")
        if c.get("strategy"):
            line = f"  strategy: {c['strategy']} over " \
                   f"{c.get('n_devices', '?')} device(s)"
            if c.get("axes"):
                line += f", mesh axes {c['axes']}"
            L.append(line)
        L.append(f"  host->device feed total: "
                 f"{_fmt_bytes(c.get('h2d_bytes_total'))}")
        L.append(f"  collective volume/step (per chip): "
                 f"{_fmt_bytes(c.get('collective_bytes_per_step'))}")
        if _num(c.get("overlapped_bytes_per_step")):
            L.append(f"  overlappable with backward: "
                     f"{_fmt_bytes(c['overlapped_bytes_per_step'])}"
                     f" ({100 * c.get('overlap_ceiling', 0):.1f}% ceiling)"
                     f", exposed: "
                     f"{_fmt_bytes(c.get('exposed_bytes_per_step'))}")
        cols = c.get("collectives", [])
        buckets = [col for col in cols if col.get("bucket") is not None]
        for col in cols:
            if col.get("bucket") is not None:
                continue
            per = col.get("bytes_per_round", 0)
            tau = col.get("steps_per_round", 1)
            line = (f"    {col.get('kind'):<22} "
                    f"{_fmt_bytes(per)}/round, every {tau} step(s)")
            if col.get("paper_broadcast_collect_bytes"):
                line += (" (paper broadcast+collect: "
                         f"{_fmt_bytes(col['paper_broadcast_collect_bytes'])})")
            L.append(line)
        if buckets:
            tot = sum(col.get("bytes_per_round", 0) for col in buckets)
            nover = sum(1 for col in buckets if col.get("overlappable"))
            line = (f"    {buckets[0].get('kind'):<22} "
                    f"x{len(buckets)} buckets, {_fmt_bytes(tot)}/round "
                    f"total, {nover} overlappable + "
                    f"{len(buckets) - nover} exposed")
            paper = next((col["paper_broadcast_collect_bytes"]
                          for col in buckets
                          if col.get("paper_broadcast_collect_bytes")),
                         None)
            if paper:
                line += (" (paper broadcast+collect: "
                         f"{_fmt_bytes(paper)})")
            L.append(line)

    t = rep.get("train")
    if t:
        hdr("loss curve")
        L.append(f"  {t.get('points')} display points, iters "
                 f"{t.get('first_iter', '?')}..{t.get('last_iter', '?')}")
        L.append(f"  loss {t.get('first_loss')} -> {t.get('final_loss')} "
                 f"(min {t.get('min_loss')})")
        for rate in ("images_per_sec", "tokens_per_sec", "images_per_s"):
            if rate in t:
                L.append(f"  {rate}: mean {t[rate]['mean']} "
                         f"last {t[rate]['last']}")
    if rep.get("test"):
        hdr("last test scores")
        for k, v in sorted(rep["test"].items()):
            L.append(f"  {k} = {v}")
    if rep.get("summary"):
        hdr("run summary event")
        for k, v in sorted(rep["summary"].items()):
            L.append(f"  {k} = {v}")

    if any(rep.get(k) for k in ("recovery", "chaos", "retries",
                                "checkpoints", "elasticity")):
        hdr("resilience")
        cp = rep.get("checkpoints")
        if cp:
            line = f"  checkpoints: {cp.get('count', 0)}"
            if cp.get("last_iter") is not None:
                line += f" (last at iter {cp['last_iter']}, " \
                        f"{_fmt_bytes(cp.get('last_bytes'))})"
            L.append(line)
            if cp.get("resumed_from_iter") is not None:
                line = f"  resumed from iter {cp['resumed_from_iter']}"
                if cp.get("resume_refused"):
                    line += f" ({cp['resume_refused']} snapshot(s) refused)"
                L.append(line)
            if cp.get("reshard"):
                rsh = cp["reshard"]
                L.append(f"  resharded snapshot for this world "
                         f"({rsh.get('direction')}): "
                         f"{rsh.get('from_world')} -> "
                         f"{rsh.get('to_world')}")
        r = rep.get("recovery")
        if r:
            L.append("  recovery: " + ", ".join(
                f"{k}: {v}" for k, v in sorted(r["kinds"].items())))
            if r.get("rollback_iters"):
                L.append(f"    rolled back to iters {r['rollback_iters']}")
            if r.get("last_reason"):
                L.append(f"    last reason: {r['last_reason']}")
        if rep.get("chaos"):
            L.append("  chaos injected: " + ", ".join(
                f"{k}: {v}" for k, v in sorted(rep["chaos"].items())))
        rt = rep.get("retries")
        if rt:
            L.append(f"  io retries: {rt['count']} "
                     f"({rt['exhausted']} exhausted)")
        el = rep.get("elasticity")
        if el:
            line = f"  elastic membership: {el.get('evictions', 0)} " \
                   f"eviction(s), {el.get('readmissions', 0)} " \
                   "readmission(s)"
            if el.get("admissions"):
                line += f", {el['admissions']} admission(s)"
            if _num(el.get("min_live")):
                line += f", live dipped to {el['min_live']}"
            L.append(line)
            for r in el.get("eviction_records", [])[:10]:
                L.append(f"    evicted {r.get('unit', 'worker')} "
                         f"{r.get('worker')} at round "
                         f"{r.get('round')}: {r.get('reason')}")
            for r in el.get("admission_records", [])[:10]:
                L.append(f"    admitted {r.get('unit', 'worker')} "
                         f"{r.get('worker')} at round "
                         f"{r.get('round')} ({r.get('via')})")
            if el.get("mesh_shrunk"):
                L.append(f"    mesh shrunk {el['mesh_shrunk'].get('from')}"
                         f" -> {el['mesh_shrunk'].get('to')} workers")
            if el.get("quorum_lost"):
                q = el["quorum_lost"]
                L.append(f"    QUORUM LOST at round {q.get('round')}: "
                         f"{q.get('live')} live < quorum "
                         f"{q.get('quorum')} (exit 4)")
    sa = rep.get("staleness")
    if sa:
        hdr("async staleness (bounded-staleness local SGD)")
        line = f"  parks: {sa.get('parks', 0)}, unparks: " \
               f"{sa.get('unparks', 0)}"
        if _num(sa.get("s")):
            line += f", bound s={sa['s']}"
        if _num(sa.get("max_lag")):
            line += f", max lag seen {sa['max_lag']}"
        L.append(line)
        if sa.get("parks_by_worker"):
            L.append("  parks by worker: " + ", ".join(
                f"w{k}: {v}" for k, v in sorted(
                    sa["parks_by_worker"].items())))
        if _num(sa.get("park_rounds_total")):
            L.append(f"  total park time: {sa['park_rounds_total']} "
                     "round(s)")
        if sa.get("last_lag") is not None:
            L.append(f"  last version lag per worker: {sa['last_lag']}")
        if sa.get("drift_cause"):
            L.append("  drift attribution: " + ", ".join(
                f"{k}: {v}" for k, v in sorted(
                    sa["drift_cause"].items()))
                + (f" (last stale share "
                   f"{sa['drift_stale_frac_last']})"
                   if _num(sa.get("drift_stale_frac_last")) else ""))
    mh = rep.get("multihost")
    if mh:
        hdr("multi-host fault domains")
        if mh.get("hosts_seen") is not None:
            line = f"  hosts observed: {mh['hosts_seen']}"
            if mh.get("hosts_down"):
                line += f", DOWN: {mh['hosts_down']}"
            L.append(line)
        if _num(mh.get("max_lease_age_s")):
            L.append(f"  max lease age seen: {mh['max_lease_age_s']} s")
        g = mh.get("round_gate")
        if g:
            ps = {q: g.get(f"wait_s_{q}") for q in ("p50", "p95", "p99")}
            line = f"  round gate: {g.get('rounds_gated')} rounds"
            if any(_num(v) for v in ps.values()):
                line += ", wait " + "  ".join(
                    f"{q}={ps[q]:.3f}s" for q in ("p50", "p95", "p99")
                    if _num(ps[q]))
            L.append(line)
            if g.get("last_lease_age_s"):
                L.append(f"    last lease ages: {g['last_lease_age_s']}")
        for r in mh.get("host_evictions", [])[:10]:
            L.append(f"  evicted host {r.get('host')} at round "
                     f"{r.get('round')}: {r.get('reason')}")
        for r in mh.get("host_joins", [])[:10]:
            L.append(f"  joined host {r.get('host')} at round "
                     f"{r.get('round')} ({r.get('via')}, world -> "
                     f"{r.get('world')})")
        cr = mh.get("coordinated_restart")
        if cr:
            L.append(f"  coordinated restart: "
                     f"{'AGREED' if cr.get('agreed') else 'DISAGREED'} "
                     f"on manifest {cr.get('sha')} across hosts "
                     f"{cr.get('hosts')}")
    fl = rep.get("simulation")
    if fl:
        hdr("fleet simulation")
        L.append(f"  {fl.get('hosts')} virtual hosts x "
                 f"{fl.get('rounds')} rounds "
                 f"({fl.get('sim_s')} simulated s)")
        L.append(f"  live: min {fl.get('live_min')}, final "
                 f"{fl.get('live_final')}; "
                 f"{fl.get('evictions')} eviction(s), "
                 f"{fl.get('readmissions')} readmission(s), "
                 f"{fl.get('admissions')} admission(s), "
                 f"peak parked {fl.get('parked_max')}")
        ps = {q: fl.get(f"wait_s_{q}") for q in ("p50", "p95", "p99")}
        if any(_num(v) for v in ps.values()):
            L.append("  gate wait " + "  ".join(
                f"{q}={ps[q]:.3f}s" for q in ("p50", "p95", "p99")
                if _num(ps[q])))
    ftl = rep.get("fleet")
    if ftl:
        hdr("fleet timeline")
        L.append(f"  {len(ftl.get('hosts', []))} track(s), "
                 f"{ftl.get('beacons', 0)} clock beacon(s)")
        for h, o in sorted(ftl.get("offsets", {}).items()):
            if not o.get("aligned"):
                L.append(f"    host {h}: unaligned (no beacon path)")
                continue
            err = o.get("err_s")
            err_txt = "one-sided bound" if err is None \
                else f"±{err * 1e3:.1f} ms"
            L.append(f"    host {h}: offset "
                     f"{o.get('offset_s', 0.0) * 1e3:+.1f} ms "
                     f"({err_txt}, {o.get('samples', 0)} beacon(s))")
        cps = ftl.get("critpath") or {}
        if cps.get("rounds"):
            L.append(f"  critical path over {cps['rounds']} round(s), "
                     f"{cps.get('wall_s', 0)}s wall")
            pt = cps.get("phase_totals") or {}
            split = ", ".join(f"{k} {v}s" for k, v in sorted(pt.items())
                              if _num(v) and v > 0)
            if split:
                L.append(f"    phase totals: {split}")
            for b in cps.get("top_blockers", []):
                L.append(f"    blocker host {b['host']}: "
                         f"{b['rounds_blocked']} round(s), "
                         f"{b['exposed_s']}s exposed")
    if any(rep.get(k) for k in ("divergence", "health", "memstats")):
        hdr("training health")
        d = rep.get("divergence")
        if d:
            line = f"  divergence ({d.get('kind', 'params')}): " \
                   f"mean {d.get('first_mean', '?')} -> " \
                   f"{d.get('last_mean', '?')} " \
                   f"(peak {d.get('peak_mean', '?')}, " \
                   f"{d.get('samples')} samples"
            if _num(d.get("trend")):
                line += f", trend x{d['trend']}"
            if d.get("tau"):
                line += f", tau={d['tau']}"
            line += ")"
            L.append(line)
            if _num(d.get("rel")) or _num(d.get("gns_proxy")):
                bits = []
                if _num(d.get("rel")):
                    bits.append(f"drift/update ratio {d['rel']}")
                if _num(d.get("gns_proxy")):
                    bits.append(f"grad-noise-scale proxy {d['gns_proxy']}")
                L.append("    " + ", ".join(bits))
            if d.get("top_layers"):
                L.append("    top drifting layers: " + ", ".join(
                    f"{k}={v:.3g}" for k, v in d["top_layers"]))
            pr = d.get("per_round") or []
            if pr:
                L.append("    per-round mean divergence (last "
                         f"{len(pr[-8:])}): " + ", ".join(
                             f"{r}:{m:.3g}" for r, m in pr[-8:]))
        h = rep.get("health")
        if h:
            L.append(f"  health alarms: {h.get('alarms', 0)} (" + ", ".join(
                f"{k}: {v}" for k, v in sorted(
                    h.get("by_kind", {}).items())) + ")")
            if h.get("stragglers_by_worker"):
                L.append("    straggler: worker "
                         f"{h['worst_straggler']} flagged "
                         f"{h['stragglers_by_worker'][str(h['worst_straggler'])]}x "
                         f"(all: {h['stragglers_by_worker']})")
            la = h.get("last_alarm")
            if la:
                detail = " ".join(f"{k}={v}" for k, v in la.items()
                                  if k != "kind")
                L.append(f"    last alarm: [{la.get('kind')}] {detail}")
            if _num(h.get("suggest_tau")):
                L.append(f"    suggested tau: {h['suggest_tau']}")
            if _num(h.get("suggest_s")):
                L.append(f"    suggested staleness bound s: "
                         f"{h['suggest_s']}")
        m = rep.get("memstats")
        if m:
            bits = [f"{m.get('samples')} samples"]
            if _num(m.get("live_bytes_peak")):
                bits.append(f"peak live arrays "
                            f"{_fmt_bytes(m['live_bytes_peak'])}")
            if _num(m.get("hbm_peak_bytes_in_use")):
                bits.append(f"hbm peak "
                            f"{_fmt_bytes(m['hbm_peak_bytes_in_use'])}")
            if _num(m.get("compile_cache_last")):
                bits.append(f"compile cache {m['compile_cache_last']}")
            if _num(m.get("host_rss_peak")):
                bits.append(f"host rss peak "
                            f"{_fmt_bytes(m['host_rss_peak'])}")
            L.append("  memory: " + ", ".join(bits))
    if rep.get("device_cache"):
        hdr("device cache (last gauge)")
        for k, v in sorted(rep["device_cache"].items()):
            L.append(f"  {k} = {v}")
    if rep.get("watchdog"):
        hdr("watchdog")
        for k, v in sorted(rep["watchdog"].items()):
            L.append(f"  {k}: {v}")
    if rep.get("prefetch"):
        hdr("prefetch (last gauge)")
        for k, v in sorted(rep["prefetch"].items()):
            L.append(f"  {k} = {v}")
    ip = rep.get("input_pipeline")
    if ip:
        hdr("input pipeline")
        st = ip.get("h2d_stage")
        if st:
            L.append(f"  h2d staging: {st.get('puts', 0)} put(s), "
                     f"{_fmt_bytes(st.get('bytes'))} shipped, "
                     f"{st.get('kb_per_item', '?')} KB/item")
            L.append(f"    dispatch {st.get('dispatch_ms', '?')} ms, "
                     f"wait {st.get('wait_ms', '?')} ms, "
                     f"in flight {st.get('in_flight', '?')}/"
                     f"{st.get('slots', '?')} slot(s)")
        ig = ip.get("ingest")
        if ig:
            hosts = ig.get("hosts", {})
            L.append(f"  sharded ingest: {len(hosts)} host(s)"
                     + (f", {ig['respreads']} re-spread(s)"
                        if ig.get("respreads") else ""))
            for h, d in sorted(hosts.items()):
                rng = (f" [{d['lo']}..{d['hi']}]"
                       if _num(d.get("lo")) and d["lo"] >= 0 else "")
                L.append(f"    host {h}: partitions {d.get('partitions')}"
                         f", {d.get('records')} record(s){rng}, "
                         f"{d.get('reads', 0)} read(s)")
    if rep.get("hbm"):
        hdr("device memory")
        L.append(f"  peak bytes in use: "
                 f"{_fmt_bytes(rep['hbm'].get('peak_bytes_in_use'))} "
                 f"({rep['hbm'].get('samples')} samples)")
    if rep.get("bench"):
        hdr("bench rows")
        for r in rep["bench"]:
            bits = [str(r.get("model", "?")), str(r.get("mode", ""))]
            for k in ("images_per_sec", "tokens_per_sec", "mfu",
                      "rps", "latency_ms_p50", "latency_ms_p99"):
                if _num(r.get(k)):
                    bits.append(f"{k}={r[k]}")
            L.append("  " + "  ".join(b for b in bits if b))
    sv = rep.get("serving")
    if sv:
        hdr("serving")
        line = f"  requests: {sv.get('requests', 0)}"
        if _num(sv.get("rows")):
            line += f" ({sv['rows']} rows)"
        line += f", batches: {sv.get('batches', 0)}" \
                f", rejects: {sv.get('rejects', 0)}" \
                f", reloads: {sv.get('reloads', 0)}"
        L.append(line)
        ps = {q: sv.get(f"latency_ms_{q}") for q in ("p50", "p95", "p99")}
        if any(_num(v) for v in ps.values()):
            line = "  latency ms  " + "  ".join(
                f"{q}={ps[q]:.3f}" for q in ("p50", "p95", "p99")
                if _num(ps[q]))
            if _num(sv.get("queue_wait_ms_p99")):
                line += f"  (queue wait p99={sv['queue_wait_ms_p99']:.3f})"
            L.append(line)
        bits = []
        if _num(sv.get("rps")):
            bits.append(f"{sv['rps']} req/s")
        if _num(sv.get("batch_fill_mean")):
            bits.append(f"batch fill {sv['batch_fill_mean']:.0%}")
        elif _num(sv.get("batch_fill")):
            bits.append(f"batch fill {sv['batch_fill']:.0%}")
        if sv.get("buckets_used"):
            bits.append(f"buckets {sv['buckets_used']}")
        if _num(sv.get("queue_depth_max")):
            bits.append(f"max queue depth {sv['queue_depth_max']}")
        if bits:
            L.append("  " + ", ".join(bits))
        if sv.get("rejects_by_reason"):
            L.append("  rejects by reason: " + ", ".join(
                f"{k}: {v}" for k, v in sorted(
                    sv["rejects_by_reason"].items())))
        if sv.get("reload_iters"):
            L.append(f"  hot reloads to iters {sv['reload_iters']}")
        if sv.get("drained"):
            L.append("  drained cleanly")
    fl = rep.get("routing")
    if fl:
        hdr("routing fleet")
        line = f"  dispatches: {fl.get('dispatches', 0)}"
        if fl.get("by_code"):
            line += " (" + ", ".join(
                f"{k}: {v}" for k, v in sorted(fl["by_code"].items())) \
                + ")"
        L.append(line)
        if _num(fl.get("availability")):
            line = f"  availability {fl['availability']:.2%}, " \
                   f"retried {fl.get('retried', 0)}"
            if _num(fl.get("latency_ms_p99")):
                line += f", latency p99 {fl['latency_ms_p99']:.3f} ms"
            L.append(line)
        if fl.get("by_replica"):
            L.append("  by replica: " + ", ".join(
                f"{k}: {v}" for k, v in sorted(fl["by_replica"].items())))
        for e in fl.get("scale_events", []):
            L.append(f"  scale {e.get('action')} ({e.get('reason')}): "
                     f"live {e.get('live')}, p99 {e.get('p99_ms')} ms, "
                     f"depth {e.get('queue_depth')}")
        for e in fl.get("canary_events", []):
            bits = [f"  canary {e.get('action')} sha={e.get('sha')} "
                    f"(baseline {e.get('baseline_sha')})"]
            if e.get("reason"):
                bits.append(f"reason={e['reason']}")
            if _num(e.get("err_rate")):
                bits.append(f"err {e['err_rate']:.2%} vs "
                            f"{(e.get('base_err_rate') or 0):.2%}")
            L.append(" ".join(bits))
    tr = rep.get("tracing")
    if tr:
        hdr("request tracing")
        L.append(f"  traces: {tr.get('traces', 0)} "
                 f"({tr.get('tails', 0)} tail exemplar(s), "
                 f"{tr.get('retried', 0)} retried)")
        for k, st in (tr.get("stages") or {}).items():
            L.append(f"  {k:>8}  " + "  ".join(
                f"{q}={st[q]:.3f}" for q in ("p50", "p95", "p99")
                if _num(st.get(q))) + " ms")
        attr = tr.get("p99_attribution")
        if attr:
            total = tr.get("p99_cohort_ms") or sum(attr.values())
            top = tr.get("top_stage")
            L.append(f"  p99 attribution (where did the p99 go): "
                     f"top stage {top} "
                     f"({attr.get(top, 0):.3f} of {total:.3f} ms)")
            L.append("    " + "  ".join(
                f"{k}={v:.3f}" for k, v in attr.items()) + " ms")
    bn = rep.get("slo_burn")
    if bn:
        hdr("slo error budget")
        last = bn.get("last") or {}
        line = (f"  burn rate: fast x{last.get('fast')}"
                f"/{last.get('fast_long')}, "
                f"slow x{last.get('slow')}/{last.get('slow_long')}")
        if _num(last.get("budget_left")):
            line += f", budget left {last['budget_left']:.1%}"
        L.append(line)
        alerts = bn.get("alerts") or {}
        L.append("  alerts: " + (", ".join(
            f"{k}: {v}" for k, v in sorted(alerts.items()))
            if alerts else "none") +
            f" (peak fast burn x{bn.get('peak_fast_burn')}, "
            f"{bn.get('evaluations', 0)} evaluation(s))")
    L.append("")
    return "\n".join(L)


def filter_events(events, since=None, event_types=None):
    """Apply the report's --since / --event selection. ``since``: keep
    events with t >= since (seconds into the run — the ``t`` field every
    MetricsLogger line carries); ``event_types``: iterable of event
    names to keep. Returns the filtered list; the CALLER must treat an
    empty result as an error — an empty report renders exactly like
    "all healthy", which is the dangerous lie the exit-2 contract
    prevents."""
    out = events
    if since is not None:
        out = [e for e in out
               if isinstance(e.get("t"), (int, float))
               and e["t"] >= float(since)]
    if event_types:
        keep = {str(k) for k in event_types}
        out = [e for e in out if e.get("event") in keep]
    return out


def report_file(jsonl_path, json_out=None, chrome_out=None, out=print,
                since=None, event_types=None, fmt="text"):
    """Load + aggregate + render; optionally write JSON / Chrome trace.
    The implementation behind `sparknet report`. ``since``/
    ``event_types`` select a slice of the stream; a selection that
    matches ZERO events raises MetricsFileError (exit 2 at the CLI) —
    never an empty report that reads as "all healthy".

    ``fmt="json"`` emits the report dict itself on stdout (sorted keys,
    one stable document — the same keys --json writes) so CI and the
    bench perf gate can assert on report content without scraping the
    rendered text."""
    events, bad = load_events(jsonl_path)
    if not events:
        raise MetricsFileError(
            f"metrics file has no parseable events: {jsonl_path}"
            + (f" ({bad} malformed line(s) skipped)" if bad
               else " (file is empty)"))
    if since is not None or event_types:
        selected = filter_events(events, since=since,
                                 event_types=event_types)
        if not selected:
            sel = []
            if since is not None:
                sel.append(f"--since {since}")
            if event_types:
                sel.append(f"--event {','.join(sorted(event_types))}")
            raise MetricsFileError(
                f"{' '.join(sel)} selected 0 of {len(events)} events in "
                f"{jsonl_path} — refusing to print an empty report that "
                "would read as healthy")
        events = selected
    rep = aggregate(events)
    if bad:
        rep["malformed_lines"] = bad
    if fmt == "json":
        out(json.dumps(rep, indent=1, sort_keys=True, default=str))
    else:
        out(render(rep))
    if json_out:
        with open(json_out, "w") as f:
            json.dump(rep, f, indent=1, default=str)
        if fmt != "json":
            out(f"wrote {json_out}")
    if chrome_out:
        from .trace import export_chrome
        spans = [e for e in events if e.get("event") == "span"]
        export_chrome(chrome_out, spans)
        if fmt != "json":
            out(f"wrote {chrome_out} ({len(spans)} spans)")
    return rep
