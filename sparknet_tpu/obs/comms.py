"""Comms accounting: bytes moved per sync round, mapped to the paper's
broadcast/collect cost model.

Collectives run *inside* compiled XLA programs, so their traffic can't be
counted at runtime from the host; instead each solver registers its
per-round collective volume analytically at step-build time (the same
ring cost model bench.py's multi-chip projection uses: a pmean of B bytes
over N peers moves 2(N-1)/N * B past every chip). Host->device feed
traffic IS measurable and is counted directly from the batch arrays.

This is the tau-tradeoff of the SparkNet paper measured directly: a
LocalSGD round of tau steps does ONE param-sized allreduce (the paper's
broadcast+collect through the driver — 2*N*B bytes at the driver there,
2(N-1)/N * B per chip on a ring here), while per-step DP pays a
grad-sized allreduce every step. ``comms`` events carry both models so
`sparknet report` prints bytes/step for any tau.
"""


def tree_bytes(tree):
    """Total bytes of every array leaf in a pytree (global shapes for
    sharded jax arrays — the analytic models want global volume)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is None:
            import numpy as np
            try:
                nb = np.asarray(leaf).nbytes
            except Exception:
                nb = 0
        total += int(nb)
    return total


def ring_allreduce_bytes(nbytes, n):
    """Per-chip bytes for one ring allreduce (reduce-scatter+all-gather)
    of ``nbytes`` over ``n`` peers. world_size<=1 or an empty payload is
    a no-op collective: 0 bytes, never negative/NaN."""
    n, nbytes = int(n), int(nbytes)
    if n <= 1 or nbytes <= 0:
        return 0
    return int(2 * (n - 1) / n * nbytes)


def ring_reduce_scatter_bytes(nbytes, n):
    """Per-chip bytes for one ring reduce-scatter of ``nbytes`` over
    ``n`` peers — the gradient half of an allreduce ((n-1)/n * B), which
    is all FSDP pays on the backward side (each chip keeps only its own
    shard of the reduced tree)."""
    n, nbytes = int(n), int(nbytes)
    if n <= 1 or nbytes <= 0:
        return 0
    return int((n - 1) / n * nbytes)


def ring_all_gather_bytes(nbytes, n):
    """Per-chip bytes for one ring all-gather reassembling a ``nbytes``
    GLOBAL payload from its n shards ((n-1)/n * B) — FSDP's
    params-at-use leg on the forward side."""
    n, nbytes = int(n), int(nbytes)
    if n <= 1 or nbytes <= 0:
        return 0
    return int((n - 1) / n * nbytes)


def broadcast_collect_bytes(nbytes, n):
    """The paper's driver-centric sync cost: broadcast N copies out plus
    collect N copies back through one driver (SparkNet's per-round
    weight movement, CifarApp.scala:92-135). A single worker IS the
    driver — nothing moves — and an empty payload moves nothing."""
    n, nbytes = int(n), int(nbytes)
    if n <= 1 or nbytes <= 0:
        return 0
    return int(2 * n * nbytes)


def all_to_all_bytes(nbytes, n):
    """Per-chip bytes for one all_to_all of a ``nbytes`` local buffer:
    (n-1)/n of it leaves the chip (the diagonal block stays)."""
    n, nbytes = int(n), int(nbytes)
    if n <= 1 or nbytes <= 0:
        return 0
    return int((n - 1) / n * nbytes)


class CommsMeter:
    """Counts host->device feed bytes and attributes registered
    per-round collective volume; emits ``comms`` events on the same
    sampled cadence as step accounting."""

    def __init__(self, sink, emit_every=20):
        self.sink = sink
        self.emit_every = max(1, int(emit_every))
        self.topology = {}
        self.collectives = []
        self.h2d_bytes = 0           # since last emit
        self.h2d_total = 0
        self._nticks = 0
        self._last_emit_it = None

    def set_topology(self, **kw):
        self.topology.update({k: v for k, v in kw.items() if v is not None})

    def register(self, kind, bytes_per_round, axis=None, steps_per_round=1,
                 note=None, **extra):
        """Declare a collective the compiled step performs: per-chip
        ``bytes_per_round`` every ``steps_per_round`` steps (tau for
        local SGD, 1 for per-step DP). A zero-byte collective (world
        size 1, empty payload) is a no-op: nothing is registered —
        0 bytes, 0 rounds — so single-worker runs never report phantom
        (or negative) collective traffic."""
        if int(bytes_per_round) <= 0:
            return None
        c = {"kind": kind, "bytes_per_round": int(bytes_per_round),
             "steps_per_round": max(1, int(steps_per_round))}
        if axis is not None:
            c["axis"] = axis
        if note:
            c["note"] = note
        c.update(extra)
        self.collectives.append(c)
        return c

    def add_h2d(self, nbytes):
        self.h2d_bytes += int(nbytes)
        self.h2d_total += int(nbytes)

    def collective_bytes_per_step(self):
        return int(sum(c["bytes_per_round"] / c["steps_per_round"]
                       for c in self.collectives))

    def overlapped_bytes_per_step(self):
        """Per-step bytes of collectives the registering solver marked
        ``overlappable=True`` — issued while compute that doesn't depend
        on them still runs (the bucketed grad allreduce: every bucket
        but the last-issued one hides under the backward tail)."""
        return int(sum(c["bytes_per_round"] / c["steps_per_round"]
                       for c in self.collectives
                       if c.get("overlappable")))

    def exposed_bytes_per_step(self):
        """Per-step bytes structurally stuck on the critical path: the
        whole-tree collectives plus the last-issued bucket."""
        return (self.collective_bytes_per_step()
                - self.overlapped_bytes_per_step())

    def tick(self, it, force=False):
        """Call once per step/round with the just-finished iteration."""
        self._nticks += 1
        if not (force or self._nticks <= 2 or self._last_emit_it is None
                or (it - self._last_emit_it) >= self.emit_every):
            return
        steps = it - self._last_emit_it if self._last_emit_it is not None \
            else it + 1
        ev = dict(self.topology)
        ev.update(iter=it, steps=max(1, steps),
                  h2d_bytes=self.h2d_bytes,
                  h2d_bytes_total=self.h2d_total,
                  collective_bytes_per_step=self.collective_bytes_per_step())
        if self.collectives:
            ev["collectives"] = self.collectives
            over = self.overlapped_bytes_per_step()
            if over:
                total = self.collective_bytes_per_step()
                ev["overlapped_bytes_per_step"] = over
                ev["exposed_bytes_per_step"] = self.exposed_bytes_per_step()
                # upper bound: realized overlap depends on backward being
                # long enough to hide under — the trace, not this model,
                # settles that. This is the structural ceiling.
                ev["overlap_ceiling"] = round(over / total, 4) if total \
                    else 0.0
        self.sink.log("comms", **ev)
        self.h2d_bytes = 0
        self._last_emit_it = it

    def flush(self, it):
        if self.h2d_bytes > 0 or self._last_emit_it is None \
                or (self._last_emit_it != it and self._nticks):
            self.tick(it, force=True)
