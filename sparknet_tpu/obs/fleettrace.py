"""Cross-host trace correlation: merge N per-host metrics streams into
one clock-aligned fleet timeline (ISSUE 16).

Every host writes its own metrics JSONL on its own clocks — wall for the
``t`` field, monotonic for gate deadlines and spans — and nothing on one
host's timeline is directly comparable to another's. The alignment
substrate is the heartbeat protocol itself: each beat record carries the
sender's monotonic send time (``mono``, heartbeat.beat), and the peer
that observes the new record emits a ``trace_align`` event pairing it
with its OWN monotonic receipt time. A receipt can only happen after the
send, so each beacon is a one-sided bound on the pairwise clock offset —
exactly the NTP interval argument:

    A observes B:  off(A,B) := monoA - monoB  <=  obs_mono - peer_mono
    B observes A:  off(A,B)  >= -(obs_monoB - peer_monoA)

Minimizing each side over many beacons gives an interval [lo, hi]; the
estimate is its midpoint and the error bar its half-width. Offsets reach
hosts with no direct pair through BFS over the bounds graph (error bars
add along the path). Simulated fleets (sparknet simfleet) share one
SimClock, so their beacons solve to ~zero offset through the exact same
path — no special cases.

Placement of an individual event on the merged timeline, best first:
  1. an explicit ``mono`` field (host_round gate exits, relay_io) —
     exact;
  2. the per-host wall->mono fit: ``t`` is wall seconds since the
     logger's epoch, and every trace_align/host_round event carries both
     ``t`` and a mono stamp, so median(mono - t) maps any event of that
     host onto its monotonic clock (robust to NTP steps between
     beacons — the median ignores a minority of pre/post-step samples);
  3. raw ``t`` (a stream with no mono-bearing events at all — marked
     unaligned).

The merged result exports as ONE Chrome trace_event file: one process
(track group) per host carrying its rounds, gate waits, spans, steps,
relay/consensus IO and H2D staging, with the solved clock offset and
error bar in the process label and in ``otherData.clock_offsets``.
"""

import json
import os
from collections import defaultdict

#: metrics events attributed to a host by which field
_HOST_FIELD = {"host_round": "observer", "trace_align": "observer",
               "host_alive": "observer", "ghost_reaped": "observer",
               "relay_io": "host"}

#: fleet-level events in a multiplexed (simfleet) stream — they belong
#: to the run, not to any one host's clock
_FLEET_EVENTS = {"sim", "membership"}

FLEET_TRACK = "fleet"


def host_of(ev):
    """The host id an event is attributed to, or None (stream-scoped —
    belongs to whichever host wrote the file)."""
    field = _HOST_FIELD.get(ev.get("event"))
    if field is None:
        # chaos slow_host events name the stalled host directly
        if ev.get("event") == "chaos" and ev.get("kind") == "slow_host" \
                and isinstance(ev.get("host"), int):
            return ev["host"]
        return None
    h = ev.get(field)
    return h if isinstance(h, int) else None


def split_streams(streams):
    """``streams``: list of per-file event lists -> {host: [events]},
    each host's events in file order. A file with ONE distinct
    self-attributed host (a real per-host run) contributes every event
    to that host; a multiplexed file (simfleet: many hosts through one
    logger) is split per event, with fleet-level events and unattributed
    leftovers going to the FLEET_TRACK pseudo-host. Files with no host
    evidence at all become synthetic hosts file<i>."""
    out = defaultdict(list)
    for i, events in enumerate(streams):
        owners = {host_of(ev) for ev in events} - {None}
        # observers see peers; the file's own host is the one that
        # OBSERVES (emits trace_align/host_round), not the ones observed
        self_ids = {ev.get("observer") for ev in events
                    if ev.get("event") in ("host_round", "trace_align")
                    and isinstance(ev.get("observer"), int)}
        if not self_ids and not owners:
            # a serving-tier stream (router or replica) has no beacon
            # observer ints; its serve_trace events name the writer in
            # ``src`` ("router", "replica0", ...) — one distinct src
            # means the whole file is that host's track
            srcs = {ev.get("src") for ev in events
                    if ev.get("event") == "serve_trace"} - {None}
            if len(srcs) == 1:
                out[next(iter(srcs))].extend(events)
                continue
        self_ids = self_ids or owners
        if len(self_ids) == 1:
            out[next(iter(self_ids))].extend(events)
        elif not self_ids:
            out[f"file{i}"].extend(events)
        else:
            for ev in events:
                if ev.get("event") in _FLEET_EVENTS:
                    out[FLEET_TRACK].append(ev)
                    continue
                h = host_of(ev)
                out[h if h is not None else FLEET_TRACK].append(ev)
    return dict(out)


def beacons(per_host):
    """All trace_align events across the split streams."""
    out = []
    for evs in per_host.values():
        out.extend(ev for ev in evs if ev.get("event") == "trace_align")
    return out


def pair_bounds(beacon_events):
    """{(observer, peer): (hi, n_samples)} — hi is the tightest upper
    bound on off(observer, peer) = mono_obs - mono_peer seen in any
    beacon for the ordered pair."""
    hi = {}
    for b in beacon_events:
        a, p = b.get("observer"), b.get("peer")
        om, pm = b.get("obs_mono"), b.get("peer_mono")
        if not (isinstance(a, int) and isinstance(p, int)):
            continue
        if not all(isinstance(x, (int, float)) for x in (om, pm)):
            continue
        bound = float(om) - float(pm)
        cur = hi.get((a, p))
        hi[(a, p)] = (bound, 1) if cur is None else \
            (min(cur[0], bound), cur[1] + 1)
    return hi


def solve_offsets(bounds, hosts, ref=None):
    """Per-host offset to the reference host's monotonic timeline.

    Returns {host: {"offset_s", "err_s", "samples", "one_sided"}} where
    ref_time = host_mono + offset_s. ``err_s`` is the accumulated
    interval half-width along the BFS path (None when every hop was
    one-sided — the estimate is then the bound itself, biased late by
    at most one delivery delay). Hosts unreachable through the beacon
    graph get offset 0.0 with err None (unaligned)."""
    hosts = [h for h in hosts if isinstance(h, int)]
    if not hosts:
        return {}
    ref = min(hosts) if ref is None else ref
    # pairwise interval per unordered pair, oriented as off(a, b)
    edges = defaultdict(list)   # a -> [(b, est_ab, err_ab, n, one_sided)]
    done = set()
    for (a, b), (hi_ab, n_ab) in bounds.items():
        if (b, a) in done or (a, b) in done:
            continue
        done.add((a, b))
        rev = bounds.get((b, a))
        if rev is not None:
            lo_ab, n = -rev[0], n_ab + rev[1]
            est = (lo_ab + hi_ab) / 2.0
            err = max(0.0, (hi_ab - lo_ab) / 2.0)
            one_sided = False
        else:
            est, err, n, one_sided = hi_ab, None, n_ab, True
        edges[a].append((b, est, err, n, one_sided))
        edges[b].append((a, -est, err, n, one_sided))
    out = {h: {"offset_s": 0.0, "err_s": None, "samples": 0,
               "one_sided": True, "aligned": False} for h in hosts}
    if ref not in out:
        return out
    out[ref] = {"offset_s": 0.0, "err_s": 0.0, "samples": 0,
                "one_sided": False, "aligned": True}
    frontier = [ref]
    while frontier:
        a = frontier.pop(0)
        for b, est_ab, err_ab, n, one_sided in edges.get(a, ()):
            if b not in out or out[b]["aligned"]:
                continue
            # off(a,b) = mono_a - mono_b at one instant, so a peer's
            # mono maps to the ref frame as mono_b + off(ref, b) where
            # off(ref, b) chains: offset_b = offset_a + off(a, b)
            base = out[a]
            err = None if (err_ab is None or base["err_s"] is None) \
                else base["err_s"] + err_ab
            out[b] = {"offset_s": base["offset_s"] + est_ab,
                      "err_s": None if err is None else round(err, 6),
                      "samples": n,
                      "one_sided": one_sided or base["one_sided"],
                      "aligned": True}
            frontier.append(b)
    for rec in out.values():
        rec["offset_s"] = round(rec["offset_s"], 6)
    return out


def wall_to_mono(events):
    """Median (mono - t) over this host's mono-bearing events — the
    wall->mono fit used to place events that carry only ``t``. None
    when the stream has no mono evidence (placement falls back to raw
    t)."""
    deltas = []
    for ev in events:
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        if ev.get("event") == "trace_align":
            m = ev.get("obs_mono")
        else:
            m = ev.get("mono")
        if isinstance(m, (int, float)):
            deltas.append(float(m) - float(t))
    if not deltas:
        return None
    deltas.sort()
    n = len(deltas)
    mid = n // 2
    return deltas[mid] if n % 2 else (deltas[mid - 1] + deltas[mid]) / 2


class FleetTrace:
    """The merged, clock-aligned fleet timeline.

    hosts     sorted track keys (ints, then synthetic string tracks)
    events    {host: [events]} as split from the input streams
    offsets   {host: offset record} from solve_offsets (int hosts only)
    fits      {host: wall->mono delta or None}
    """

    def __init__(self, per_host, offsets, fits):
        self.events = per_host
        self.offsets = offsets
        self.fits = fits
        self.hosts = sorted([h for h in per_host if isinstance(h, int)]) \
            + sorted([h for h in per_host if not isinstance(h, int)])

    def place(self, host, ev):
        """Event -> seconds on the reference timeline, or None (no time
        evidence). Explicit mono beats the wall fit beats raw t."""
        off = self.offsets.get(host, {}).get("offset_s", 0.0)
        m = ev.get("obs_mono") if ev.get("event") == "trace_align" \
            else ev.get("mono")
        if isinstance(m, (int, float)):
            return float(m) + off
        if ev.get("event") == "sim" and \
                isinstance(ev.get("t_s"), (int, float)):
            # simfleet events stamp virtual mono directly; every sim
            # host shares that clock, so no offset applies
            return float(ev["t_s"])
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            return None
        fit = self.fits.get(host)
        if fit is not None:
            return float(t) + fit + off
        return float(t)

    def aligned(self, host):
        rec = self.offsets.get(host)
        return bool(rec and rec.get("aligned"))


def merge_streams(streams):
    """Per-file event lists -> FleetTrace (split, solve, fit)."""
    per_host = split_streams(streams)
    offs = solve_offsets(pair_bounds(beacons(per_host)),
                         list(per_host.keys()))
    fits = {h: wall_to_mono(evs) for h, evs in per_host.items()}
    return FleetTrace(per_host, offs, fits)


# -- Chrome synthesis --------------------------------------------------------

#: synthetic track (tid) layout inside each host's process group
_TID_ROUNDS, _TID_IO, _TID_H2D, _TID_STEPS, _TID_SPANS = 0, 1, 2, 3, 4
_TID_SERVE = 5

_TRACK_NAMES = {_TID_ROUNDS: "rounds", _TID_IO: "relay/consensus",
                _TID_H2D: "h2d", _TID_STEPS: "steps",
                _TID_SPANS: "spans", _TID_SERVE: "serve"}


def _x(name, ts_s, dur_s, pid, tid, args):
    return {"name": name, "ph": "X", "cat": "fleet",
            "ts": round(ts_s * 1e6, 1),
            "dur": round(max(0.0, dur_s) * 1e6, 1),
            "pid": pid, "tid": tid, "args": args}


def _i(name, ts_s, pid, tid, args):
    return {"name": name, "ph": "i", "cat": "fleet", "s": "t",
            "ts": round(ts_s * 1e6, 1), "pid": pid, "tid": tid,
            "args": args}


def _host_events(ft, host, pid):
    """One host's metrics events -> Chrome events on the merged
    timeline. Durations come from each event's own duration fields;
    placement anchors at the event's EMIT time (the end of what it
    measures), so spans/waits are drawn end-anchored."""
    evs = []
    last_round_end = None
    for ev in ft.events[host]:
        kind = ev.get("event")
        at = ft.place(host, ev)
        if at is None:
            continue
        if kind == "host_round":
            wait = float(ev.get("wait_s") or 0.0)
            evs.append(_x(f"gate r{ev.get('round')}", at - wait, wait,
                          pid, _TID_ROUNDS,
                          {"round": ev.get("round"),
                           "arrived": ev.get("arrived"),
                           "dead": ev.get("dead")}))
        elif kind == "sim":
            wait = float(ev.get("wait_s") or 0.0)
            evs.append(_x(f"gate r{ev.get('round')}", at - wait, wait,
                          pid, _TID_ROUNDS,
                          {k: ev.get(k) for k in
                           ("round", "live", "parked", "dead")}))
        elif kind == "round":
            # round events mark completion; the span covers the gap
            # back to the previous round event on the same track
            start = last_round_end if last_round_end is not None else at
            evs.append(_x(f"round {ev.get('round')}", start,
                          at - start, pid, _TID_STEPS,
                          {k: ev.get(k) for k in
                           ("round", "iter", "loss", "images_per_s")
                           if ev.get(k) is not None}))
            last_round_end = at
        elif kind == "relay_io":
            dur = float(ev.get("seconds") or 0.0)
            evs.append(_x(f"relay r{ev.get('round')}", at - dur, dur,
                          pid, _TID_IO, {"round": ev.get("round"),
                                         "bytes": ev.get("bytes")}))
        elif kind == "h2d_stage":
            dur = (float(ev.get("dispatch_ms") or 0.0)
                   + float(ev.get("wait_ms") or 0.0)) / 1e3
            evs.append(_x(f"h2d {ev.get('name', '')}".strip(), at - dur,
                          dur, pid, _TID_H2D,
                          {k: ev.get(k) for k in
                           ("bytes", "wait_ms", "dispatch_ms")
                           if ev.get(k) is not None}))
        elif kind == "step":
            dur = float(ev.get("host_ms") or 0.0) / 1e3
            evs.append(_x("step", at - dur, dur, pid, _TID_STEPS,
                          {k: ev.get(k) for k in
                           ("iter", "host_ms", "device_ms")
                           if ev.get(k) is not None}))
        elif kind == "span":
            dur = float(ev.get("dur_ms") or 0.0) / 1e3
            args = {k: v for k, v in ev.items()
                    if k not in ("event", "t", "run", "start_ms",
                                 "dur_ms", "tid", "name")}
            evs.append(_x(str(ev.get("name", "span")), at - dur, dur,
                          pid, _TID_SPANS, args))
        elif kind == "serve_trace":
            # one traced serve request, end-anchored at its emit time.
            # Router events nest their per-attempt dispatch spans;
            # replica events nest the stage breakdown. The shared
            # trace id in args is what correlates the router's span
            # with the replica's across process tracks.
            total_s = float(ev.get("total_ms")
                            or ev.get("server_ms") or 0.0) / 1e3
            trace = ev.get("trace")
            start = at - total_s
            args = {k: ev.get(k) for k in
                    ("trace", "replica", "code", "attempts", "retried",
                     "tail", "net_ms", "queue_ms", "batch_ms",
                     "infer_ms", "fulfill_ms")
                    if ev.get(k) is not None}
            name = f"req {trace}" if trace else "req"
            if ev.get("tail"):
                name += " [tail]"
            evs.append(_x(name, start, total_s, pid, _TID_SERVE, args))
            spans = ev.get("spans")
            if spans:
                for sp in spans:
                    if not isinstance(sp, dict):
                        continue
                    dur = float(sp.get("dur_ms") or 0.0) / 1e3
                    evs.append(_x(
                        f"dispatch r{sp.get('replica')}",
                        start + float(sp.get("start_ms") or 0.0) / 1e3,
                        dur, pid, _TID_SERVE,
                        {"trace": trace, "replica": sp.get("replica"),
                         "code": sp.get("code")}))
            else:
                cursor = start
                for stage in ("net", "queue", "batch", "infer",
                              "fulfill"):
                    dur_ms = ev.get(f"{stage}_ms")
                    if not isinstance(dur_ms, (int, float)) \
                            or dur_ms <= 0:
                        continue
                    evs.append(_x(stage, cursor, dur_ms / 1e3, pid,
                                  _TID_SERVE, {"trace": trace}))
                    cursor += dur_ms / 1e3
        elif kind == "chaos":
            evs.append(_i(f"chaos {ev.get('kind')}", at, pid,
                          _TID_ROUNDS,
                          {k: v for k, v in ev.items()
                           if k not in ("event", "t", "run")}))
        elif kind in ("host_alive", "health", "recompile"):
            evs.append(_i(kind, at, pid, _TID_ROUNDS,
                          {k: v for k, v in ev.items()
                           if k not in ("event", "t", "run")}))
    return evs


def chrome_doc(ft):
    """FleetTrace -> Chrome trace_event document: one process per host
    (sorted, deterministic), labeled with the solved clock offset."""
    events = []
    pids = {}
    for idx, host in enumerate(ft.hosts):
        pid = idx + 1
        pids[host] = pid
        off = ft.offsets.get(host)
        if host == FLEET_TRACK:
            label = "fleet"
        elif off and off.get("aligned"):
            err = off.get("err_s")
            err_txt = "one-sided" if err is None \
                else f"±{err * 1e3:.1f}ms"
            label = (f"host {host} (offset "
                     f"{off['offset_s'] * 1e3:+.1f}ms {err_txt})")
        else:
            label = f"host {host} (unaligned)"
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": pid, "args": {"sort_index": idx}})
        for tid, tname in _TRACK_NAMES.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        events.extend(_host_events(ft, host, pid))
    offsets_meta = {str(h): rec for h, rec in sorted(
        ft.offsets.items(), key=lambda kv: str(kv[0]))
        if isinstance(h, int)}
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"clock_offsets": offsets_meta,
                          "hosts": [str(h) for h in ft.hosts]}}


def export_chrome(path, ft):
    """Write the merged fleet trace as a Chrome trace_event file."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_doc(ft), f)
    return path


def align_summary(ft):
    """Stable machine-readable alignment summary (report --format json
    and the report/monitor fleet sections render from this)."""
    n_beacons = sum(1 for evs in ft.events.values()
                    for ev in evs if ev.get("event") == "trace_align")
    return {"hosts": [str(h) for h in ft.hosts],
            "beacons": n_beacons,
            "offsets": {str(h): rec for h, rec in sorted(
                ft.offsets.items(), key=lambda kv: str(kv[0]))
                if isinstance(h, int)}}
