"""Per-sync-round critical-path attribution over the merged fleet
timeline (ISSUE 16) — The Mystery Machine's observation applied to our
own closed event schema: the causal structure of a sync round is known
(gate -> local round -> relay exchange -> gate), so the per-phase wall
time and the blocking host can be mined from the timestamped events the
runtime already emits, no new instrumentation.

The unit is the round CYCLE ending at gate-exit of round r: every host
announces arrival at r only after finishing its round r-1 work, so the
host that ENTERS gate r last is the host the whole fleet waited on —
its own gate wait is ~0 while everyone else's wait_s is the exposed
straggler time. That is exactly the chaos ``slow_host``/``slow_worker``
shape, which is what the end-to-end tests inject and expect named.

Each cycle's wall time decomposes into phases:

  gate_wait   max peer wait at gate r (exposed, blocked on host H)
  relay       consensus/relay IO (relay_io events, measured)
  h2d         host->device staging (h2d_stage events in the window)
  ingest      spans whose name marks the input pipeline
  compute     the remainder (local tau steps; a chaos stall that
              happens outside any instrumented phase lands here too)

The fleet summary ranks top blockers by exposed seconds and reuses
comms.py's byte/cost models to report structurally exposed vs
overlappable collective traffic next to the measured relay time.
"""

from collections import defaultdict

from .comms import broadcast_collect_bytes, ring_allreduce_bytes

#: span names counted as input-pipeline time
INGEST_NAMES = ("ingest", "batch", "feed", "stage", "shard")

#: below this wait spread (seconds) a round has no meaningful blocker
BALANCED_S = 0.02


def _gates(ft):
    """{round: {host: {"wait_s", "at"(ref exit time or None)}}} from
    host_round events, plus simfleet ``sim`` gate records under the
    observer-less FLEET view (host key "sim")."""
    out = defaultdict(dict)
    for host, evs in ft.events.items():
        for ev in evs:
            kind = ev.get("event")
            if kind == "host_round":
                r = ev.get("round")
                if not isinstance(r, int):
                    continue
                out[r][ev.get("observer", host)] = {
                    "wait_s": float(ev.get("wait_s") or 0.0),
                    "at": ft.place(host, ev)}
            elif kind == "sim":
                r = ev.get("round")
                if not isinstance(r, int):
                    continue
                out[r].setdefault("sim", {
                    "wait_s": float(ev.get("wait_s") or 0.0),
                    "at": ft.place(host, ev),
                    "live": ev.get("live"), "dead": ev.get("dead")})
    return dict(out)


def _windowed(evs, ft, host, lo, hi):
    """Events of one host placed inside (lo, hi] on the ref timeline."""
    if lo is None or hi is None:
        return []
    out = []
    for ev in evs:
        at = ft.place(host, ev)
        if at is not None and lo < at <= hi:
            out.append((at, ev))
    return out


def _host_components(ft, host, lo, hi):
    """One host's measured phase seconds inside its cycle window."""
    comp = {"relay": 0.0, "h2d": 0.0, "ingest": 0.0}
    for _, ev in _windowed(ft.events.get(host, []), ft, host, lo, hi):
        kind = ev.get("event")
        if kind == "relay_io":
            comp["relay"] += float(ev.get("seconds") or 0.0)
        elif kind == "h2d_stage":
            comp["h2d"] += (float(ev.get("dispatch_ms") or 0.0)
                            + float(ev.get("wait_ms") or 0.0)) / 1e3
        elif kind == "span":
            name = str(ev.get("name", "")).lower()
            if any(k in name for k in INGEST_NAMES):
                comp["ingest"] += float(ev.get("dur_ms") or 0.0) / 1e3
    return comp


def _blocker(gates_r):
    """(host, spread_s) — the host the round waited on, by latest gate
    ENTRY when placement exists for everyone, else by smallest wait
    (the last arriver waits for nobody). None when waits are too even
    to name one."""
    hosts = {h: g for h, g in gates_r.items() if h != "sim"}
    if len(hosts) < 2:
        return None, 0.0
    waits = {h: g["wait_s"] for h, g in hosts.items()}
    spread = max(waits.values()) - min(waits.values())
    if spread < BALANCED_S:
        return None, spread
    if all(g["at"] is not None for g in hosts.values()):
        entry = {h: g["at"] - g["wait_s"] for h, g in hosts.items()}
        host = max(sorted(entry), key=lambda h: entry[h])
    else:
        host = min(sorted(waits), key=lambda h: waits[h])
    return host, spread


def _chaos_for(ft, host, round_idx):
    """A chaos event corroborating this blocker, if the stream has one
    (attribution annotation only — the blocker itself is timing-derived)."""
    for evs in ft.events.values():
        for ev in evs:
            if ev.get("event") != "chaos":
                continue
            if ev.get("round") != round_idx:
                continue
            if ev.get("kind") in ("slow_host", "slow_worker") and \
                    (ev.get("host") == host or ev.get("worker") == host
                     or len(ft.events) <= 1):
                return ev.get("kind")
    return None


def compute(ft, round_filter=None):
    """FleetTrace -> {"rounds": [per-round dicts], "summary": {...}}.

    Per round r (the cycle ENDING at gate-exit r): wall seconds,
    blocking host, the blocker's dominant phase, and the fleet phase
    split. round_filter limits to one round index (CLI --round N)."""
    gates = _gates(ft)
    rounds = []
    prev_exit = {}
    for r in sorted(gates):
        g = gates[r]
        hosts = {h: rec for h, rec in g.items() if h != "sim"}
        sim = g.get("sim")
        waits = {h: rec["wait_s"] for h, rec in hosts.items()}
        if sim is not None and not hosts:
            waits = {"sim": sim["wait_s"]}
        gate_wait = max(waits.values()) if waits else 0.0
        blocker, spread = _blocker(g)
        # cycle window per host: previous gate exit -> this gate entry
        wall = None
        exits = {h: rec["at"] for h, rec in hosts.items()
                 if rec["at"] is not None}
        if sim is not None and sim["at"] is not None:
            exits.setdefault("sim", sim["at"])
        if exits and all(h in prev_exit for h in exits):
            wall = max(exits[h] - prev_exit[h] for h in exits)
        phases = {"gate_wait": round(gate_wait, 4), "relay": 0.0,
                  "h2d": 0.0, "ingest": 0.0, "compute": None}
        blocker_phase = None
        chaos_kind = None
        if blocker is not None:
            lo = prev_exit.get(blocker)
            rec = hosts.get(blocker)
            hi = None if rec is None or rec["at"] is None \
                else rec["at"] - rec["wait_s"]
            comp = _host_components(ft, blocker, lo, hi)
            busy = None if lo is None or hi is None else max(0.0, hi - lo)
            comp["compute"] = None if busy is None else \
                max(0.0, busy - sum(comp.values()))
            named = {k: v for k, v in comp.items() if v}
            blocker_phase = max(sorted(named), key=lambda k: named[k]) \
                if named else "compute"
            chaos_kind = _chaos_for(ft, blocker, r) \
                or _chaos_for(ft, blocker, r - 1)
        # fleet phase split: max per-host measured components in the
        # cycle, remainder is compute
        for h in hosts:
            comp = _host_components(ft, h, prev_exit.get(h),
                                    exits.get(h))
            for k in ("relay", "h2d", "ingest"):
                phases[k] = round(max(phases[k], comp[k]), 4)
        if wall is not None:
            phases["compute"] = round(
                max(0.0, wall - phases["gate_wait"] - phases["relay"]
                    - phases["h2d"] - phases["ingest"]), 4)
        rounds.append({"round": r,
                       "wall_s": None if wall is None
                       else round(wall, 4),
                       "blocker": blocker,
                       "blocker_phase": blocker_phase,
                       "chaos": chaos_kind,
                       "spread_s": round(spread, 4),
                       "waits": {str(h): round(w, 4)
                                 for h, w in sorted(
                                     waits.items(),
                                     key=lambda kv: str(kv[0]))},
                       "phases": phases})
        for h, at in exits.items():
            prev_exit[h] = at
    if round_filter is not None:
        rounds = [rec for rec in rounds if rec["round"] == round_filter]
    return {"rounds": rounds, "summary": _summary(ft, rounds)}


def _summary(ft, rounds):
    blocked = defaultdict(lambda: [0, 0.0])   # host -> [rounds, seconds]
    phase_tot = defaultdict(float)
    wall_tot = 0.0
    for rec in rounds:
        if rec["blocker"] is not None:
            b = blocked[str(rec["blocker"])]
            b[0] += 1
            b[1] += rec["phases"]["gate_wait"]
        for k, v in rec["phases"].items():
            if isinstance(v, (int, float)):
                phase_tot[k] += v
        if rec["wall_s"]:
            wall_tot += rec["wall_s"]
    top = sorted(blocked.items(),
                 key=lambda kv: (-kv[1][1], -kv[1][0], kv[0]))
    out = {"rounds": len(rounds),
           "wall_s": round(wall_tot, 4),
           "phase_totals": {k: round(v, 4)
                            for k, v in sorted(phase_tot.items())},
           "top_blockers": [{"host": h, "rounds_blocked": n,
                             "exposed_s": round(s, 4)}
                            for h, (n, s) in top[:5]]}
    comms = _comms_exposure(ft)
    if comms:
        out["comms"] = comms
    return out


def _comms_exposure(ft):
    """Exposed vs overlappable collective traffic from the newest
    ``comms`` event, plus the relay's measured seconds against the
    analytic ring/broadcast volumes for the same payload — the paper's
    cost model next to the measured wire time."""
    newest = None
    for evs in ft.events.values():
        for ev in evs:
            if ev.get("event") == "comms":
                newest = ev
    relay_s, relay_bytes, relay_n = 0.0, 0, 0
    hosts = [h for h in ft.hosts if isinstance(h, int)]
    for evs in ft.events.values():
        for ev in evs:
            if ev.get("event") == "relay_io":
                relay_s += float(ev.get("seconds") or 0.0)
                relay_bytes = max(relay_bytes, int(ev.get("bytes") or 0))
                relay_n += 1
    out = {}
    if newest is not None:
        for k in ("collective_bytes_per_step", "exposed_bytes_per_step",
                  "overlapped_bytes_per_step", "overlap_ceiling"):
            if newest.get(k) is not None:
                out[k] = newest[k]
    if relay_n:
        n = max(2, len(hosts))
        out["relay_rounds"] = relay_n
        out["relay_s_total"] = round(relay_s, 4)
        out["relay_payload_bytes"] = relay_bytes
        out["ring_allreduce_bytes"] = ring_allreduce_bytes(relay_bytes, n)
        out["broadcast_collect_bytes"] = \
            broadcast_collect_bytes(relay_bytes, n)
    return out


def render(cp, out=print, top_rounds=10):
    """Human-readable critical-path report (CLI `sparknet trace
    --critpath` and report.py's fleet section)."""
    rounds, summary = cp["rounds"], cp["summary"]
    out("critical path "
        f"({summary['rounds']} round(s), "
        f"{summary['wall_s']:.2f}s wall)")
    worst = sorted(rounds, key=lambda r: -(r["wall_s"] or
                                           r["phases"]["gate_wait"]))
    for rec in worst[:top_rounds]:
        wall = f"{rec['wall_s']:.3f}s" if rec["wall_s"] is not None \
            else "?"
        if rec["blocker"] is not None:
            chaos = f" [chaos {rec['chaos']}]" if rec["chaos"] else ""
            who = (f"blocked on host {rec['blocker']} "
                   f"({rec['blocker_phase']}){chaos}")
        else:
            who = "balanced"
        ph = rec["phases"]
        split = ", ".join(f"{k} {v:.3f}s" for k, v in ph.items()
                          if isinstance(v, (int, float)) and v > 0)
        out(f"  round {rec['round']}: wall {wall} — {who}"
            + (f" | {split}" if split else ""))
    if summary["top_blockers"]:
        out("  top blockers:")
        for b in summary["top_blockers"]:
            out(f"    host {b['host']}: blocked {b['rounds_blocked']} "
                f"round(s), {b['exposed_s']:.3f}s exposed")
    comms = summary.get("comms")
    if comms:
        if "exposed_bytes_per_step" in comms:
            out(f"  comms: exposed {comms['exposed_bytes_per_step']} "
                f"B/step vs overlapped "
                f"{comms.get('overlapped_bytes_per_step', 0)} B/step "
                f"(ceiling {comms.get('overlap_ceiling', 0)})")
        if "relay_rounds" in comms:
            out(f"  relay: {comms['relay_rounds']} exchange(s), "
                f"{comms['relay_s_total']:.3f}s measured, payload "
                f"{comms['relay_payload_bytes']} B (ring model "
                f"{comms['ring_allreduce_bytes']} B/chip, paper "
                f"broadcast+collect {comms['broadcast_collect_bytes']} "
                "B)")
