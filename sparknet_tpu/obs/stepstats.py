"""Step accounting: host dispatch vs device wall time, recompile
detection, and step-time percentiles.

The solver's async-dispatch discipline (solver.py) means the host-side
step time measures only *dispatch* — the device runs behind a queue and
fetching anything is a full round trip. So this module records the cheap
host dispatch time every step, and SAMPLES device wall time by blocking
on the step result at a low cadence (the first two observations, then
every ``sample_every``): the wall clock since the previous sample divided
by the steps in between is the true amortized per-step device time, queue
drain included.

Recompiles — the classic silent TPU perf killer (a shape change retraces
and recompiles mid-run) — are detected from the jitted callable's
``_cache_size()`` growth plus a feed-shape signature, and emitted as
``recompile`` events (the first compile is expected, flagged first=True).
"""

import time

import numpy as np


def percentiles(vals, qs=(50, 95, 99)):
    """Linear-interpolation percentiles of a sequence -> {"p50": ...}."""
    if not len(vals):
        return {}
    s = sorted(float(v) for v in vals)
    n = len(s)
    out = {}
    for q in qs:
        pos = q / 100.0 * (n - 1)
        lo = int(pos)
        hi = min(lo + 1, n - 1)
        out[f"p{q}"] = s[lo] + (s[hi] - s[lo]) * (pos - lo)
    return out


def device_memory(device=None):
    """HBM gauge where the backend exposes one (TPU/GPU; None on CPU)."""
    try:
        import jax
        d = device if device is not None else jax.local_devices()[0]
        ms = d.memory_stats()
    except Exception:
        return None
    if not ms:
        return None
    return {k: int(ms[k]) for k in ("bytes_in_use", "peak_bytes_in_use",
                                    "bytes_limit", "largest_alloc_size")
            if k in ms}


class StepAccounting:
    """Per-step accounting the solver calls once per train_step.

    Emits to the JSONL sink:
      step         at sampled steps — host_ms (this dispatch), sync_ms
                   (block_until_ready wait), device_ms (amortized per-step
                   wall since the previous sample), steps_since_sync
      recompile    whenever the jitted fn's executable cache grows
      hbm          at sampled steps, when the backend reports memory
      step_summary on flush() — full-histogram p50/p95/p99 + counts
    """

    def __init__(self, sink, sample_every=20, max_hist=8192, name="train"):
        self.sink = sink
        self.sample_every = max(1, int(sample_every))
        self.max_hist = max_hist
        self.name = name
        self.host_s = []            # ring buffer of host dispatch seconds
        self.device_s = []          # amortized device seconds per sample
        self.steps = 0
        self.recompiles = 0         # beyond the expected first compile
        self._last_cache = 0
        self._sig = None
        self._nobs = 0
        self._last_sample_it = None
        self._last_sample_t = None
        self._hbm_dead = False

    # -- internals ---------------------------------------------------------
    def _push_host(self, v):
        if len(self.host_s) < self.max_hist:
            self.host_s.append(v)
        else:                       # ring overwrite, keeps recent window
            self.host_s[self.steps % self.max_hist] = v

    def _check_recompile(self, it, jit_fn, batch):
        sig = None
        if batch is not None:
            try:
                sig = tuple(sorted(
                    (k, tuple(np.shape(v)), str(getattr(v, "dtype", "")))
                    for k, v in batch.items()))
            except Exception:
                sig = None
        cache = None
        if jit_fn is not None:
            try:
                cache = int(jit_fn._cache_size())
            except Exception:
                cache = None
        if cache is not None and cache > self._last_cache:
            first = self._last_cache == 0
            if not first:
                self.recompiles += 1
            reason = "first_compile" if first else (
                "shape_change" if sig is not None and self._sig is not None
                and sig != self._sig else "retrace")
            self.sink.log("recompile", iter=it, cache_size=cache,
                          first=first, reason=reason)
            self._last_cache = cache
        elif cache is None and sig is not None and self._sig is not None \
                and sig != self._sig:
            # no cache introspection available; shape tracking still works
            self.recompiles += 1
            self.sink.log("recompile", iter=it, cache_size=None,
                          first=False, reason="shape_change")
        if sig is not None:
            self._sig = sig

    # -- public API --------------------------------------------------------
    def observe(self, it, host_s, result=None, jit_fn=None, batch=None,
                sample=None):
        """Record one step. host_s: dispatch wall seconds. result: the
        step's output (blocked on at sample points). sample: None for the
        automatic cadence, True/False to force. Returns True when this
        step was sampled (i.e. the host already paid the device sync) —
        callers piggyback other fetch-costly sampling on it."""
        self.steps += 1
        self._push_host(host_s)
        self._check_recompile(it, jit_fn, batch)
        if sample is None:
            sample = self._nobs < 2 or self._last_sample_it is None \
                or (it - self._last_sample_it) >= self.sample_every
        self._nobs += 1
        if not sample or result is None:
            return False
        t0 = time.perf_counter()
        try:
            import jax
            jax.block_until_ready(result)
        except Exception:
            pass
        now = time.perf_counter()
        sync_s = now - t0
        ev = {"iter": it, "host_ms": round(host_s * 1e3, 3),
              "sync_ms": round(sync_s * 1e3, 3)}
        if self._last_sample_t is not None and self._last_sample_it is not None:
            k = max(1, it - self._last_sample_it)
            dev = (now - self._last_sample_t) / k
            self.device_s.append(dev)
            ev["device_ms"] = round(dev * 1e3, 3)
            ev["steps_since_sync"] = k
        else:
            # first sample: this step's full wall (dispatch + drain) is
            # the only device estimate available — dominated by compile
            dev = host_s + sync_s
            self.device_s.append(dev)
            ev["device_ms"] = round(dev * 1e3, 3)
            ev["steps_since_sync"] = 1
        self._last_sample_t = now
        self._last_sample_it = it
        self.sink.log("step", **ev)
        if not self._hbm_dead:
            mem = device_memory()
            if mem is None:
                self._hbm_dead = True       # CPU: don't re-probe per sample
            else:
                self.sink.log("hbm", iter=it, **mem)
        return True

    def summary(self):
        host = percentiles([v * 1e3 for v in self.host_s])
        dev = percentiles([v * 1e3 for v in self.device_s])
        out = {"steps": self.steps, "recompiles": self.recompiles,
               "device_samples": len(self.device_s)}
        out.update({f"host_ms_{k}": round(v, 3) for k, v in host.items()})
        out.update({f"device_ms_{k}": round(v, 3) for k, v in dev.items()})
        if self.host_s:
            out["host_ms_mean"] = round(
                sum(self.host_s) / len(self.host_s) * 1e3, 3)
            out["host_ms_max"] = round(max(self.host_s) * 1e3, 3)
        return out

    def flush(self, it=None):
        if self.steps:
            self.sink.log("step_summary", iter=it, name=self.name,
                          **self.summary())
