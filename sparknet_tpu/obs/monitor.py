"""`sparknet monitor` — live terminal view of a training run.

`sparknet report` is a post-mortem; this is the in-flight view. It tails
the metrics JSONL a run writes via --metrics (the same single stream the
whole obs subsystem shares) and renders a compact summary that refreshes
in place: current round/iter and loss, per-worker losses, worker
divergence with top offender layers, straggler flags, memory/compile
state, and the last health alarm. Pure file tailing — no jax imports, no
connection to the training process — so it works over any shared
filesystem, from any machine, against a live or finished run.

Partial trailing lines (the run is mid-write) are buffered until their
newline arrives; malformed lines are counted and skipped, never fatal.
"""

import collections
import json
import os
import sys
import threading
import time

from .report import MetricsFileError, _fmt_bytes, _num


class MonitorState:
    """Fold metrics events into the "now" view of a run.

    Thread contract: the live view ingests on a background tailer
    thread (monitor_file) while the main thread renders, so every
    mutable field is guarded by ``_lock`` (class-wide ``guarded-by-
    default`` annotation, enforced by `sparknet lint` SPK201/202);
    ``update``/``render`` take the lock, the ``_locked`` twins assume
    it."""
    # spk: guarded-by-default=_lock

    def __init__(self):
        self._lock = threading.Lock()
        self.events = 0
        self.bad_lines = 0
        self.by_type = collections.Counter()
        self.iter = None
        self.round = None
        self.loss = None
        self.min_loss = None
        self.lr = None
        self.rate = None            # (name, value)
        self.step = None            # last step event
        self.worker_loss = None
        self.divergence = None      # last divergence event
        self.memstats = None
        self.comms = None
        self.alarms = collections.Counter()
        self.last_alarm = None
        self.straggler_counts = collections.Counter()
        self.recompiles = 0
        self.recoveries = 0
        self.chaos = 0
        self.checkpoint_iter = None
        # elastic membership (resilience/elastic.py)
        self.live = None            # last reported live worker count
        self.evictions = collections.Counter()   # worker -> count
        self.last_eviction = None
        self.readmissions = 0
        self.quorum_lost = None
        # async bounded staleness (resilience/elastic.py, ISSUE 7)
        self.staleness = None       # last staleness event (lag/version)
        self.parks = collections.Counter()       # worker -> park count
        self.unparks = 0
        self.last_park = None
        # host fault domains (resilience/heartbeat.py)
        self.host_alive = {}        # host -> bool (last transition)
        self.host_lease_age = None  # last per-host lease-age vector
        self.host_gate = None       # last host_round event
        self.host_evictions = collections.Counter()
        self.host_joins = collections.Counter()
        self.last_host_join = None
        self.coordinated_restart = None
        # fleet simulation (sim/fleet.py, per-round summary)
        self.sim = None             # last sim event
        # fleet timeline (obs/fleettrace.py): clock-sync beacons plus
        # per-observer gate waits of the newest round — the live
        # blocker estimate (the full solve is `sparknet trace`)
        self.align_beacons = 0
        self.align_hosts = set()
        self.gate_waits = {}        # round -> {observer: wait_s}
        self.last_gate_round = None
        # elastic world resizing (resilience/checkpoint.py reshard)
        self.reshard = None         # last reshard event, if any
        # input pipeline (data/prefetch.py, data/ingest.py, ISSUE 13)
        self.prefetch = None        # last prefetch gauge event
        self.h2d_stage = None       # last h2d_stage event
        self.ingest_hosts = {}      # host -> last ingest event
        self.ingest_respreads = 0
        # serving tier (serve/server.py, ISSUE 11)
        self.serve_requests = 0
        self.serve_rows = 0
        self.serve_batches = 0
        self.serve_rejects = 0
        self.serve_reloads = 0
        self.serve_fill_sum = 0.0
        self.serve_lat_ms = collections.deque(maxlen=2048)
        self.last_serve_batch = None
        self.last_serve_reject = None
        self.last_serve_reload = None
        self.serve_summary = None
        # routing fleet (serve/fleet.py, `sparknet route`)
        self.route_dispatches = 0
        self.route_by_code = collections.Counter()
        self.route_retried = 0
        self.route_lat_ms = collections.deque(maxlen=2048)
        self.scale_events = []      # (action, reason, live)
        self.last_canary = None
        self.canary_rollbacks = 0
        # request tracing + SLO burn (obs/tracing.py, ISSUE 18)
        self.trace_count = 0
        self.trace_tails = 0
        self.trace_stage_ms = {
            k: collections.deque(maxlen=2048)
            for k in ("net", "queue", "batch", "infer", "fulfill")}
        self.trace_total_ms = collections.deque(maxlen=2048)
        self.last_burn = None
        self.burn_alerts = collections.Counter()
        self.done = None            # summary event, if the run finished

    def update(self, ev):               # spk: thread-entry
        with self._lock:
            self._update_locked(ev)

    def note_bad_line(self):            # spk: thread-entry
        with self._lock:
            self.bad_lines += 1

    def _update_locked(self, ev):       # spk: holds=_lock
        self.events += 1
        kind = ev.get("event", "?")
        self.by_type[kind] += 1
        if kind in ("train", "round"):
            if _num(ev.get("iter")):
                self.iter = ev["iter"]
            if _num(ev.get("round")):
                self.round = ev["round"]
            if _num(ev.get("loss")):
                self.loss = ev["loss"]
                self.min_loss = ev["loss"] if self.min_loss is None \
                    else min(self.min_loss, ev["loss"])
            if _num(ev.get("lr")):
                self.lr = ev["lr"]
            for r in ("images_per_sec", "tokens_per_sec", "images_per_s"):
                if _num(ev.get(r)):
                    self.rate = (r, ev[r])
        elif kind == "step":
            self.step = ev
            if _num(ev.get("iter")):
                self.iter = max(self.iter or 0, ev["iter"])
        elif kind == "divergence":
            self.divergence = ev
            if ev.get("worker_loss"):
                self.worker_loss = ev["worker_loss"]
            if _num(ev.get("round")):
                self.round = ev["round"]
        elif kind == "health":
            k = ev.get("kind", "?")
            self.alarms[k] += 1
            self.last_alarm = ev
            if k == "straggler" and ev.get("worker") is not None:
                self.straggler_counts[ev["worker"]] += 1
        elif kind == "memstats":
            self.memstats = ev
        elif kind == "comms":
            self.comms = ev
        elif kind == "recompile":
            if not ev.get("first"):
                self.recompiles += 1
        elif kind == "recovery":
            self.recoveries += 1
        elif kind == "chaos":
            self.chaos += 1
        elif kind == "checkpoint":
            if _num(ev.get("iter")):
                self.checkpoint_iter = ev["iter"]
        elif kind == "eviction":
            if ev.get("worker") is not None:
                self.evictions[ev["worker"]] += 1
            self.last_eviction = ev
            if _num(ev.get("live")):
                self.live = ev["live"]
        elif kind == "readmission":
            self.readmissions += 1
            if _num(ev.get("live")):
                self.live = ev["live"]
        elif kind == "membership":
            if ev.get("kind") == "quorum_lost":
                self.quorum_lost = ev
            if ev.get("kind") == "coordinated_restart":
                self.coordinated_restart = ev
            if _num(ev.get("live")):
                self.live = ev["live"]
        elif kind == "staleness":
            self.staleness = ev
        elif kind == "parked":
            if ev.get("worker") is not None:
                self.parks[ev["worker"]] += 1
            self.last_park = ev
        elif kind == "unparked":
            self.unparks += 1
        elif kind == "host_alive":
            if ev.get("host") is not None:
                self.host_alive[int(ev["host"])] = bool(ev.get("alive"))
        elif kind == "host_round":
            self.host_gate = ev
            if isinstance(ev.get("lease_age_s"), list):
                self.host_lease_age = ev["lease_age_s"]
            if _num(ev.get("round")) and ev.get("observer") is not None:
                r = int(ev["round"])
                self.gate_waits.setdefault(r, {})[int(ev["observer"])] \
                    = float(ev.get("wait_s") or 0.0)
                self.last_gate_round = r
                for old in sorted(self.gate_waits)[:-4]:
                    del self.gate_waits[old]
        elif kind == "trace_align":
            self.align_beacons += 1
            for f in ("observer", "peer"):
                if isinstance(ev.get(f), int):
                    self.align_hosts.add(ev[f])
        elif kind == "host_evicted":
            if ev.get("host") is not None:
                self.host_evictions[int(ev["host"])] += 1
        elif kind == "host_joined":
            if ev.get("host") is not None:
                self.host_joins[int(ev["host"])] += 1
                self.host_alive[int(ev["host"])] = True
            self.last_host_join = ev
        elif kind == "sim":
            self.sim = ev
            if _num(ev.get("round")):
                self.round = ev["round"]
        elif kind == "reshard":
            self.reshard = ev
        elif kind == "prefetch":
            self.prefetch = ev
        elif kind == "h2d_stage":
            self.h2d_stage = ev
        elif kind == "ingest":
            if ev.get("host") is not None:
                self.ingest_hosts[int(ev["host"])] = ev
            if ev.get("kind") == "respread":
                self.ingest_respreads += 1
        elif kind == "serve_request":
            self.serve_requests += 1
            if _num(ev.get("rows")):
                self.serve_rows += ev["rows"]
            if _num(ev.get("latency_ms")):
                self.serve_lat_ms.append(ev["latency_ms"])
        elif kind == "serve_batch":
            self.serve_batches += 1
            if _num(ev.get("fill")):
                self.serve_fill_sum += ev["fill"]
            self.last_serve_batch = ev
            if _num(ev.get("iter")):
                self.iter = max(self.iter or 0, ev["iter"])
        elif kind == "serve_reject":
            self.serve_rejects += 1
            self.last_serve_reject = ev
        elif kind == "serve_reload":
            self.serve_reloads += 1
            self.last_serve_reload = ev
        elif kind == "serve_summary":
            self.serve_summary = ev
        elif kind == "route":
            self.route_dispatches += 1
            if _num(ev.get("code")):
                self.route_by_code[int(ev["code"])] += 1
            if ev.get("retried"):
                self.route_retried += 1
            if _num(ev.get("latency_ms")):
                self.route_lat_ms.append(ev["latency_ms"])
        elif kind == "scale":
            self.scale_events.append((ev.get("action"),
                                      ev.get("reason"), ev.get("live")))
        elif kind == "canary":
            self.last_canary = ev
            if ev.get("action") == "rollback":
                self.canary_rollbacks += 1
        elif kind == "serve_trace":
            self.trace_count += 1
            if ev.get("tail"):
                self.trace_tails += 1
            if _num(ev.get("total_ms")):
                self.trace_total_ms.append(ev["total_ms"])
            for k, dq in self.trace_stage_ms.items():
                if _num(ev.get(f"{k}_ms")):
                    dq.append(ev[f"{k}_ms"])
        elif kind == "slo_burn":
            self.last_burn = ev
            if ev.get("alert"):
                self.burn_alerts[str(ev["alert"])] += 1
        elif kind == "summary":
            self.done = ev

    # -- rendering ---------------------------------------------------------
    @staticmethod
    def _fmt_workers(vals, fmt="{:.4g}"):
        return "[" + " ".join(fmt.format(v) for v in vals) + "]"

    def render(self, path=""):
        with self._lock:
            return self._render_locked(path)

    def _render_locked(self, path):     # spk: holds=_lock
        L = []
        status = "FINISHED" if self.done else "live"
        L.append(f"sparknet monitor — {path} ({self.events} events, "
                 f"{self.bad_lines} bad lines, {status})")
        pos = []
        if self.round is not None:
            pos.append(f"round {self.round}")
        if self.iter is not None:
            pos.append(f"iter {self.iter}")
        if self.loss is not None:
            pos.append(f"loss {self.loss:.6g}"
                       + (f" (min {self.min_loss:.6g})"
                          if self.min_loss is not None else ""))
        if self.lr is not None:
            pos.append(f"lr {self.lr:.4g}")
        if self.rate:
            pos.append(f"{self.rate[0]} {self.rate[1]:,.0f}")
        if pos:
            L.append("  " + "  ".join(pos))
        if self.step:
            bits = [f"host {self.step.get('host_ms', '?')} ms",
                    f"device {self.step.get('device_ms', '?')} ms"]
            if self.recompiles:
                bits.append(f"recompiles {self.recompiles}")
            L.append("  step: " + "  ".join(bits))
        if self.worker_loss:
            L.append("  workers: loss " + self._fmt_workers(self.worker_loss)
                     + f"  skew {max(self.worker_loss) - min(self.worker_loss):.4g}")
        d = self.divergence
        if d:
            line = f"  divergence: mean {d.get('mean', 0):.4g} " \
                   f"max {d.get('max', 0):.4g}"
            if _num(d.get("rel")):
                line += f"  rel {d['rel']:.3g}"
            if _num(d.get("gns_proxy")):
                line += f"  gns~{d['gns_proxy']:.3g}"
            if d.get("tau"):
                line += f"  tau={d['tau']}"
            L.append(line)
            if d.get("top_layers"):
                L.append("    top layers: " + ", ".join(
                    f"{k}={v:.3g}" for k, v in d["top_layers"]))
        if self.evictions or self.quorum_lost or self.readmissions:
            bits = []
            if self.live is not None:
                bits.append(f"{self.live} live")
            bits.append(f"evictions {sum(self.evictions.values())}"
                        + (" (" + ", ".join(
                            f"w{w}:{c}" for w, c in
                            self.evictions.most_common()) + ")"
                           if self.evictions else ""))
            if self.readmissions:
                bits.append(f"readmissions {self.readmissions}")
            L.append("  membership: " + "  ".join(bits))
            if self.last_eviction is not None:
                e = self.last_eviction
                L.append(f"    last eviction: worker {e.get('worker')} "
                         f"round {e.get('round')} ({e.get('reason')})")
            if self.quorum_lost is not None:
                q = self.quorum_lost
                L.append(f"    QUORUM LOST: {q.get('live')} live < "
                         f"quorum {q.get('quorum')}")
        if self.staleness or self.parks or self.unparks:
            bits = []
            st = self.staleness or {}
            if _num(st.get("s")):
                bits.append(f"s={st['s']}")
            if isinstance(st.get("lag"), list):
                bits.append("lag " + self._fmt_workers(st["lag"], "{:d}"))
            if isinstance(st.get("parked"), list) and st["parked"]:
                bits.append(f"parked {st['parked']}")
            bits.append(f"parks {sum(self.parks.values())}"
                        + (" (" + ", ".join(
                            f"w{w}:{c}" for w, c in
                            self.parks.most_common()) + ")"
                           if self.parks else ""))
            if self.unparks:
                bits.append(f"unparks {self.unparks}")
            L.append("  staleness: " + "  ".join(bits))
            if self.last_park is not None:
                p = self.last_park
                L.append(f"    last park: {p.get('unit', 'worker')} "
                         f"{p.get('worker')} round {p.get('round')} "
                         f"(lag {p.get('lag')})")
        if (self.host_alive or self.host_gate or self.host_evictions
                or self.host_joins):
            bits = []
            if self.host_alive:
                down = sorted(h for h, a in self.host_alive.items() if not a)
                up = sorted(h for h, a in self.host_alive.items() if a)
                bits.append(f"up {up}" + (f" DOWN {down}" if down else ""))
            if self.host_evictions:
                bits.append("evicted " + ", ".join(
                    f"h{h}:{c}" for h, c in self.host_evictions.most_common()))
            if self.host_joins:
                bits.append("joined " + ", ".join(
                    f"h{h}" for h in sorted(self.host_joins)))
            if self.host_gate and _num(self.host_gate.get("wait_s")):
                bits.append(f"gate wait {self.host_gate['wait_s']:.3f}s "
                            f"@r{self.host_gate.get('round')}")
            L.append("  hosts: " + "  ".join(bits))
            if self.last_host_join is not None:
                j = self.last_host_join
                L.append(f"    last join: host {j.get('host')} at round "
                         f"{j.get('round')} ({j.get('via')}, world -> "
                         f"{j.get('world')})")
            if self.host_lease_age:
                L.append("    lease ages: " + " ".join(
                    f"{a:.2f}s" for a in self.host_lease_age))
            if self.coordinated_restart is not None:
                cr = self.coordinated_restart
                L.append("    coordinated restart "
                         + ("AGREED" if cr.get("agreed") else "DISAGREED")
                         + f" across hosts {cr.get('hosts')}")
        waits = self.gate_waits.get(self.last_gate_round) or {}
        if self.align_beacons or len(waits) > 1:
            bits = []
            if self.align_beacons:
                bits.append(f"{self.align_beacons} clock beacon(s) over "
                            f"{len(self.align_hosts)} host(s)")
            if len(waits) > 1:
                spread = max(waits.values()) - min(waits.values())
                if spread >= 0.02:
                    # the host that waited least entered the gate last —
                    # everyone else's wait is its exposed straggle
                    blk = min(sorted(waits), key=lambda h: waits[h])
                    bits.append(f"r{self.last_gate_round} blocked on "
                                f"host {blk} ({spread:.3f}s exposed)")
                else:
                    bits.append(f"r{self.last_gate_round} balanced")
            L.append("  fleet: " + "  ".join(bits))
        if self.sim is not None:
            s = self.sim
            bits = [f"{s.get('hosts')} hosts",
                    f"round {s.get('round')}",
                    f"live {s.get('live')}"]
            if _num(s.get("parked")) and s["parked"]:
                bits.append(f"parked {s['parked']}")
            if _num(s.get("wait_s")):
                bits.append(f"wait {s['wait_s']:.3f}s")
            tot = [f"{k} {s[k]}" for k in
                   ("evictions", "readmissions", "admissions")
                   if _num(s.get(k)) and s[k]]
            L.append("  sim: " + "  ".join(bits + tot))
        if self.serve_requests or self.serve_rejects or self.serve_summary:
            from .stepstats import percentiles
            bits = [f"requests {self.serve_requests}",
                    f"batches {self.serve_batches}"]
            if self.serve_rejects:
                bits.append(f"rejects {self.serve_rejects}")
            if self.serve_reloads:
                bits.append(f"reloads {self.serve_reloads}")
            if self.serve_lat_ms:
                p = percentiles(list(self.serve_lat_ms))
                bits.append(f"p50 {p['p50']:.1f}ms p99 {p['p99']:.1f}ms")
            if self.serve_batches:
                bits.append(
                    f"fill {self.serve_fill_sum / self.serve_batches:.0%}")
            L.append("  serving: " + "  ".join(bits))
            sb = self.last_serve_batch
            if sb is not None:
                L.append(f"    last batch: {sb.get('size')} rows -> "
                         f"bucket {sb.get('bucket')} "
                         f"({sb.get('infer_ms')} ms, "
                         f"depth {sb.get('queue_depth')})")
            if self.last_serve_reload is not None:
                r = self.last_serve_reload
                L.append(f"    hot reload: iter {r.get('iter')} "
                         f"(was {r.get('from_iter')}) in {r.get('ms')} ms")
            if self.last_serve_reject is not None:
                rj = self.last_serve_reject
                L.append(f"    last reject: {rj.get('reason')} "
                         f"(depth {rj.get('queue_depth')}/"
                         f"{rj.get('limit')})")
            if self.serve_summary is not None and \
                    self.serve_summary.get("drained"):
                L.append("    drained cleanly")
        if self.route_dispatches or self.scale_events or self.last_canary:
            from .stepstats import percentiles
            ok = self.route_by_code.get(200, 0)
            bits = [f"dispatches {self.route_dispatches}"]
            if self.route_dispatches:
                bits.append(f"avail {ok / self.route_dispatches:.1%}")
            if self.route_retried:
                bits.append(f"retried {self.route_retried}")
            bad = {c: n for c, n in sorted(self.route_by_code.items())
                   if c != 200}
            if bad:
                bits.append("codes " + " ".join(
                    f"{c}:{n}" for c, n in bad.items()))
            if self.route_lat_ms:
                p = percentiles(list(self.route_lat_ms))
                bits.append(f"p99 {p['p99']:.1f}ms")
            L.append("  routing: " + "  ".join(bits))
            if self.scale_events:
                a, reason, live = self.scale_events[-1]
                L.append(f"    scale: {len(self.scale_events)} "
                         f"decision(s); last {a} ({reason}) "
                         f"at live {live}")
            if self.last_canary is not None:
                c = self.last_canary
                line = f"    canary: {c.get('action')} " \
                       f"sha={c.get('sha')} " \
                       f"(baseline {c.get('baseline_sha')})"
                if self.canary_rollbacks:
                    line += f"  rollbacks {self.canary_rollbacks}"
                L.append(line)
        if self.trace_count:
            from .stepstats import percentiles
            bits = [f"traces {self.trace_count}",
                    f"tails {self.trace_tails}"]
            if self.trace_total_ms:
                p = percentiles(list(self.trace_total_ms))
                bits.append(f"total p99 {p['p99']:.1f}ms")
            stage_p99 = {k: percentiles(list(dq))["p99"]
                         for k, dq in self.trace_stage_ms.items() if dq}
            if stage_p99:
                top = max(stage_p99.items(), key=lambda kv: kv[1])
                bits.append(f"top stage {top[0]} ({top[1]:.1f}ms)")
            L.append("  tracing: " + "  ".join(bits))
            if stage_p99:
                L.append("    stage p99: " + "  ".join(
                    f"{k} {v:.1f}ms"
                    for k, v in sorted(stage_p99.items(),
                                       key=lambda kv: -kv[1])))
        if self.last_burn is not None:
            b = self.last_burn
            bits = [f"fast x{b.get('fast')}/{b.get('fast_long')}",
                    f"slow x{b.get('slow')}/{b.get('slow_long')}"]
            if _num(b.get("budget_left")):
                bits.append(f"budget left {b['budget_left']:.1%}")
            if b.get("alert"):
                bits.append(f"ALERT {b['alert']}")
            if self.burn_alerts:
                bits.append("alerts " + " ".join(
                    f"{k}:{n}" for k, n in sorted(
                        self.burn_alerts.items())))
            L.append("  slo burn: " + "  ".join(bits))
        if self.straggler_counts:
            worst = self.straggler_counts.most_common(1)[0]
            L.append(f"  stragglers: worker {worst[0]} flagged "
                     f"{worst[1]}x" + (
                         "  (others: " + ", ".join(
                             f"w{w}:{c}" for w, c in
                             self.straggler_counts.most_common()[1:]) + ")"
                         if len(self.straggler_counts) > 1 else ""))
        m = self.memstats
        if m:
            bits = []
            if _num(m.get("live_bytes")):
                bits.append(f"live {_fmt_bytes(m['live_bytes'])} "
                            f"({m.get('live_arrays', '?')} arrays)")
            if _num(m.get("hbm_peak_bytes_in_use")):
                bits.append(
                    f"hbm peak {_fmt_bytes(m['hbm_peak_bytes_in_use'])}")
            if _num(m.get("compile_cache")):
                bits.append(f"compile cache {m['compile_cache']}")
            if _num(m.get("host_rss_bytes")):
                bits.append(f"rss {_fmt_bytes(m['host_rss_bytes'])}")
            if bits:
                L.append("  memory: " + "  ".join(bits))
        if self.comms and _num(self.comms.get("collective_bytes_per_step")):
            L.append("  comms: "
                     f"{_fmt_bytes(self.comms['collective_bytes_per_step'])}"
                     "/step collective, h2d total "
                     f"{_fmt_bytes(self.comms.get('h2d_bytes_total'))}")
        if self.prefetch or self.h2d_stage or self.ingest_hosts:
            bits = []
            pf = self.prefetch or {}
            if pf.get("name"):
                bits.append(f"{pf['name']}")
            if _num(pf.get("echo")) and pf["echo"] > 1:
                bits.append(f"echo x{pf['echo']}")
            if pf.get("wire") and pf.get("wire") != "raw":
                bits.append(f"wire {pf['wire']}")
            if _num(pf.get("h2d_kb_per_image")):
                bits.append(f"{pf['h2d_kb_per_image']} KB/img")
            st = self.h2d_stage
            if st:
                bits.append(f"staged {st.get('puts', 0)} "
                            f"({st.get('kb_per_item', '?')} KB/item, "
                            f"wait {st.get('wait_ms', '?')} ms, "
                            f"{st.get('in_flight', '?')}/"
                            f"{st.get('slots', '?')} in flight)")
            if self.ingest_hosts:
                bits.append(f"ingest {len(self.ingest_hosts)} host(s)"
                            + (f", {self.ingest_respreads} re-spread(s)"
                               if self.ingest_respreads else ""))
            if bits:
                L.append("  feed: " + "  ".join(bits))
            for h, e in sorted(self.ingest_hosts.items()):
                rng = (f" [{e['lo']}..{e['hi']}]"
                       if _num(e.get("lo")) and e["lo"] >= 0 else "")
                L.append(f"    ingest host {h}: {e.get('records')} "
                         f"record(s){rng}, {e.get('reads', 0)} read(s)")
        extras = []
        if self.recoveries:
            extras.append(f"recoveries {self.recoveries}")
        if self.chaos:
            extras.append(f"chaos injections {self.chaos}")
        if self.checkpoint_iter is not None:
            extras.append(f"last checkpoint iter {self.checkpoint_iter}")
        if self.reshard is not None:
            extras.append(
                f"resharded ({self.reshard.get('direction')}) "
                f"{self.reshard.get('n_from')} -> "
                f"{self.reshard.get('n_to')} slots")
        if extras:
            L.append("  " + "  ".join(extras))
        if self.alarms:
            L.append("  alarms: " + ", ".join(
                f"{k}: {v}" for k, v in sorted(self.alarms.items())))
        a = self.last_alarm
        if a:
            detail = " ".join(f"{k}={v}" for k, v in a.items()
                              if k not in ("event", "t", "kind", "severity"))
            L.append(f"  last alarm: [{a.get('kind')}] {detail}")
        elif self.by_type.get("health") == 0 or not self.alarms:
            L.append("  no health alarms")
        return "\n".join(L)


class _Tail:
    """Incremental JSONL reader: returns complete new lines per poll,
    buffers a partial trailing line, survives truncation by reopening."""

    def __init__(self, path):
        self.path = path
        self.pos = 0
        self.buf = ""

    def poll(self):
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.pos:            # truncated/rotated: start over
            self.pos, self.buf = 0, ""
        if size == self.pos:
            return []
        with open(self.path, "r", errors="replace") as f:
            f.seek(self.pos)
            chunk = f.read()
            self.pos = f.tell()
        self.buf += chunk
        lines = self.buf.split("\n")
        self.buf = lines.pop()         # '' after a complete final line
        return lines


def monitor_file(path, interval=1.0, once=False, wait=False,
                 duration=None, out=None, clear=None):
    """Tail ``path`` and render the live summary every ``interval``
    seconds. once=True renders the current state and returns. wait=True
    blocks for the file to appear (a run that hasn't started writing
    yet) instead of erroring. Returns the final MonitorState."""
    write = out or (lambda s: print(s, flush=True))
    t0 = time.time()
    while not os.path.exists(path):
        if not wait:
            raise MetricsFileError(f"metrics file not found: {path}")
        if duration is not None and time.time() - t0 > duration:
            raise MetricsFileError(
                f"metrics file never appeared: {path}")
        time.sleep(min(interval, 0.5))
    tail = _Tail(path)
    state = MonitorState()
    if clear is None:
        clear = sys.stdout.isatty()

    def ingest():
        got = False
        for line in tail.poll():
            line = line.strip()
            if not line:
                continue
            got = True
            try:
                ev = json.loads(line)
            except ValueError:
                state.note_bad_line()
                continue
            if isinstance(ev, dict):
                state.update(ev)
            else:
                state.note_bad_line()
        return got

    ingest()
    if once:
        if state.events == 0 and state.bad_lines == 0:
            raise MetricsFileError(f"metrics file is empty: {path}")
        write(state.render(path))
        return state
    # live view: a background tailer thread ingests continuously (the
    # _Tail cursor is confined to it between start and join), so a slow
    # terminal write or a long --interval never backs the cursor up;
    # MonitorState's lock makes the concurrent update/render safe (the
    # discipline `sparknet lint`'s SPK201 checker enforces)
    stop = threading.Event()
    pump_err = []

    def pump():
        while not stop.wait(min(interval, 0.5)):
            try:
                ingest()
            except Exception as e:      # surfaced on the render side
                pump_err.append(e)
                return

    tailer = threading.Thread(target=pump, daemon=True,
                              name="sparknet-monitor-tail")
    tailer.start()
    try:
        while True:
            write(("\x1b[2J\x1b[H" if clear else "")
                  + state.render(path) + ("" if clear else "\n"))
            if pump_err:
                raise pump_err[0]
            if duration is not None and time.time() - t0 >= duration:
                break
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        tailer.join(timeout=2.0)
    if not pump_err:
        ingest()                        # final drain (tailer has quit)
    return state
