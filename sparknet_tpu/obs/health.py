"""Training-dynamics anomaly detectors: the sensors that say WHEN to act.

PR 1 measured (steps, comms, spans), PR 2 reacted (rollback, retries,
resume); this module sits between them. A HealthMonitor ingests the
per-sync-round signals the solvers already materialize — per-worker
losses, per-worker round latencies, the divergence summary from
obs/divergence.py — runs rolling anomaly detectors over them, and emits
structured ``health`` events the report/monitor render and supervisors
can alert on:

  straggler         one worker's round latency stretches past
                    ``straggler_factor`` x the median of the others (a
                    synchronous round is as slow as its slowest worker —
                    the paper's broadcast/collect stalls on it)
  loss_skew         the spread of per-worker losses jumps past
                    ``loss_skew_factor`` x its own rolling EMA (and the
                    ``loss_skew_min`` absolute floor, so noise-level
                    spreads never alarm) — one shard is training on
                    different-looking data or a replica is going bad
  worker_nonfinite  a single worker's loss is NaN/inf while others are
                    healthy (an averaged NaN poisons everyone at the
                    next sync; this names the culprit BEFORE the pmean)
  divergence_trend  mean worker divergence grew ``trend_rounds``
                    observations in a row by ``trend_factor`` total —
                    tau is outrunning the averaging
  divergence_high   divergence crossed the absolute ``div_abs`` ceiling
  worker_masked     the compiled round's validity mask zeroed out a
                    worker the host still considers alive (its replica
                    went non-finite mid-round; the masked consensus of
                    resilience/elastic.py already excluded it — this
                    alarm is the paper trail, and the eviction streak
                    the ElasticPolicy acts on)
  host_down         a peer HOST's heartbeat lease expired (resilience/
                    heartbeat.py) — the fault-domain-granularity crash
                    signal; the eviction itself is the ElasticPolicy's,
                    this alarm is the sensor-side paper trail
  host_lease        a live host's lease age crossed half the lease —
                    it is still in the membership but its heartbeats
                    are lagging (pre-failure warning)
  staleness_high    (async bounded-staleness mode) a live worker's
                    version lag reached the staleness bound s — its
                    pushes are about to be excluded; carries
                    ``suggest_s`` (a bound that would keep it
                    contributing, the staleness twin of suggest_tau)
  parked_worker     the bound was hit: a worker is PARKED — excluded
                    from the consensus until it resyncs. By design, not
                    a failure, but the paper trail an operator needs to
                    tell "one chronic straggler" from "the whole fleet
                    thrashing" (check parks-by-worker in the report)

With an ElasticPolicy armed, the detectors receive the alive mask and
skip evicted workers — a dead slot's (masked, meaningless) latency or
NaN loss must not keep the straggler/skew alarms firing.

Alarms can *arm* the existing resilience RecoveryPolicy (the solver
rolls back instead of averaging poison) and carry a tau suggestion —
divergence alarms suggest halving tau (sync more often), a quiet run
with relatively tiny divergence suggests raising it. Every detector has
a per-kind cooldown so a persistent condition logs once per
``cooldown`` observations, not once per round.
"""

import collections
import math

import numpy as np


def _finite(v):
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


class HealthMonitor:
    """observe_round(...) once per materialized sync round (or sampled
    DP step). All detectors are independent; missing inputs simply skip
    their detector, so any solver can feed whatever it has."""

    def __init__(self, sink, log_fn=print, solver=None,
                 straggler_factor=1.5, straggler_min_s=0.05,
                 loss_skew_factor=3.0, loss_skew_min=0.01,
                 skew_ema_decay=0.8,
                 trend_rounds=5, trend_factor=2.0, div_abs=0.0,
                 cooldown=5, arm_recovery=False, recovery_kw=None):
        self.sink = sink
        self.log = log_fn or (lambda *a: None)
        self.solver = solver
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_s = float(straggler_min_s)
        self.loss_skew_factor = float(loss_skew_factor)
        self.loss_skew_min = float(loss_skew_min)
        self.skew_ema_decay = float(skew_ema_decay)
        self.trend_rounds = max(2, int(trend_rounds))
        self.trend_factor = float(trend_factor)
        self.div_abs = float(div_abs)
        self.cooldown = max(1, int(cooldown))
        self.arm_recovery = bool(arm_recovery)
        self.recovery_kw = dict(recovery_kw or {})

        self.alarms = 0
        self.last_alarm = None
        self.straggler_counts = collections.Counter()
        self.tau_suggestion = None
        self.s_suggestion = None
        self._obs = 0
        self._last_fired = {}           # kind -> observation index
        self._skew_ema = None
        self._div_window = collections.deque(maxlen=self.trend_rounds)

    # -- alarm plumbing ----------------------------------------------------
    def _alarm(self, kind, severity="warn", suggest_tau=None,
               suggest_s=None, **fields):
        if self._obs - self._last_fired.get(kind, -10**9) < self.cooldown:
            return None
        self._last_fired[kind] = self._obs
        self.alarms += 1
        ev = {"kind": kind, "severity": severity}
        ev.update(fields)
        if suggest_tau is not None:
            ev["suggest_tau"] = int(suggest_tau)
            self.tau_suggestion = int(suggest_tau)
        if suggest_s is not None:
            ev["suggest_s"] = int(suggest_s)
            self.s_suggestion = int(suggest_s)
        self.last_alarm = ev
        self.log("health: " + kind + " " + " ".join(
            f"{k}={v}" for k, v in fields.items())
            + (f" (suggest tau={suggest_tau})"
               if suggest_tau is not None else "")
            + (f" (suggest s={suggest_s})"
               if suggest_s is not None else ""))
        if self.sink is not None:
            self.sink.log("health", **ev)
        if severity == "critical":
            self._maybe_arm_recovery(kind)
        return ev

    def _maybe_arm_recovery(self, kind):
        """A critical alarm arms the resilience RecoveryPolicy on the
        attached solver (if it has none yet), so the NEXT bad loss rolls
        back instead of averaging poison."""
        s = self.solver
        if not self.arm_recovery or s is None or \
                getattr(s, "recovery", None) is not None or \
                not hasattr(s, "arm_recovery"):
            return
        try:
            s.arm_recovery(**self.recovery_kw)
            self.log(f"health: armed RecoveryPolicy after {kind} alarm")
            if self.sink is not None:
                self.sink.log("health", kind="recovery_armed", cause=kind,
                              severity="info")
        except Exception as e:      # monitoring must never kill the run
            self.log(f"health: failed to arm recovery: {e!r}")

    def _tau(self):
        return getattr(self.solver, "tau", None) if self.solver else None

    # -- detectors ---------------------------------------------------------
    @staticmethod
    def _live_subset(vec, live):
        """(values, global_worker_ids) restricted to live workers —
        evicted workers' signals are masked garbage, not anomalies."""
        vec = np.asarray(vec, np.float64).ravel()
        if live is None:
            return vec, np.arange(vec.size)
        idx = np.asarray([w for w in live if w < vec.size], np.int64)
        return vec[idx], idx

    def _check_stragglers(self, it, round_idx, latencies, live=None):
        lat, ids = self._live_subset(latencies, live)
        if lat.size < 2:
            return
        w = int(np.argmax(lat))
        others = np.delete(lat, w)
        med = float(np.median(others))
        if lat[w] - med < self.straggler_min_s:
            return
        ratio = float(lat[w] / max(med, 1e-9))
        if ratio < self.straggler_factor:
            return
        worker = int(ids[w])
        self.straggler_counts[worker] += 1
        self._alarm("straggler", iter=it, round=round_idx, worker=worker,
                    latency_s=round(float(lat[w]), 4),
                    median_s=round(med, 4), ratio=round(ratio, 3),
                    times_flagged=self.straggler_counts[worker])

    def _check_loss_skew(self, it, round_idx, worker_losses, live=None):
        wl, ids = self._live_subset(worker_losses, live)
        if wl.size < 2:
            return
        finite = np.isfinite(wl)
        if not finite.all():
            for w in np.nonzero(~finite)[0]:
                self._alarm("worker_nonfinite", severity="critical",
                            iter=it, round=round_idx, worker=int(ids[w]),
                            loss=str(wl[w]))
            return
        skew = float(wl.max() - wl.min())
        prior = self._skew_ema
        self._skew_ema = skew if prior is None else \
            self.skew_ema_decay * prior + (1 - self.skew_ema_decay) * skew
        if prior is None:
            return
        if skew > self.loss_skew_factor * max(prior, 1e-9) and \
                skew > self.loss_skew_min:
            self._alarm("loss_skew", iter=it, round=round_idx,
                        skew=round(skew, 6), ema=round(prior, 6),
                        worker=int(ids[int(np.argmax(wl))]),
                        worker_losses=[round(float(x), 6) for x in wl])

    def _check_validity(self, it, round_idx, valid, live=None):
        """A live worker the device mask zeroed out: its replica went
        non-finite inside the round. The masked consensus already kept
        it out of the average; this records WHO, per round, so the
        membership policy's eviction streaks have a paper trail."""
        v, ids = self._live_subset(valid, live)
        for i in range(v.size):
            if not v[i] > 0:
                self._alarm("worker_masked", severity="critical",
                            iter=it, round=round_idx, worker=int(ids[i]))

    def _check_staleness(self, it, round_idx, lag, parked, bound,
                         live=None):
        """Async-mode detectors: a live worker whose version lag reached
        the bound is about to be excluded (staleness_high, with a
        suggest_s that would keep it in), and every freshly-parked
        worker gets a parked_worker record. Evicted workers' lag is
        masked garbage and is skipped like every other signal."""
        if bound is None:
            return
        parked = set(int(w) for w in (parked or ()))
        lagv, ids = self._live_subset(lag, live)
        for i in range(lagv.size):
            w = int(ids[i])
            if w in parked:
                self._alarm("parked_worker", iter=it, round=round_idx,
                            worker=w, lag=int(lagv[i]), s=int(bound))
            elif bound > 0 and lagv[i] >= bound:
                # one more slow round and it parks: suggest the bound
                # that would keep this straggler contributing
                self._alarm("staleness_high", iter=it, round=round_idx,
                            worker=w, lag=int(lagv[i]), s=int(bound),
                            suggest_s=int(lagv[i]) + 1)

    def _check_divergence(self, it, round_idx, div):
        mean = div.get("mean")
        if not _finite(mean):
            return
        mean = float(mean)
        tau = div.get("tau", self._tau())
        half = max(1, tau // 2) if tau and tau > 1 else None
        if self.div_abs > 0 and mean > self.div_abs:
            self._alarm("divergence_high", severity="critical", iter=it,
                        round=round_idx, mean=round(mean, 8),
                        threshold=self.div_abs, suggest_tau=half)
        self._div_window.append(mean)
        w = list(self._div_window)
        if len(w) == self.trend_rounds and \
                all(b > a > 0 for a, b in zip(w, w[1:])) and \
                w[-1] >= self.trend_factor * w[0]:
            self._alarm("divergence_trend", iter=it, round=round_idx,
                        mean=round(mean, 8),
                        grew=f"x{w[-1] / max(w[0], 1e-20):.2f} over "
                             f"{self.trend_rounds} rounds",
                        suggest_tau=half)

    def observe_hosts(self, round_idx, alive=None, lease_age_s=None,
                      lease_s=None, wait_s=None):
        """Feed one round gate's host-liveness view (resilience/
        heartbeat.py): ``alive`` the per-host mask, ``lease_age_s`` the
        per-host lease ages, ``lease_s`` the lease the ages are judged
        against, ``wait_s`` the gate's wait. Fault-domain-granularity
        twins of the worker detectors."""
        self._obs += 1
        try:
            if alive is not None:
                a = np.asarray(alive).ravel()
                for h in range(a.size):
                    if not a[h]:
                        self._alarm("host_down", severity="critical",
                                    round=round_idx, host=int(h))
            if lease_age_s is not None and lease_s:
                ages = np.asarray(lease_age_s, np.float64).ravel()
                for h in range(ages.size):
                    if alive is not None and h < np.asarray(alive).size \
                            and not np.asarray(alive).ravel()[h]:
                        continue        # dead: host_down already fired
                    if float(lease_s) > ages[h] > 0.5 * float(lease_s):
                        self._alarm("host_lease", round=round_idx,
                                    host=int(h),
                                    lease_age_s=round(float(ages[h]), 3),
                                    lease_s=float(lease_s))
        except Exception as e:          # detectors must never kill a run
            self.log(f"health: host detector error: {e!r}")

    # -- public API --------------------------------------------------------
    def observe_round(self, it, round_idx=None, worker_losses=None,
                      latencies=None, divergence=None, valid=None,
                      alive=None, lag=None, parked=None, staleness=None):
        """Feed one sync round's signals. Any subset may be None.
        ``alive``: the elastic membership mask — evicted workers are
        excluded from every detector. ``valid``: the round's effective
        per-worker validity vector (alive AND device-finite). ``lag``/
        ``parked``/``staleness``: the async mode's per-worker version
        lag, parked worker ids, and the bound s."""
        self._obs += 1
        try:
            live = None
            if alive is not None:
                a = np.asarray(alive).ravel()
                live = [int(w) for w in range(a.size) if a[w]]
            if latencies is not None:
                self._check_stragglers(it, round_idx, latencies, live)
            if worker_losses is not None:
                self._check_loss_skew(it, round_idx, worker_losses, live)
            if valid is not None:
                self._check_validity(it, round_idx, valid, live)
            if lag is not None:
                self._check_staleness(it, round_idx, lag, parked,
                                      staleness, live)
            if divergence:
                self._check_divergence(it, round_idx, divergence)
        except Exception as e:          # detectors must never kill a run
            self.log(f"health: detector error: {e!r}")

    def summary(self):
        out = {"observations": self._obs, "alarms": self.alarms,
               "stragglers_by_worker": dict(self.straggler_counts),
               "last_alarm": self.last_alarm,
               "tau_suggestion": self.tau_suggestion}
        if self.s_suggestion is not None:
            out["s_suggestion"] = self.s_suggestion
        return out
