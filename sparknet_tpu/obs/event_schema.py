"""Metrics event registry — GENERATED, do not edit by hand.

Every event name the repo emits via ``metrics.log(...)`` with
the union of field names seen at its emit sites (``open`` =
some site forwards **kwargs, so the field set is not closed).
Consumers (obs/report.py, obs/monitor.py) may only filter on
names in this registry — `sparknet lint` rule SPK401 and
tests/test_event_schema.py both enforce it.

Regenerate with:  python -m sparknet_tpu lint --write-event-schema
"""

EVENTS = {
    'bench': {
        "fields": ['kind'],
        "open": True,
    },
    'bench_config': {
        "fields": ['device', 'iters_per_window', 'peak_bf16_flops', 'platform', 'warmup', 'windows'],
        "open": False,
    },
    'bench_headline': {
        "fields": [],
        "open": True,
    },
    'canary': {
        "fields": ['action', 'base_err_rate', 'base_p99_ms', 'baseline_sha', 'err_rate', 'p99_ms', 'pct', 'reason', 'requests', 'sha'],
        "open": False,
    },
    'chaos': {
        "fields": ['kind'],
        "open": True,
    },
    'checkpoint': {
        "fields": ['bytes', 'dropped', 'format', 'iter', 'kept', 'kind', 'model', 'refused', 'state'],
        "open": False,
    },
    'comms': {
        "fields": [],
        "open": True,
    },
    'config': {
        "fields": ['batch', 'd_model', 'dtype', 'fsdp', 'layers', 'loss_floor_nats', 'pipeline_stages', 'precision', 'seq_len', 'tp'],
        "open": False,
    },
    'device_cache': {
        "fields": ['hit_rate', 'hits', 'misses', 'nbytes', 'reason', 'records', 'resident', 'source'],
        "open": True,
    },
    'divergence': {
        "fields": [],
        "open": True,
    },
    'eviction': {
        "fields": [],
        "open": True,
    },
    'fsdp': {
        "fields": ['axis', 'hist_bytes_per_device', 'hist_bytes_replicated', 'iter', 'kind', 'min_size', 'param_bytes_per_device', 'param_bytes_replicated', 'sharded_leaves', 'total_leaves', 'world'],
        "open": False,
    },
    'ghost_reaped': {
        "fields": ['hosts', 'observer', 'orphaned_files'],
        "open": False,
    },
    'h2d_stage': {
        "fields": ['bytes', 'dispatch_ms', 'in_flight', 'kb_per_item', 'name', 'puts', 'slots', 'wait_ms'],
        "open": False,
    },
    'hbm': {
        "fields": ['iter'],
        "open": True,
    },
    'health': {
        "fields": ['cause', 'kind', 'severity'],
        "open": True,
    },
    'health_summary': {
        "fields": [],
        "open": True,
    },
    'host_alive': {
        "fields": ['alive', 'host', 'lease_age_s', 'observer'],
        "open": False,
    },
    'host_evicted': {
        "fields": ['host', 'live', 'reason', 'round'],
        "open": False,
    },
    'host_joined': {
        "fields": ['host', 'live', 'round', 'via', 'world'],
        "open": False,
    },
    'host_round': {
        "fields": ['arrived', 'dead', 'lease_age_s', 'mono', 'observer', 'round', 'wait_s'],
        "open": False,
    },
    'ingest': {
        "fields": ['hi', 'host', 'hosts', 'kind', 'lo', 'partitions', 'reads', 'records'],
        "open": False,
    },
    'membership': {
        "fields": ['agreed', 'from_world', 'hosts', 'kind', 'live', 'observer', 'quorum', 'round', 'sha', 'to_world', 'unit'],
        "open": True,
    },
    'memstats': {
        "fields": [],
        "open": True,
    },
    'moe': {
        "fields": ['eval_ce', 'expert_util', 'iter', 'overflow_fraction'],
        "open": True,
    },
    'parked': {
        "fields": ['lag', 'round', 'unit', 'worker'],
        "open": True,
    },
    'prefetch': {
        "fields": [],
        "open": True,
    },
    'readmission': {
        "fields": [],
        "open": True,
    },
    'recompile': {
        "fields": ['cache_size', 'first', 'iter', 'reason'],
        "open": False,
    },
    'recovery': {
        "fields": ['attempt', 'iter', 'kind', 'loss', 'lr_decay', 'reason', 'rollbacks', 'to_iter'],
        "open": False,
    },
    'relay_io': {
        "fields": ['bytes', 'host', 'mono', 'round', 'seconds'],
        "open": False,
    },
    'reshard': {
        "fields": ['direction', 'from_world', 'iter', 'n_from', 'n_to', 'owners', 'state', 'to_world'],
        "open": False,
    },
    'retry': {
        "fields": ['attempt', 'error', 'exhausted', 'where'],
        "open": False,
    },
    'round': {
        "fields": ['images_per_s', 'iter', 'loss', 'lr', 'round'],
        "open": False,
    },
    'route': {
        "fields": ['attempts', 'code', 'latency_ms', 'replica', 'retried', 'sha'],
        "open": False,
    },
    'scale': {
        "fields": ['action', 'breach_windows', 'live', 'p99_ms', 'queue_depth', 'reason', 'target'],
        "open": False,
    },
    'serve_batch': {
        "fields": ['bucket', 'fill', 'infer_ms', 'iter', 'queue_depth', 'requests', 'size', 'wait_ms'],
        "open": False,
    },
    'serve_reject': {
        "fields": ['limit', 'queue_depth', 'reason'],
        "open": False,
    },
    'serve_reload': {
        "fields": ['from_iter', 'iter', 'model', 'ms'],
        "open": False,
    },
    'serve_request': {
        "fields": ['bucket', 'latency_ms', 'rows', 'wait_ms'],
        "open": False,
    },
    'serve_summary': {
        "fields": ['batch_fill', 'batches', 'drained', 'latency_ms_p50', 'latency_ms_p95', 'latency_ms_p99', 'rejects', 'reloads', 'requests', 'rows', 'rps', 'uptime_s'],
        "open": False,
    },
    'serve_trace': {
        "fields": ['attempts', 'batch_ms', 'code', 'fulfill_ms', 'infer_ms', 'net_ms', 'queue_ms', 'replica', 'retried', 'server_ms', 'spans', 'src', 'tail', 'total_ms', 'trace'],
        "open": False,
    },
    'sim': {
        "fields": ['admissions', 'dead', 'evictions', 'hosts', 'live', 'parked', 'readmissions', 'round', 't_s', 'wait_s'],
        "open": False,
    },
    'slo_burn': {
        "fields": ['alert', 'bad', 'budget_left', 'fast', 'fast_long', 'good', 'slow', 'slow_long'],
        "open": False,
    },
    'span': {
        "fields": [],
        "open": True,
    },
    'staleness': {
        "fields": ['lag', 'park_rounds', 'parked', 'round', 's', 'version', 'weight'],
        "open": False,
    },
    'step': {
        "fields": [],
        "open": True,
    },
    'step_summary': {
        "fields": ['iter', 'name'],
        "open": True,
    },
    'summary': {
        "fields": ['final_loss', 'loss_floor_nats', 'steps', 'tokens_per_sec'],
        "open": False,
    },
    'test': {
        "fields": ['iter', 'metric', 'round', 'value'],
        "open": True,
    },
    'trace_align': {
        "fields": ['obs_mono', 'observer', 'peer', 'peer_mono', 'peer_stamp', 'seq'],
        "open": False,
    },
    'train': {
        "fields": ['images_per_sec', 'iter', 'loss', 'lr', 'tokens_per_sec'],
        "open": False,
    },
    'unparked': {
        "fields": [],
        "open": True,
    },
    'watchdog': {
        "fields": ['elapsed_s', 'emergency_snapshot_ok', 'exit_code', 'kind', 'loss'],
        "open": False,
    },
}

KINDS = ['abort', 'admission', 'coordinated_restart', 'exec', 'killed', 'mesh_shrunk', 'nan', 'params', 'plan', 'quorum_lost', 'recovery_armed', 'resume', 'rollback', 'serve', 'stall', 'summary', 'world_reset']

KINDS_OPEN = True
