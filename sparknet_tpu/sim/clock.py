"""The discrete-event clock — the simulated half of the time seam.

Same three-method duck type as resilience.seam.Clock, plus an event
heap. The trick that lets the REAL protocol code run unmodified: its
poll loops all block via ``clock.sleep(...)``, so a simulated sleep is
where time advances — every event due inside the slept window fires (in
due order, ties broken by scheduling order) before the sleep returns.
A 1,000-host fleet's beats, arrivals and crashes are just events; the
observer's gate() polls exactly as it does on metal and sees the same
interleavings, at microseconds of real time per simulated second.

Wall vs monotonic: monotonic is THE timeline (starts at 0.0 and only
the event loop advances it); wall = monotonic + offset, and
``jump_wall`` moves the offset — an NTP step or suspend/resume in one
line, which is how the no-mass-expiry regression test steps the wall
clock backwards an hour mid-gate (tests/test_sim.py).
"""

import heapq


class SimClock:
    """Deterministic virtual time. Not thread-safe by design: the
    simulator is single-threaded (events ARE the concurrency)."""

    #: a recognizable fake epoch (mid-2023) so simulated wall stamps
    #: look like wall stamps in logs without ever touching time.time()
    START_WALL = 1.7e9

    def __init__(self, start_wall=START_WALL):
        self._mono = 0.0
        self._wall_offset = float(start_wall)
        self._heap = []          # (due_mono, seq, fn)
        self._seq = 0            # FIFO tie-break for same-instant events

    # -- the Clock duck type -----------------------------------------------
    def time(self):
        """Simulated wall seconds (subject to jump_wall steps)."""
        return self._mono + self._wall_offset

    def monotonic(self):
        return self._mono

    def sleep(self, seconds):
        """Advance virtual time by ``seconds``, firing every event due
        in the window. THE blocking primitive: the protocol code's poll
        loops make progress because the events they are waiting on
        (peer beats, round arrivals, crashes) fire inside their sleeps.
        """
        self.advance_to(self._mono + max(0.0, float(seconds)))

    # -- the event loop ------------------------------------------------------
    def at(self, due_mono, fn):
        """Schedule ``fn()`` at monotonic ``due_mono`` (clamped to now —
        the past is not available)."""
        heapq.heappush(self._heap,
                       (max(float(due_mono), self._mono), self._seq, fn))
        self._seq += 1

    def after(self, delay_s, fn):
        self.at(self._mono + max(0.0, float(delay_s)), fn)

    def advance_to(self, due_mono):
        """Run the event loop up to monotonic ``due_mono``. Events may
        schedule further events; anything that lands inside the window
        fires too (a recurring beat chains through it)."""
        due_mono = max(float(due_mono), self._mono)
        while self._heap and self._heap[0][0] <= due_mono:
            due, _, fn = heapq.heappop(self._heap)
            self._mono = max(self._mono, due)
            fn()
        self._mono = due_mono

    def pending(self):
        """Number of scheduled events not yet fired."""
        return len(self._heap)

    # -- fault injection on time itself --------------------------------------
    def jump_wall(self, delta_s):
        """Step the WALL clock by ``delta_s`` (negative = backwards —
        an NTP correction, a resumed laptop). Monotonic time is
        untouched, exactly like the real clocks; lease math on the
        monotonic source must not notice (the satellite-1 regression)."""
        self._wall_offset += float(delta_s)
