"""Sweep grids over the control-plane knobs — the study driver.

A sweep spec is comma-separated ``key=v1:v2:...`` axes whose Cartesian
product defines the cells, e.g.::

    hosts=200:1000,fail_rate=0.0005:0.005,lease_s=1.0:3.0,quorum=1

Each cell is one deterministic FleetSim run; fail_rate/fail_seed/
fail_corr axes become the chaos failure process, everything else maps
straight onto FleetSim's knobs. Unknown keys and malformed values are
an error naming the token (same contract as the chaos grammar — a
typo'd axis must never produce a vacuous study). Results power the
DEPLOY.md "Tuning the control plane at fleet scale" tables; the
simfleet CLI verb (`sparknet simfleet --sweep ...`) is the entry point.
"""

import itertools
import time

from .fleet import FleetSim

INT_KEYS = {"hosts", "rounds", "tau", "quorum", "evict_after",
            "readmit_after", "staleness", "unpark_after", "fail_corr",
            "fail_seed", "recover_after", "seed", "slow_worker",
            "slow_round"}
FLOAT_KEYS = {"lease_s", "interval_s", "step_s", "round_s", "jitter",
              "fail_rate", "s_decay", "slow_s"}
#: chaos-process axes, routed into a ChaosMonkey spec rather than
#: FleetSim kwargs
CHAOS_KEYS = ("fail_rate", "fail_seed", "fail_corr", "slow_worker",
              "slow_s", "slow_round")

#: the serving-fleet study (`sparknet simfleet --serve --sweep`):
#: axes map onto ServeFleetSim knobs, chaos keys onto the grammar's
#: serving-tier injectors
SERVE_INT_KEYS = {"replicas", "windows", "queue_limit", "slo_depth",
                  "breach_windows", "idle_windows", "min_replicas",
                  "max_replicas", "seed", "canary_w",
                  "canary_min_requests", "die_w", "rejoin_w",
                  "kill_replica", "kill_req", "slow_replica"}
SERVE_FLOAT_KEYS = {"window_s", "lease_s", "interval_s", "service_ms",
                    "rate", "spike_x", "slo_p99_ms", "spawn_delay_s",
                    "canary_pct", "canary_err", "slow_ms"}
SERVE_STR_KEYS = {"trace"}
SERVE_CHAOS_KEYS = ("kill_replica", "kill_req", "slow_replica",
                    "slow_ms")


def _parse_axes(spec, int_keys, float_keys, str_keys=frozenset()):
    """Shared grid parser: ``key=v1:v2:...`` axes -> Cartesian-product
    cell dicts. Unknown keys and malformed values are an error naming
    the token (a typo'd axis must never produce a vacuous study)."""
    known = int_keys | float_keys | str_keys
    valid = f"valid axes: {', '.join(sorted(known))}"
    axes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        k = k.strip()
        if not eq:
            raise ValueError(f"sweep token {part!r}: expected "
                             f"key=v1:v2:...; {valid}")
        if k not in known:
            raise ValueError(f"sweep token {part!r}: unknown axis "
                             f"{k!r}; {valid}")
        conv = int if k in int_keys else \
            float if k in float_keys else str
        try:
            vals = [conv(x.strip()) for x in v.split(":")]
            if conv is str and not all(vals):
                raise ValueError("empty value")
        except (TypeError, ValueError):
            raise ValueError(
                f"sweep token {part!r}: bad value(s) {v!r} for {k} "
                f"(expects {conv.__name__}); {valid}") from None
        axes.append((k, vals))
    keys = [k for k, _ in axes]
    return [dict(zip(keys, combo))
            for combo in itertools.product(*[vs for _, vs in axes])]


def parse_grid(spec):
    """"hosts=100:1000,fail_rate=0.001,tau=4:16" -> list of cell dicts
    (the Cartesian product over every axis, in spec order)."""
    return _parse_axes(spec, INT_KEYS, FLOAT_KEYS)


def parse_serve_grid(spec):
    """The serving-fleet variant, e.g.
    "replicas=2:4,lease_s=1:3,trace=spike:flash,kill_replica=1"."""
    return _parse_axes(spec, SERVE_INT_KEYS, SERVE_FLOAT_KEYS,
                       SERVE_STR_KEYS)


def run_cell(cell, metrics=None, log_fn=None):
    """One sweep cell -> FleetSim summary (with the cell echoed and the
    real wall seconds it cost)."""
    kw = dict(cell)
    chaos_bits = [f"{k}={kw.pop(k)}" for k in CHAOS_KEYS if k in kw]
    t0 = time.time()
    sim = FleetSim(chaos=",".join(chaos_bits) or None,
                   metrics=metrics, log_fn=log_fn, **kw)
    out = sim.run()
    out["cell"] = dict(cell)
    out["real_s"] = round(time.time() - t0, 2)
    return out


def run_serve_cell(cell, metrics=None, log_fn=None):
    """One serving-fleet sweep cell -> ServeFleetSim summary."""
    from .servefleet import ServeFleetSim
    kw = dict(cell)
    chaos_bits = [f"{k}={kw.pop(k)}" for k in SERVE_CHAOS_KEYS
                  if k in kw]
    t0 = time.time()
    sim = ServeFleetSim(chaos=",".join(chaos_bits) or None,
                        metrics=metrics, log_fn=log_fn, **kw)
    out = sim.run()
    out["cell"] = dict(cell)
    out["real_s"] = round(time.time() - t0, 2)
    return out


def run_sweep(cells, metrics=None, log_fn=None, budget_s=None,
              cell_fn=None):
    """Run the cells in order, stopping early (and saying so) when the
    real wall budget is exhausted — a bounded study never silently
    reads as a complete one. ``cell_fn`` picks the simulator (default:
    the training-fleet FleetSim via run_cell)."""
    log = log_fn or (lambda *a: None)
    cell_fn = cell_fn or run_cell
    out = []
    t0 = time.time()
    for i, cell in enumerate(cells):
        if budget_s is not None and time.time() - t0 >= budget_s:
            log(f"sweep: wall budget {budget_s:g}s exhausted after "
                f"{i}/{len(cells)} cells; {len(cells) - i} cell(s) "
                "NOT run")
            break
        log(f"sweep: cell {i + 1}/{len(cells)}: {cell}")
        out.append(cell_fn(cell, metrics=metrics, log_fn=log_fn))
    return out


_COLS = (("hosts", "hosts"), ("rounds", "rounds"), ("lease_s", "lease"),
         ("quorum", "quorum"), ("evictions", "evict"),
         ("readmissions", "readmit"), ("admissions", "admit"),
         ("parks", "park"), ("live_final", "live"),
         ("quorum_lost", "qlost"), ("real_s", "real_s"))


def render_table(results):
    """The sweep results as an aligned text table (one row per cell),
    with the gate-wait tail — the metric lease tuning trades against —
    pulled out explicitly."""
    rows = []
    for s in results:
        row = [str(s.get(k, "")) for k, _ in _COLS]
        row.insert(4, f"{s['gate_wait_s']['p95']:.3f}")
        row.insert(5, f"{s['gate_wait_s']['max']:.3f}")
        cell = s.get("cell", {})
        row.append(",".join(f"{k}={v}" for k, v in cell.items()
                            if k in CHAOS_KEYS + ("tau", "staleness"))
                   or "-")
        rows.append(row)
    hdr = [h for _, h in _COLS]
    hdr.insert(4, "wait_p95")
    hdr.insert(5, "wait_max")
    hdr.append("chaos/tau/s")
    widths = [max(len(hdr[i]), *(len(r[i]) for r in rows)) if rows
              else len(hdr[i]) for i in range(len(hdr))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(hdr, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


_SERVE_COLS = (("replicas", "reps"), ("replicas_final", "final"),
               ("trace", "trace"), ("rate", "rate"),
               ("lease_s", "lease"), ("arrivals", "arrive"),
               ("ok", "ok"), ("rejected", "rej"), ("errors", "err"),
               ("retries", "retry"), ("lost", "lost"),
               ("availability", "avail"), ("p99_ms", "p99_ms"),
               ("evictions", "evict"), ("admissions", "admit"),
               ("grow", "grow"), ("shrink", "shrink"),
               ("canary_rollbacks", "rollbk"), ("real_s", "real_s"))


def render_serve_table(results):
    """The serving-fleet sweep as an aligned table — the DEPLOY.md
    "no lost request without a 429" evidence rows (lost must read 0
    in every cell)."""
    rows = []
    for s in results:
        row = [str(s.get(k, "")) for k, _ in _SERVE_COLS]
        cell = s.get("cell", {})
        row.append(",".join(
            f"{k}={v}" for k, v in cell.items()
            if k in SERVE_CHAOS_KEYS + ("die_w", "rejoin_w",
                                        "canary_w", "spike_x")) or "-")
        rows.append(row)
    hdr = [h for _, h in _SERVE_COLS] + ["chaos/schedule"]
    widths = [max(len(hdr[i]), *(len(r[i]) for r in rows)) if rows
              else len(hdr[i]) for i in range(len(hdr))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(hdr, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)
