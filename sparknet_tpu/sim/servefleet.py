"""ServeFleetSim — adversarial validation of the serving fleet.

The REAL routing tier (serve/fleet.py: Router + ElasticPolicy +
SLOAutoscaler + CanaryController) and the REAL replica membership
(ReplicaMember -> HeartbeatCoordinator) run unmodified on the simulated
seam (SimClock + MemDir); only the replicas themselves are virtual — an
analytic single-server queue per replica (bounded backlog -> 429,
deterministic service time, seeded canary faults) standing in for the
engine+batcher at zero device cost.

An open-loop arrival process (flat/diurnal/spike/flash traces) fires
request events whose handlers call the real Router.dispatch(); the
virtual replica computes the request's queue wait + service time
analytically and returns it as the third element of the post_fn result
— SimClock event handlers must never nest sleeps (advance_to rewinds
the outer window), so simulated service time is computed, not slept.

Failure processes reuse the chaos grammar: ``kill_replica=R,kill_req=N``
kills replica R right AFTER it fulfills its kill_req-th request (the
dispatch-then-die case retry-once must never double — its lease then
lapses, the router evicts within one window and fails over);
``slow_replica=R,slow_ms=S`` inflates R's service time. Deterministic
``die_w``/``rejoin_w`` windows drive the eviction/readmission path for
replay-style assertions, and ``canary_w`` flips one replica to a faulty
checkpoint sha mid-run to prove auto-rollback.

The invariant the sweep proves (DEPLOY.md table): NO LOST REQUESTS —
every arrival gets a terminal response (200, explicit 429 backpressure,
or explicit 5xx), ``lost = arrivals - responses == 0`` under kill,
churn, and flash crowds. `sparknet simfleet --serve` is the entry
point; exit 1 when the invariant breaks.
"""

import json
import math

import numpy as np

from ..obs.tracing import STAGES, BurnRateLedger, TraceSampler
from ..resilience.chaos import ChaosMonkey
from ..serve.fleet import CanaryController, ReplicaMember, Router, \
    SLOAutoscaler
from .clock import SimClock
from .memdir import MemDir

TRACES = ("flat", "diurnal", "spike", "flash")


def _quiet(*a, **k):
    pass


class _VBatcher:
    """The three batcher methods ReplicaMember's beat payload reads,
    answered from the virtual queue."""

    def __init__(self, rep):
        self.rep = rep

    def depth(self):
        return self.rep.depth()

    def pending(self):
        return self.rep.depth()

    def draining(self):
        return self.rep.draining


class _VEngine:
    def __init__(self, rep):
        self.rep = rep

    def status(self):
        return {"sha": self.rep.sha, "iter": 0}


class _VReplica:
    """One virtual serve replica: a bounded single-server queue with a
    REAL ReplicaMember leasing it into the rendezvous. Beats are
    scheduled as SimClock events (never member.start() — that spawns a
    real thread)."""

    def __init__(self, sim, rid, sha="sha-base"):
        self.sim = sim
        self.rid = int(rid)
        self.sha = sha
        self.up = True
        self.err_p = 0.0           # per-request fault prob (canary flip)
        self.served = 0
        self.busy_until = 0.0      # mono time the backlog clears
        self._completions = []     # completion times of queued requests
        self.member = ReplicaMember(
            sim.dirops.root, rid, replicas=sim.replicas,
            engine=_VEngine(self), batcher=_VBatcher(self),
            url=f"sim://replica/{rid}", interval_s=sim.interval_s,
            lease_s=sim.lease_s, metrics=sim.metrics, log_fn=sim.log,
            clock=sim.clock, dirops=sim.dirops)

    @property
    def draining(self):
        return self.member.drain_event.is_set()

    def depth(self):
        now = self.sim.clock.monotonic()
        self._completions = [t for t in self._completions if t > now]
        return len(self._completions)

    def serve(self, body):
        """-> (code, payload, latency_ms, stages): the analytic queue
        step. The stage breakdown uses the SAME shape the real replica
        echoes in X-Sparknet-Stages (serve/server.py stage_breakdown),
        so the router's tracing loop closes with zero special cases:
        queue = backlog wait, infer = service time (chaos slowness
        inflates it, matching the real tier where injected slowness
        lands inside the forward)."""
        now = self.sim.clock.monotonic()
        if not self.up:
            return (-1, b"", None, None)
        if self.draining:
            return (429, json.dumps(
                {"error": "draining", "reason": "replica_draining",
                 "queue_depth": self.depth()}).encode(), 0.0, None)
        if self.depth() >= self.sim.queue_limit:
            return (429, json.dumps(
                {"error": "queue full", "reason": "queue_full",
                 "queue_depth": self.depth()}).encode(), 0.0, None)
        service = self.sim.service_s
        chaos = self.sim.chaos
        if chaos is not None:
            spec = chaos.replica_slow_spec(self.rid)
            if spec is not None:
                service += spec[1]
        start = max(now, self.busy_until)
        done = start + service
        self.busy_until = done
        self._completions.append(done)
        self.served += 1
        lat_ms = (done - now) * 1e3
        stages = {"total": lat_ms,
                  "queue": (start - now) * 1e3,
                  "batch": 0.0,
                  "infer": service * 1e3,
                  "fulfill": 0.0}
        if chaos is not None and \
                chaos.replica_kill_due(self.rid, self.served):
            # dispatch-then-die: THIS request is fulfilled, then the
            # process dies — the router must return the 200 it already
            # holds and never re-send
            self.sim.kill(self, why="chaos kill_replica")
            self.sim.lat_ms.append(lat_ms)
            return (200, b'{"outputs": {}}', lat_ms, stages)
        if self.err_p > 0 and self.sim.rng.random_sample() < self.err_p:
            return (500, json.dumps(
                {"error": f"sim fault on {self.sha}"}).encode(),
                lat_ms, None)
        self.sim.lat_ms.append(lat_ms)
        return (200, b'{"outputs": {}}', lat_ms, stages)


class ServeFleetSim:
    """One simulated serving-fleet run; run() returns a summary dict.

    replicas/windows/window_s  fleet size and router-window count/size
    interval_s/lease_s         the real membership knobs (sim seconds)
    service_ms/queue_limit     the virtual replica's queue model
    rate/trace/spike_x         open-loop arrivals: base req/s shaped by
                               flat|diurnal|spike|flash (x spike_x)
    slo_p99_ms/slo_depth/breach_windows/idle_windows/min_replicas/
    max_replicas               the real SLOAutoscaler knobs; a grow
                               decision spawns a virtual replica after
                               spawn_delay_s (cold start), admitted via
                               the real grow path
    canary_w/canary_pct/canary_err/canary_min_requests
                               at window canary_w the highest live
                               replica hot-reloads to a faulty sha
                               (err_p=canary_err); the real controller
                               must detect and roll back
    die_w/rejoin_w             deterministic kill/rejoin windows for
                               the eviction/readmission contract
    chaos                      ChaosMonkey or spec string
                               (kill_replica/kill_req/slow_replica/
                               slow_ms)
    """

    def __init__(self, replicas=3, windows=30, window_s=1.0,
                 interval_s=0.25, lease_s=2.0, service_ms=20.0,
                 queue_limit=64, rate=40.0, trace="flat", spike_x=4.0,
                 slo_p99_ms=500.0, slo_depth=32, breach_windows=3,
                 idle_windows=10, min_replicas=1, max_replicas=8,
                 spawn_delay_s=1.0, canary_w=0, canary_pct=20.0,
                 canary_err=1.0, canary_min_requests=10,
                 die_w=None, rejoin_w=None, chaos=None, seed=0,
                 trace_sample=1.0, tail_ms=None, slo_burn=False,
                 burn_scale=1.0, metrics=None, log_fn=None):
        if trace not in TRACES:
            raise ValueError(f"unknown arrival trace {trace!r} "
                             f"(valid: {', '.join(TRACES)})")
        self.replicas = int(replicas)
        self.windows = int(windows)
        self.window_s = float(window_s)
        self.interval_s = float(interval_s)
        self.lease_s = float(lease_s)
        self.service_s = float(service_ms) / 1e3
        self.queue_limit = int(queue_limit)
        self.rate = float(rate)
        self.trace = trace
        self.spike_x = float(spike_x)
        self.spawn_delay_s = float(spawn_delay_s)
        self.canary_w = int(canary_w)
        self.canary_err = float(canary_err)
        self.die_w = None if die_w is None else int(die_w)
        self.rejoin_w = None if rejoin_w is None else int(rejoin_w)
        self.metrics = metrics
        self.log = log_fn or _quiet
        self.rng = np.random.RandomState(seed)
        self.clock = SimClock()
        self.dirops = MemDir(self.clock)
        if isinstance(chaos, str):
            chaos = ChaosMonkey.parse(chaos, metrics=metrics,
                                      log_fn=self.log) if chaos else None
        self.chaos = chaos
        self.reps = [_VReplica(self, r) for r in range(self.replicas)]
        self.canary = CanaryController(
            pct=float(canary_pct),
            min_requests=int(canary_min_requests), metrics=metrics,
            log_fn=self.log)
        self.router = Router(
            self.dirops.root, replicas=self.replicas,
            lease_s=self.lease_s, canary=self.canary, metrics=metrics,
            log_fn=self.log, clock=self.clock, dirops=self.dirops,
            post_fn=self._post,
            tracer=TraceSampler(sample=float(trace_sample),
                                tail_ms=tail_ms),
            slo=BurnRateLedger(slo_ms=float(slo_p99_ms),
                               scale=float(burn_scale),
                               metrics=metrics, log_fn=self.log)
            if slo_burn else None)
        self.autoscaler = SLOAutoscaler(
            p99_ms=float(slo_p99_ms), depth=int(slo_depth),
            windows=int(breach_windows), idle_windows=int(idle_windows),
            min_replicas=int(min_replicas),
            max_replicas=int(max_replicas), metrics=metrics,
            log_fn=self.log)
        self.duration = self.windows * self.window_s
        self.arrivals = 0
        self.responses = 0
        self.by_code = {}
        self.lat_ms = []
        self.killed = []
        self.spawned = []

    # -- transport + processes ----------------------------------------------
    def _post(self, url, body, timeout, headers=None):
        # accepting ``headers`` tells the router this transport can
        # carry the X-Sparknet-Trace header — the propagation path the
        # real tier uses, exercised verbatim in sim
        for rep in self.reps:
            if rep.member.url == url:
                return rep.serve(body)
        return (-1, b"", None, None)

    def kill(self, rep, why=""):
        """A replica dies: it stops beating and stops answering; its
        lease simply lapses — eviction flows through the real
        lease-expiry path, never injected into the policy."""
        if rep.up:
            rep.up = False
            self.killed.append(rep.rid)
            self.log(f"simserve: replica {rep.rid} died "
                     f"({why or 'scheduled'}) at "
                     f"t={self.clock.monotonic():.2f}s")

    def _revive(self, rep):
        if rep.up:
            return
        rep.up = True
        rep.busy_until = self.clock.monotonic()
        rep._completions = []
        self._schedule_beat(rep, 0.0)
        self.log(f"simserve: replica {rep.rid} rejoined at "
                 f"t={self.clock.monotonic():.2f}s")

    def _spawn(self):
        rid = len(self.reps)
        rep = _VReplica(self, rid)
        self.reps.append(rep)
        self.spawned.append(rid)
        self._schedule_beat(rep, self.spawn_delay_s)
        self.log(f"simserve: replica {rid} spawning "
                 f"(cold start {self.spawn_delay_s:g}s)")
        return rep

    def _schedule_beat(self, rep, delay):
        def fire():
            if not rep.up:
                return
            rep.member.coord.beat()
            if rep.draining and rep.depth() == 0:
                rep.up = False        # drained; the process exits 0
                self.log(f"simserve: replica {rep.rid} drained and "
                         "exited")
            else:
                self.clock.after(self.interval_s, fire)
        self.clock.after(delay, fire)

    # -- the arrival process -------------------------------------------------
    def _rate_at(self, t):
        x = t / max(self.duration, 1e-9)
        if self.trace == "diurnal":
            return self.rate * (0.15 + 0.425 * (1.0 - math.cos(
                2.0 * math.pi * x)))
        if self.trace == "spike":
            return self.rate * (self.spike_x if 0.4 <= x < 0.6 else 1.0)
        if self.trace == "flash":
            return self.rate * (self.spike_x if x >= 0.5 else 1.0)
        return self.rate

    def _schedule_arrival(self, delay):
        def fire():
            now = self.clock.monotonic()
            if now >= self.duration:
                return
            self._request()
            gap = self.rng.exponential(
                1.0 / max(self._rate_at(now), 1e-3))
            self.clock.after(gap, fire)
        self.clock.after(delay, fire)

    def _request(self):
        self.arrivals += 1
        code, _ = self.router.dispatch(b"{}", timeout=1.0)
        self.responses += 1
        self.by_code[code] = self.by_code.get(code, 0) + 1

    # -- the run -------------------------------------------------------------
    def run(self):
        for rep in self.reps:
            self._schedule_beat(rep, self.rng.uniform(0.0,
                                                      self.interval_s))
        # one beat cycle so every replica has leased in before traffic
        self.clock.sleep(self.interval_s * 1.5)
        self.router.poll()
        self._schedule_arrival(self.rng.exponential(
            1.0 / max(self._rate_at(0.0), 1e-3)))
        for w in range(self.windows):
            self.clock.sleep(self.window_s)
            if self.die_w is not None and w == self.die_w:
                live = [r for r in self.reps if r.up]
                if live:
                    self.kill(live[0], why="die_w")
            if self.rejoin_w is not None and w == self.rejoin_w:
                for rep in self.reps:
                    if not rep.up and not rep.draining:
                        self._revive(rep)
                        break
            if self.canary_w and w == self.canary_w:
                live = [r for r in self.reps if r.up]
                if live:
                    rep = live[-1]
                    rep.sha = "sha-canary"
                    rep.err_p = self.canary_err
                    self.log(f"simserve: replica {rep.rid} hot-reloaded"
                             f" to {rep.sha} (err_p={self.canary_err:g})")
            self.router.poll()
            stats = self.router.window_stats()
            decision = self.autoscaler.observe(
                stats, live=self.router.policy.live_count())
            if decision == "grow":
                self._spawn()
            elif decision == "shrink":
                self.router.request_drain()
            self.canary.evaluate()
        return self.summary()

    def summary(self):
        snap = self.router.stats_snapshot()
        lats = np.asarray(self.lat_ms or [0.0], np.float64)
        lost = self.arrivals - self.responses
        grow = sum(1 for _, a in self.autoscaler.decisions
                   if a == "grow")
        shrink = sum(1 for _, a in self.autoscaler.decisions
                     if a == "shrink")
        # attributed tail: which stage owns the p99 (the knob-ranking
        # signal a `simfleet --serve` sweep sorts by)
        stages_p99 = self.router.stages.p99()
        ranked = [(v, k) for k, v in stages_p99.items() if k in STAGES]
        top_stage = max(ranked)[1] if ranked else None
        return {
            "replicas": self.replicas,
            "replicas_final": self.router.policy.live_count(),
            "windows": self.windows, "window_s": self.window_s,
            "lease_s": self.lease_s, "interval_s": self.interval_s,
            "trace": self.trace, "rate": self.rate,
            "sim_s": round(self.clock.monotonic(), 3),
            "arrivals": self.arrivals, "responses": self.responses,
            "lost": lost,
            "ok": snap["ok"], "rejected": snap["rejected"],
            "errors": snap["errors"], "retries": snap["retries"],
            "availability": round(
                snap["ok"] / self.arrivals, 4) if self.arrivals else None,
            "p99_ms": round(float(np.percentile(lats, 99)), 3),
            "evictions": len(self.router.policy.evictions),
            "readmissions": len(self.router.policy.readmissions),
            "admissions": len(self.router.policy.admissions),
            "grow": grow, "shrink": shrink,
            "canary_rollbacks": self.canary.rollbacks,
            "killed": list(self.killed), "spawned": list(self.spawned),
            "quorum_lost": bool(self.router.quorum_lost),
            "stages_p99": stages_p99,
            "top_stage": top_stage,
            "burn": (self.router.slo.snapshot()
                     if self.router.slo is not None else None),
        }
