"""Replay validation: the simulator must reproduce reality, exactly.

The claim the whole package rests on is that FleetSim exercises the
SAME control plane as a real multi-process run. This module makes the
claim falsifiable:

  record_real()  runs N REAL HeartbeatCoordinators — real threads, real
                 wall clock, a real on-disk rendezvous directory, the
                 default seam — through a scripted SIGKILL-shaped crash
                 (the victim stops leasing mid-run, announcing nothing),
                 with one host driving the real ElasticPolicy off its
                 gate results. The coordinators share one process, but
                 the protocol is entirely file-based: the code paths are
                 byte-for-byte the ones separate processes execute (the
                 multi-process smoke stages prove that equivalence
                 elsewhere).
  replay_sim()   feeds the recorded config + death schedule to FleetSim
                 (simulated clock, in-memory dir, same policy knobs) and
                 compares the ORDERED membership sequence — every
                 host_evicted / host_joined / readmission / parked event
                 with its host and round — which must match exactly.

A mismatch fails the simfleet smoke stage: either the simulator drifted
from the protocol, or a protocol change altered membership behavior
without anyone noticing. Both are exactly what this gate is for.
"""

import threading
import time

from ..resilience.elastic import ElasticPolicy, QuorumLost
from ..resilience.heartbeat import HeartbeatCoordinator
from .fleet import FleetSim

#: the membership events whose order defines a run's control-plane story
SEQ_EVENTS = ("host_evicted", "host_joined", "readmission", "parked")


def _quiet(*a, **k):
    pass


class SequenceSink:
    """A metrics-shaped recorder keeping the ordered membership
    sequence (and forwarding everything to an inner logger, if any)."""

    def __init__(self, inner=None):
        self.inner = inner
        self.sequence = []

    def log(self, event, **fields):
        if event in SEQ_EVENTS:
            host = fields.get("host", fields.get("worker"))
            self.sequence.append(
                [event, int(host), int(fields.get("round", -1))])
        if self.inner is not None:
            self.inner.log(event, **fields)


def record_real(directory, hosts=3, rounds=9, kill_round=3, victim=None,
                interval_s=0.1, lease_s=0.5, round_s=0.12,
                evict_after=1, readmit_after=3, quorum=1, log_fn=None):
    """Run a real multi-coordinator crash scenario and return the
    recording dict (config + membership sequence) replay_sim consumes.

    Every host gates every round in its own thread (the real rendezvous
    shape); the victim stops leasing right before ``kill_round`` and
    never announces it, so the survivors' gate discovers a lapsed lease
    — the true crash shape. Host 0 drives the real ElasticPolicy:
    eviction on gate.dead, cooldown readmission via observe_round, the
    production sequencing. With the cooldown shorter than the remaining
    rounds the recording contains the full churn signature —
    evict -> readmit -> re-evict — which is exactly the hard case the
    simulator must reproduce round-exact."""
    victim = hosts - 1 if victim is None else int(victim)
    sink = SequenceSink()
    log = log_fn or _quiet
    coords = [HeartbeatCoordinator(directory, host=h, n_hosts=hosts,
                                   interval_s=interval_s, lease_s=lease_s,
                                   log_fn=_quiet).start()
              for h in range(hosts)]
    policy = ElasticPolicy(n_workers=hosts, quorum=quorum,
                           evict_after=evict_after,
                           readmit_after=readmit_after, metrics=sink,
                           log_fn=log, unit="host")

    def peer_loop(h):
        for r in range(rounds):
            if h == victim and r >= kill_round:
                coords[h].stop()        # silent death: the lease lapses
                return
            time.sleep(round_s)
            if h == 0:
                expect = set(policy.live()) - {0}
                res = coords[0].gate(r, expect=expect, timeout=None)
                for d in res.dead:
                    try:
                        policy.evict(d, r, "lease_expired")
                    except QuorumLost:
                        return
                try:
                    policy.observe_round(r)
                except QuorumLost:
                    return
            else:
                coords[h].gate(r, timeout=None)

    threads = [threading.Thread(target=peer_loop, args=(h,),
                                name=f"sim-record-{h}")
               for h in range(hosts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=rounds * (round_s + lease_s) + 30)
    for c in coords:
        c.close()
    return {"config": {"hosts": hosts, "rounds": rounds,
                       "kill_round": kill_round, "victim": victim,
                       "interval_s": interval_s, "lease_s": lease_s,
                       "round_s": round_s, "evict_after": evict_after,
                       "readmit_after": readmit_after, "quorum": quorum},
            "sequence": sink.sequence}


def replay_sim(recording, metrics=None, log_fn=None):
    """Re-run a recording's scenario in the simulator and compare the
    membership sequences. Returns (match, real_seq, sim_seq)."""
    cfg = recording["config"]
    sink = SequenceSink(inner=metrics)
    sim = FleetSim(hosts=int(cfg["hosts"]), rounds=int(cfg["rounds"]),
                   interval_s=float(cfg["interval_s"]),
                   lease_s=float(cfg["lease_s"]),
                   round_s=float(cfg["round_s"]), jitter=0.0,
                   quorum=int(cfg["quorum"]),
                   evict_after=int(cfg["evict_after"]),
                   readmit_after=int(cfg["readmit_after"]),
                   consensus="none",
                   deaths={int(cfg["victim"]): int(cfg["kill_round"])},
                   seed=0, metrics=sink, log_fn=log_fn)
    sim.run()
    real_seq = [list(e) for e in recording["sequence"]]
    return sink.sequence == real_seq, real_seq, sink.sequence
