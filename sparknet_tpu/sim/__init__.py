"""Fleet-scale discrete-event simulation of the control plane (ISSUE 15).

The robustness mechanisms — leased heartbeats, masked-consensus
eviction, staleness parking, grow-mid-run admission — only ever ran at
2–3 real processes, while the production questions (lease/quorum tuning
at 1,000 hosts, park storms, eviction cascades, gate-wait tails) are
control-plane questions. This package drives the REAL control-plane
code (HeartbeatCoordinator, FileConsensus/AsyncFileConsensus,
ElasticPolicy, RecoveryPolicy, RetryPolicy — none of it modified or
mocked) against the injectable Clock/Dir seam (resilience/seam.py):

  clock.SimClock   virtual wall + monotonic time with an event heap;
                   ``sleep`` advances time and drains due events, so the
                   protocol code's poll loops run unchanged in
                   microseconds of real time
  memdir.MemDir    the rendezvous directory as an in-memory dict with
                   the same atomic-visibility semantics as RealDir
  fleet.FleetSim   a seeded fleet: per-host round durations, the chaos
                   failure processes (fail_rate/fail_corr, kill/preempt/
                   rejoin), lease churn, gates, evictions, consensus —
                   emitting the standard closed-schema metrics stream so
                   `sparknet report`/`monitor` render a simulated fleet
                   with zero special cases
  replay           record a REAL multi-coordinator run's membership
                   sequence, then reproduce it in the simulator exactly
                   (the validation that the sim and reality share one
                   control plane)
  sweep            grids over fleet size × failure rate × τ × s ×
                   lease/quorum — the study behind DEPLOY.md's tuning
                   tables
  servefleet       the SERVING fleet under open-loop arrival traces:
                   the real Router/SLOAutoscaler/CanaryController over
                   virtual replicas, proving no-lost-request-without-
                   429 under kill/churn/flash-crowd (`sparknet
                   simfleet --serve`)

Everything is deterministic given the seed: same spec, same timeline.
"""

from .clock import SimClock
from .memdir import MemDir
from .fleet import FleetSim
from .servefleet import ServeFleetSim
from . import replay, sweep

__all__ = ["SimClock", "MemDir", "FleetSim", "ServeFleetSim", "replay",
           "sweep"]
