"""FleetSim — thousands of virtual hosts driving the real control plane.

One simulated fleet is: a shared MemDir rendezvous directory, one REAL
HeartbeatCoordinator per virtual host (the same class production runs,
via the Clock/Dir seam), one fleet-level ElasticPolicy(unit="host"),
and optionally the real FileConsensus/AsyncFileConsensus,
RecoveryPolicy and RetryPolicy — all unmodified. The simulator itself
only orchestrates: it schedules beats and round arrivals as events,
renders the chaos failure processes as hosts going silent, and lets the
protocol code discover everything the way it does on metal (leases
expire, gates time out, the policy evicts, the cooldown readmits).

Per round r:

  1. failure processes fire: chaos ``dead_hosts``/``fail_rate`` victims
     and scheduled deaths stop beating (their leases simply lapse —
     evictions flow through the real lease-expiry path, never injected
     directly into the policy); rejoining/recovered hosts resume
     beating and are admitted (via="rejoin"), mirroring
     ElasticPolicy.observe_round's own chaos branch.
  2. every live host draws a round duration (seeded jitter around
     round_s = tau x step_s; chaos stragglers pay extra) and its
     arrival (announce_round) is scheduled at that offset.
  3. the OBSERVER — the lowest live host, exactly the authority rule
     FileConsensus uses — runs the real gate(): its poll loop sleeps on
     the SimClock, which fires the pending beats/arrivals, and dead
     peers surface when their receipt age crosses lease_s.
  4. gate.dead is fed to ElasticPolicy.evict(reason "lease_expired")
     with QuorumLost deferred until survivors are recorded — the exact
     sequencing of the production round loop
     (parallel/data_parallel.py).
  5. at small fleets the real consensus transport runs over the MemDir
     (sync: post-then-exchange with the lowest-host mask authority;
     async: versioned deltas, parking on lag > s); at scale the
     policy-level virtual version clocks model staleness instead.
  6. surrogate losses drive RecoveryPolicy (chaos nan_step) and a
     surrogate ingest read drives RetryPolicy (chaos io_p) — both real,
     both sleeping on the SimClock.
  7. one closed-schema ``sim`` metrics event summarizes the round, and
     the standard host_round/host_alive/host_evicted/... events flow
     from the protocol code itself, so `sparknet report`/`monitor`
     render a simulated fleet with zero special cases.

Determinism: every random draw comes from seeded numpy generators, all
scheduling from the SimClock — same spec, same timeline, to the event.
"""

import numpy as np

from ..resilience.chaos import ChaosMonkey
from ..resilience.elastic import ElasticPolicy, QuorumLost
from ..resilience.heartbeat import (AsyncFileConsensus, FileConsensus,
                                    HeartbeatCoordinator)
from ..resilience.recovery import RecoveryAbort, RecoveryPolicy
from ..resilience.retry import RetryExhausted, RetryPolicy
from .clock import SimClock
from .memdir import MemDir

#: the real consensus transports exchange whole parameter sets per host
#: per round — rich, but O(hosts^2) loads; above this fleet size the
#: policy-level version clocks model staleness instead
CONSENSUS_MAX_HOSTS = 8


def _quiet(*a, **k):
    pass


class _SurrogateSolver:
    """The minimal solver surface RecoveryPolicy snapshots/rewinds
    (note_good/_rollback): numpy state standing in for the device
    training state, at zero device cost."""

    def __init__(self, seed=0):
        rng = np.random.RandomState(seed)
        self.params = {"w": rng.normal(size=8).astype(np.float32)}
        self.state = {"m": np.zeros(8, np.float32)}
        self.history = {"loss": np.zeros(4, np.float32)}
        self.rng = np.zeros(2, np.uint32)
        self.iter = 0
        self._it_dev = None
        self._smoothed = {}


class FleetSim:
    """One simulated fleet run. ``run()`` returns a summary dict; the
    metrics stream (if a logger is given) carries the full story.

    hosts/rounds        fleet size and simulated round count
    interval_s/lease_s  the real heartbeat knobs, in simulated seconds
    tau, step_s         round_s = tau * step_s unless round_s is given
                        directly — sweeping tau changes how much round
                        compute amortizes each gate
    jitter              per-host per-round duration jitter (std dev as
                        a fraction of round_s, seeded)
    quorum/evict_after/readmit_after/staleness/s_decay/unpark_after
                        passed straight to the real ElasticPolicy
    consensus           "auto" | "sync" | "async" | "none" — auto picks
                        the real transport at <= CONSENSUS_MAX_HOSTS
                        hosts (async when staleness is set)
    chaos               a ChaosMonkey (or spec string) driving the
                        failure processes
    deaths/rejoins      {host: round} hard schedules (replay validation
                        uses these instead of probabilistic chaos)
    recover_after       revive chaos-killed hosts after this many
                        rounds (0 = never) — the repair half of the
                        MTBF cycle fail_rate models
    """

    def __init__(self, hosts=8, rounds=20, interval_s=0.5, lease_s=3.0,
                 round_s=None, jitter=0.15, tau=4, step_s=0.25,
                 quorum=1, evict_after=1, readmit_after=0,
                 staleness=None, s_decay=0.5, unpark_after=1,
                 consensus="auto", recover_after=0,
                 deaths=None, rejoins=None, chaos=None,
                 nan_recovery=True, seed=0, metrics=None, log_fn=None):
        self.n = int(hosts)
        self.rounds = int(rounds)
        self.interval_s = float(interval_s)
        self.lease_s = float(lease_s)
        self.round_s = float(round_s) if round_s is not None \
            else float(tau) * float(step_s)
        self.jitter = float(jitter)
        self.tau = int(tau)
        self.recover_after = int(recover_after)
        self.deaths = {int(h): int(r) for h, r in (deaths or {}).items()}
        self.rejoins = {int(h): int(r) for h, r in (rejoins or {}).items()}
        self.metrics = metrics
        self.log = log_fn or _quiet
        self.clock = SimClock()
        self.dirops = MemDir(self.clock)
        if isinstance(chaos, str):
            chaos = ChaosMonkey.parse(chaos, metrics=metrics,
                                      log_fn=self.log) if chaos else None
        self.chaos = chaos
        self.staleness = None if staleness is None else int(staleness)
        if consensus == "auto":
            consensus = "none" if self.n > CONSENSUS_MAX_HOSTS else \
                ("async" if self.staleness is not None else "sync")
        self.consensus = consensus
        self.rng = np.random.RandomState(seed)
        # the real control plane, on the simulated seam
        self.coords = [
            HeartbeatCoordinator(self.dirops.root, host=h, n_hosts=self.n,
                                 interval_s=self.interval_s,
                                 lease_s=self.lease_s, metrics=metrics,
                                 log_fn=_quiet, chaos=None,
                                 clock=self.clock, dirops=self.dirops)
            for h in range(self.n)]
        self.policy = ElasticPolicy(
            n_workers=self.n, quorum=int(quorum),
            evict_after=int(evict_after),
            readmit_after=int(readmit_after), metrics=metrics,
            log_fn=self.log, chaos=None, unit="host",
            staleness=self.staleness, s_decay=float(s_decay),
            unpark_after=int(unpark_after))
        if self.consensus == "sync":
            self.fc = [FileConsensus(c) for c in self.coords]
        elif self.consensus == "async":
            self.fc = [AsyncFileConsensus(c, s=self.staleness or 0,
                                          decay=float(s_decay))
                       for c in self.coords]
        else:
            self.fc = None
        # per-host surrogate weights only exist when a transport runs
        self.leaves = [np.full(16, float(h), np.float64)
                       for h in range(self.n)] if self.fc else None
        self.recovery = None
        self.solver = None
        if nan_recovery and self.chaos is not None \
                and getattr(self.chaos, "nan_step", None) is not None:
            self.solver = _SurrogateSolver(seed)
            self.recovery = RecoveryPolicy(metrics=metrics,
                                           log_fn=self.log)
        self.retry = None
        if self.chaos is not None and getattr(self.chaos, "io_p", 0) > 0:
            self.retry = RetryPolicy(attempts=4, base_s=self.interval_s / 4,
                                     sleep=self.clock.sleep,
                                     metrics=metrics, log_fn=self.log)
        # simulator-side host state (who is actually running)
        self.up = [True] * self.n
        self.died_at = {}
        self.announced = [-1] * self.n
        self.gate_waits = []
        self.retry_exhausted = 0
        self.recovery_aborted = False
        self.quorum_lost = False

    # -- event plumbing ------------------------------------------------------
    def _schedule_beat(self, h, delay):
        def fire():
            if self.up[h]:
                self.coords[h].beat()
                self._schedule_beat(h, self.interval_s)
        self.clock.after(delay, fire)

    def _schedule_arrival(self, h, r, delay):
        def fire():
            if self.up[h] and self.announced[h] < r:
                self.announced[h] = r
                self.coords[h].announce_round(r)
        self.clock.after(delay, fire)

    def _kill(self, h, r):
        """A host dies: it simply stops beating. Nothing tells the
        policy — the observer's gate discovers the lapsed lease, the
        real path."""
        if self.up[h]:
            self.up[h] = False
            self.died_at[h] = r
            self.log(f"sim: host {h} went silent at round {r}")

    def _revive(self, h, r):
        """A host comes back: it resumes beating at the current round
        front and is admitted (via="rejoin") exactly as
        ElasticPolicy.observe_round's chaos branch admits virtual
        rejoiners."""
        if self.up[h]:
            return
        self.up[h] = True
        self.died_at.pop(h, None)
        if self.chaos is not None:
            self.chaos.revive_host(h)
        self.announced[h] = r - 1
        self.coords[h].announce_round(r - 1)
        self._schedule_beat(h, 0.0)
        self.policy.admit(h, r, via="rejoin")

    # -- the run -------------------------------------------------------------
    def _failures(self, r):
        newly = []
        if self.chaos is not None:
            newly.extend(self.chaos.dead_hosts(r, self.n))
        newly.extend(h for h, rr in self.deaths.items()
                     if rr == r and self.up[h])
        for h in newly:
            if 0 <= h < self.n:
                self._kill(h, r)
        back = []
        if self.chaos is not None:
            back.extend(self.chaos.rejoining_hosts(r))
        back.extend(h for h, rr in self.rejoins.items() if rr == r)
        if self.recover_after:
            back.extend(h for h, d in list(self.died_at.items())
                        if r - d >= self.recover_after)
        for h in sorted(set(back)):
            if 0 <= h < self.n:
                self._revive(h, r)

    def _consensus_round(self, r, live_up, losses):
        order = sorted(live_up)
        if self.consensus == "sync":
            # pre-post every contribution, then exchange authority
            # (lowest host) first: the mask decision finds all parts
            # in place and nobody polls — the async transport never
            # waits by construction, so it needs no pre-post
            for h in order:
                self.fc[h]._post(r, [self.leaves[h]], True, losses[h])
        for h in order:
            out, aux = self.fc[h].exchange(r, [self.leaves[h]], True,
                                           losses[h], live_up)
            self.leaves[h] = np.asarray(out[0], np.float64)

    def _surrogates(self, r, loss):
        if self.retry is not None:
            def _read():
                self.chaos.maybe_io_error("sim-ingest")
                return True
            try:
                self.retry.call(_read, where="sim-ingest")
            except RetryExhausted:
                self.retry_exhausted += 1
        if self.recovery is not None and not self.recovery_aborted:
            if self.chaos.poison_loss(r):
                loss = float("nan")
            try:
                if not self.recovery.observe(self.solver, loss):
                    self.solver.iter += 1
            except RecoveryAbort:
                self.recovery_aborted = True

    def run(self):
        rng = self.rng
        for h in range(self.n):
            self._schedule_beat(h, rng.uniform(0.0, self.interval_s))
        r = 0
        while r < self.rounds:
            self._failures(r)
            if not any(self.up):
                self.quorum_lost = True
                break
            obs = next(h for h in range(self.n) if self.up[h])
            durs = self.round_s * np.clip(
                rng.normal(1.0, self.jitter, self.n), 0.4, 3.0)
            slow = self.chaos.slow_worker_spec(r) \
                if self.chaos is not None else None
            if slow is not None and 0 <= int(slow[0]) < self.n:
                durs[int(slow[0])] += float(slow[1])
            for h in range(self.n):
                if h != obs and self.up[h]:
                    self._schedule_arrival(h, r, durs[h])
            # the observer does its own round work, then gates — its
            # sleep is where everyone else's beats and arrivals fire
            self.clock.sleep(float(durs[obs]))
            self.announced[obs] = r
            expect = set(self.policy.live()) - {obs}
            res = self.coords[obs].gate(r, expect=expect, timeout=None)
            self.gate_waits.append(res.wait_s)
            # eviction sequencing exactly as the production round loop:
            # record every survivor-visible death, defer QuorumLost
            ql = False
            for h in res.dead:
                try:
                    self.policy.evict(h, r, "lease_expired")
                except QuorumLost:
                    ql = True
            base_loss = 2.5 * float(np.exp(-3.0 * r / self.rounds)) \
                + float(rng.normal(0.0, 0.01))
            if not ql and self.fc is not None:
                live_up = [h for h in self.policy.live() if self.up[h]]
                if live_up:
                    losses = {h: base_loss + 0.01 * h for h in live_up}
                    self._consensus_round(r, live_up, losses)
            if self.staleness is not None and self.consensus != "async":
                # at scale the policy-level virtual clocks model
                # bounded staleness (no transport needed)
                self.policy.advance_versions(r, self.round_s, slow=slow)
                self.policy.observe_staleness(r)
            self._surrogates(r, base_loss)
            if not ql:
                try:
                    self.policy.observe_round(r)
                except QuorumLost:
                    ql = True
            if self.metrics is not None:
                self.metrics.log(
                    "sim", round=r,
                    t_s=round(self.clock.monotonic(), 3), hosts=self.n,
                    live=self.policy.live_count(),
                    parked=int(self.policy.parked.sum()),
                    dead=len(res.dead), wait_s=round(res.wait_s, 4),
                    evictions=len(self.policy.evictions),
                    readmissions=len(self.policy.readmissions),
                    admissions=len(self.policy.admissions))
            if ql:
                self.quorum_lost = True
                self.log(f"sim: QUORUM LOST at round {r} "
                         f"({self.policy.live_count()} live / "
                         f"quorum {self.policy.quorum}); fleet halts "
                         "for coordinated restart")
                break
            r += 1
        return self.summary(rounds_done=r)

    def summary(self, rounds_done=None):
        w = np.asarray(self.gate_waits or [0.0], np.float64)
        out = {"hosts": self.n,
               "rounds": int(rounds_done if rounds_done is not None
                             else self.rounds),
               "sim_s": round(self.clock.monotonic(), 3),
               "round_s": self.round_s, "tau": self.tau,
               "lease_s": self.lease_s, "interval_s": self.interval_s,
               "consensus": self.consensus,
               "quorum": self.policy.quorum,
               "live_final": self.policy.live_count(),
               "quorum_lost": bool(self.quorum_lost
                                   or self.policy.quorum_lost),
               "evictions": len(self.policy.evictions),
               "readmissions": len(self.policy.readmissions),
               "admissions": len(self.policy.admissions),
               "parks": len(self.policy.parks),
               "unparks": len(self.policy.unparks),
               "retry_exhausted": self.retry_exhausted,
               "rollbacks": (self.recovery.rollbacks
                             if self.recovery else 0),
               "recovery_aborted": self.recovery_aborted,
               "gate_wait_s": {
                   "mean": round(float(w.mean()), 4),
                   "p50": round(float(np.percentile(w, 50)), 4),
                   "p95": round(float(np.percentile(w, 95)), 4),
                   "max": round(float(w.max()), 4)}}
        if self.staleness is not None:
            out["staleness"] = self.staleness
            out["max_lag"] = int(self.policy.lag().max())
        return out
