"""The in-memory rendezvous directory — the simulated half of the Dir
seam.

Same duck type as resilience.seam.RealDir, over a plain dict. The
semantics RealDir's atomic renames guarantee are trivially true here:
a write is one dict assignment (a reader sees the old record or the new
one, never a torn middle), reads of absent names are None, and globbing
returns sorted names so every consumer iterates deterministically.

Records are stored as the PARSED objects (dicts, ndarray maps) rather
than serialized bytes — that is what makes a 1,000-host fleet cheap
(no json/npz round-trip per beat). Two contracts follow, both already
honored by every writer in resilience/:

  * writers always build a FRESH object per write (heartbeat's beat(),
    the consensus posts) — stored records are never mutated in place;
  * readers treat records as read-only snapshots.

``write_npz``/``load_npz`` store the {key: ndarray} map directly;
``mtime`` is the simulated wall time of the write, which keeps the
ghost-reaper's stamp math meaningful.
"""

import fnmatch


class MemDir:
    def __init__(self, clock, root="mem:fleet"):
        self.clock = clock
        self.root = str(root)
        self._files = {}         # name -> (wall mtime, object)

    def path(self, name):
        """A display-only path (nothing in the sim opens real files)."""
        return f"{self.root}/{name}"

    def glob(self, pattern):
        return sorted(n for n in self._files
                      if fnmatch.fnmatchcase(n, pattern))

    def read_json(self, name):
        rec = self._files.get(name)
        return rec[1] if rec is not None and isinstance(rec[1], dict) \
            else None

    def write_json(self, name, obj):
        self._files[name] = (self.clock.time(), obj)

    def write_npz(self, name, arrays):
        self._files[name] = (self.clock.time(), dict(arrays))

    def load_npz(self, name):
        rec = self._files.get(name)
        return dict(rec[1]) if rec is not None else None

    def exists(self, name):
        return name in self._files

    def remove(self, name):
        return self._files.pop(name, None) is not None

    def mtime(self, name):
        rec = self._files.get(name)
        return rec[0] if rec is not None else None
