"""Long-context sequence/context parallelism.

The reference is a 2015 CNN framework — no attention, no sequence axis
(SURVEY.md section 5, "long-context: absent entirely"). sparknet_tpu treats
long context as first-class: sequences shard across a "seq" mesh axis and
attention runs without ever materializing the full sequence on one chip.

Two interchangeable strategies (jax-native; see PAPERS.md for the source
techniques — Ring Attention with blockwise transformers, and
DeepSpeed-Ulysses all-to-all):

  ring_attention     K/V blocks rotate around the ring via ppermute while a
                     numerically-stable running softmax (the flash-attention
                     recurrence m/l/o) accumulates per Q block. Comm is
                     point-to-point neighbor traffic — rides ICI perfectly —
                     and overlaps with each block's compute.
  ulysses_attention  two all_to_alls reshard (seq-sharded, heads-full) ->
                     (seq-full, heads-sharded) around a plain attention; best
                     when num_heads % axis_size == 0 and the sequence fits
                     once resharded.

Both are exact (bitwise-modulo-reduction-order) equivalents of full
attention, verified against the dense reference in tests/test_parallel.py.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from .compat import shard_map


def _stable_block_update(o, m, l, s, v):
    """One flash-attention accumulation step.
    o: (..., Sq, D) running unnormalized output
    m: (..., Sq)    running max
    l: (..., Sq)    running denominator
    s: (..., Sq, Sk) raw scores for this K/V block
    v: (..., Sk, D)
    """
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # renormalize history; exp(-inf - -inf) guarded to 0
    alpha = jnp.exp(jnp.where(m == -jnp.inf, -jnp.inf, m - m_new))
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v)
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Exact attention over a sequence sharded on ``axis_name``.

    q, k, v: (B, H, S_local, D) — the local sequence shard. Must be called
    inside shard_map/pmap providing ``axis_name``. Returns (B, H, S_local, D).
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = (q * scale).astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = my * s_local + jnp.arange(s_local)

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        # whose block do we currently hold? blocks rotate +1 each step,
        # so at step t we hold the block originally on rank (my - t) mod n
        src = (my - step) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        o, m, l = _stable_block_update(o, m, l, s, v_cur.astype(jnp.float32))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        body, (o0, m0, l0, k, v), jnp.arange(n, dtype=jnp.int32))
    # fully-masked rows (can't happen with causal self-attn, but be safe)
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """All-to-all sequence parallelism (Ulysses): reshard so each device
    holds ALL positions for H/n heads, run plain attention, reshard back.

    q, k, v: (B, H, S_local, D); requires H % axis_size == 0."""
    n = jax.lax.psum(1, axis_name)
    h = q.shape[1]

    def seq_to_head(x):
        # (B, H, S/n, D) -> (B, H/n, S, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = dense_attention(qh, kh, vh, causal=causal, scale=scale)
    del h, n
    return head_to_seq(out)


def dense_attention(q, k, v, causal=False, scale=None):
    """Plain full attention (B, H, S, D) — the single-device reference."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", (q * scale).astype(jnp.float32),
                   k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def ring_attention_comm_bytes(block_shape, n, itemsize=4):
    """Per-chip bytes one ring_attention forward moves over the seq axis:
    the K and V blocks (each ``block_shape``, the local shard) are
    ppermuted ``n`` times around the ring (the final rotation returns
    blocks home; XLA may elide it, so this is a slight upper bound).
    Used by the obs comms meter — the traffic itself runs inside the
    compiled step and can't be counted from the host."""
    total = 1
    for d in block_shape:
        total *= int(d)
    return int(2 * int(n) * total * itemsize)


def sequence_sharded_apply(fn, mesh, seq_axis="seq", batch_args=(),
                           seq_dim=1):
    """Wrap ``fn(*arrays)`` so its array args are sharded along ``seq_dim``
    over ``seq_axis`` and fn runs under shard_map with the seq axis
    published in the parallelism context (ops.attention picks it up)."""
    from . import context

    spec = [None] * (seq_dim + 1)
    spec[seq_dim] = seq_axis
    sp = P(*spec)

    @functools.wraps(fn)
    def wrapped(*args):
        with context.axis_context(seq=seq_axis):
            inner = shard_map(fn, mesh=mesh,
                              in_specs=tuple(sp for _ in args),
                              out_specs=sp, check_vma=False)
            return inner(*args)

    return wrapped
