"""Multi-host runtime: process topology, fault-domain meshes, shrink.

The reference's cluster substrate was a Spark driver owning N executors;
one executor loss killed the job (spark.task.maxFailures=1). Here the
substrate is jax.distributed — N identical processes, each owning the
local devices of one machine — and the HOST is the real failure unit:
preemption, OOM-kill, and network partitions take out whole processes,
never single chips. This module is the thin runtime layer the rest of
the framework builds fault domains on:

  init_runtime()        wraps jax.distributed bring-up (mesh.
                        distributed_init) and publishes the process
                        topology through parallel/context.py — one
                        authoritative (process_id, local/global device
                        topology) record per process
  host_mesh()           the 2-D (host, device) training mesh of
                        mesh.make_host_device_mesh, one row per fault
                        domain
  survivor_mesh()       the mesh rebuilt over the LIVE hosts' devices
                        after evictions — falls back to this process's
                        local devices when the survivors can no longer
                        span hosts (the single-survivor case)
  local_batch_rows()    this host's slice of a slot-major global batch

The liveness signals that drive evictions live in
resilience/heartbeat.py (leased heartbeats over a shared directory);
this module only knows topology.
"""

import os

import numpy as np
import jax

from . import context
from .mesh import (HOST_AXIS, DATA_AXIS, distributed_init,
                   make_host_device_mesh, is_local_mesh)


def needs_host_relay():
    """True when the cross-host tier cannot run as an in-program
    collective on this backend — multi-process CPU jax has no
    cross-host collective transport ("Multiprocess computations aren't
    implemented on the CPU backend"), so the tau-interval average must
    go through the rendezvous directory instead
    (resilience.heartbeat.FileConsensus). TPU/GPU pods use the
    compiled collective path."""
    if jax.process_count() <= 1:
        return False
    return jax.devices()[0].platform == "cpu"


def init_runtime(coordinator_address=None, num_processes=None,
                 process_id=None):
    """Bring up (or join) the multi-host runtime and publish this
    process's topology. Idempotent; single-process runs publish the
    trivial one-host topology. Returns the topology dict."""
    distributed_init(coordinator_address=coordinator_address,
                     num_processes=num_processes, process_id=process_id)
    return publish_topology()


def publish_topology():
    """(Re)derive this process's host topology from jax and publish it
    through parallel/context.py."""
    info = {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "platform": jax.devices()[0].platform if jax.devices() else None,
    }
    return context.publish_host_topology(info)


def host_mesh(hosts=None, per_host=None, device_axis=DATA_AXIS):
    """The (host, device) training mesh for the current topology (or a
    virtual hosts x per_host partition of the local devices)."""
    return make_host_device_mesh(hosts=hosts, per_host=per_host,
                                 device_axis=device_axis)


def survivor_mesh(mesh, live_hosts, device_axis=None):
    """Rebuild a (host, device) mesh over the LIVE host rows.

    When the surviving rows include this process's devices only — the
    lone-survivor case, or a partition where the remote survivors are
    unreachable anyway — the result is a purely local mesh
    (is_local_mesh), so subsequent compiled rounds never block on the
    cross-host fabric a dead peer would hang."""
    if mesh.devices.ndim != 2:
        raise ValueError("survivor_mesh needs a (host, device) mesh")
    device_axis = device_axis or mesh.axis_names[1]
    live = sorted(int(h) for h in live_hosts)
    if not live:
        raise ValueError("no live hosts to rebuild a mesh over")
    rows = mesh.devices[np.asarray(live)]
    return make_host_device_mesh(hosts=rows.shape[0],
                                 per_host=rows.shape[1],
                                 device_axis=device_axis,
                                 devices=list(rows.flat))


def my_host_rows(mesh):
    """Host-axis indices of ``mesh`` whose devices THIS process owns —
    the rows this process feeds (normally exactly one in a real
    multi-process run; all of them on a virtual single-process mesh)."""
    me = jax.process_index()
    rows = []
    for h in range(mesh.devices.shape[0]):
        if all(d.process_index == me for d in mesh.devices[h]):
            rows.append(h)
    return rows


def local_batch_rows(global_batch, mesh):
    """(start, size) of this process's contiguous slice of a batch axis
    sharded over (host, device): host h's devices hold blocks
    [h*per_host, (h+1)*per_host), so a process feeding its own rows
    ships exactly its devices' data (the per-worker RDD partition of
    CifarApp.scala:56-64, at host granularity)."""
    hosts, per_host = mesh.devices.shape
    slots = hosts * per_host
    if global_batch % slots:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{slots} mesh slots")
    per_slot = global_batch // slots
    rows = my_host_rows(mesh)
    if not rows:
        raise ValueError("this process owns no complete host row of the "
                         "mesh (hosts must not straddle processes)")
    if rows != list(range(rows[0], rows[0] + len(rows))):
        raise ValueError(f"this process's host rows {rows} are not "
                         "contiguous on the host axis")
    return rows[0] * per_host * per_slot, len(rows) * per_host * per_slot


def exit_if_peers_died(rc, heartbeat):
    """Exit code ``rc`` WITHOUT the jax.distributed atexit shutdown —
    call at the very end of a CLI run (after metrics are flushed) when
    the heartbeat layer saw a peer host die. The coordination service's
    shutdown barrier waits for every task; with a dead peer it can only
    time out and SIGABRT the process, turning a successfully-survived
    run into exit 134. The supervisor contract (DEPLOY.md) is the rc of
    the RUN, so the survivor skips the doomed barrier. No-op (returns)
    when single-process or no host ever died."""
    if heartbeat is None or jax.process_count() <= 1:
        return
    try:
        dead = heartbeat.ever_dead()
    except Exception:
        dead = None
    if not dead:
        return
    import sys
    print(f"multihost: peer host(s) {sorted(dead)} died this run; "
          f"exiting {rc} without the distributed shutdown barrier",
          flush=True)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)


def auto_host_mesh(hosts=None, per_host=None, device_axis=DATA_AXIS):
    """The right (host, device) mesh for this runtime: the global
    host-major mesh when the backend can run cross-host collectives, a
    LOCAL one-row mesh when the cross-host tier must relay through the
    rendezvous directory (needs_host_relay) — each process then trains
    its own fault domain and the relay supplies the tau-consensus."""
    if needs_host_relay():
        return make_host_device_mesh(hosts=1, per_host=per_host,
                                     device_axis=device_axis,
                                     devices=jax.local_devices())
    return make_host_device_mesh(hosts=hosts, per_host=per_host,
                                 device_axis=device_axis)


__all__ = ["init_runtime", "publish_topology", "host_mesh",
           "survivor_mesh", "my_host_rows", "local_batch_rows",
           "HOST_AXIS", "is_local_mesh", "needs_host_relay",
           "auto_host_mesh", "exit_if_peers_died"]
