"""dp x ep solver: data parallelism composed with expert parallelism.

The MoE training runner: batch dim sharded over the full ("data",
"expert") mesh — so tokens arrive SHARDED along the expert axis and
ops.moe's all_to_all path shards expert COMPUTE ep-fold, not just weight
memory (ops/moe.py:27-43) — while each MoE layer's expert-major weight
blobs (w1/b1/w2/b2, dim 0 = num_experts) live sharded P("expert"), each
device holding and updating only its own experts' slices (optimizer
history included, ZeRO-style for the expert weights). The router stays
replicated: every token computes all num_experts logits before dispatch.

Gradient semantics (the part that makes the update equal single-device
training on the global batch): the local loss is the mean over this
device's 1/(dp*ep) token slice, so

  * replicated params (router, attention, embeddings...): grads pmean'd
    over BOTH axes == the global-batch gradient (every token's
    contribution appears on exactly one device);
  * expert-sharded params: each expert's gradient contributions appear
    only on the ep-column that owns it (the backward all_to_all routes
    them home), summed over that column's ep peers already — so the
    correct reduction is pmean over "data" DIVIDED by ep (a psum over
    "data" scaled by the global 1/(dp*ep) loss normalization).
    tests/test_expert_parallel.py asserts the resulting loss curve
    equals the single-device run's exactly (no-overflow capacity).

The Switch aux loss is computed from LOCAL routing statistics and
pmean'd — mean-of-products, not the product of global means. That is the
standard data-parallel MoE formulation (each shard balances its own
routing); with aux weight 0 the step is bit-equivalent to single-device.

No reference twin: SURVEY.md section 2c lists EP/MoE as absent from the
CNN-era reference; this solver completes the dp/tp/sp/ep/pp set with the
same Solver API as the other axes. ``seq_axis`` composes a third axis —
dp x sp x ep, the long-context MoE shape: sequence dim sharded over
"seq" (ring attention + positional offsets via parallel.context, as in
SeqParallelSolver), expert dispatch still all_to_all over "expert"
within each (data, seq) row; expert-param grads then pmean over BOTH
data and seq before the 1/ep factor.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..solver.solver import Solver
from .data_parallel import _rebatch, _batch_specs, shard_batch, \
    check_global_feed, check_seq_shardable_losses, place_tree
from . import context
from .compat import shard_map, axis_size


class ExpertParallelSolver(Solver):
    """Solver whose step runs under shard_map over ("data", "expert"):
    batch dim 0 sharded over both axes, MoE expert weights sharded over
    "expert", everything else replicated; see module docstring for the
    gradient reductions."""

    # MoE param blob order: router, w1, b1, w2, b2 (ops/moe.py
    # param_shapes); slot 0 (router) is replicated, 1-4 expert-sharded
    _EXPERT_SLOTS = (1, 2, 3, 4)

    def __init__(self, solver_param, mesh=None, data_axis="data",
                 expert_axis="expert", seq_axis=None, **kw):
        from .mesh import make_mesh
        if jax.process_count() > 1 and int(solver_param.random_seed) < 0:
            raise ValueError(
                "multi-process ExpertParallelSolver requires an explicit "
                "SolverParameter.random_seed: hosts must agree on param "
                "init and rng streams")
        self.mesh = mesh if mesh is not None else \
            make_mesh({data_axis: 1, expert_axis: -1})
        self.data_axis, self.expert_axis = data_axis, expert_axis
        # optional third axis: dim 1 (sequence) sharded over "seq" — the
        # dp x sp x ep long-context MoE composition. Sequence-aware
        # layers (ring attention, positional-embed offsets, per-token
        # loss) pick the axis up from parallel.context exactly as under
        # SeqParallelSolver; the MoE all_to_all still runs over
        # "expert" only (each (data, seq) shard's tokens route among
        # that row's ep peers).
        self.seq_axis = seq_axis
        if int(solver_param.iter_size) > 1:
            raise ValueError("ExpertParallelSolver does not support "
                             "iter_size > 1")
        super().__init__(solver_param, **kw)
        if seq_axis:
            check_seq_shardable_losses(self.net, "ExpertParallelSolver")
        dp = self.mesh.shape[data_axis]
        self.ep = ep = self.mesh.shape[expert_axis]
        sp = self.mesh.shape[seq_axis] if seq_axis else 1
        self.local_net = _rebatch(self.net, dp * ep, seq=sp)
        self.local_test_net = _rebatch(self.test_net, dp * ep, seq=sp) \
            if self.test_net is not None else None
        # per-param sharding specs ({layer: [spec per owned blob]}) + the
        # matching bool tree used to pick the gradient reduction
        self._param_specs, self._expert_flags = self._build_specs()
        self._history_specs = {
            ln: [[spec] * len(self.history[ln][i])
                 for i, spec in enumerate(specs)]
            for ln, specs in self._param_specs.items()}
        # place params/history on the mesh once at init (expert blobs
        # sharded, the rest replicated); donation keeps them resident
        self.params = self._place(self.params, self._param_specs)
        self.history = self._place(self.history, self._history_specs)

    def _build_specs(self):
        ea = self.expert_axis
        specs, flags = {}, {}
        by_name = {lp.name: (lp, impl)
                   for lp, impl, _, _ in self.net.layers}
        for lname, blobs in self.params.items():
            lp, impl = by_name[lname]
            shard = lp.type == "MoE" and getattr(impl, "expert_parallel",
                                                 False)
            if shard and self.ep > 1 and \
                    impl.num_experts % self.ep:
                raise ValueError(
                    f"{lname}: num_experts {impl.num_experts} not "
                    f"divisible by expert axis size {self.ep}")
            specs[lname] = [
                P(ea) if shard and i in self._EXPERT_SLOTS else P()
                for i in range(len(blobs))]
            flags[lname] = [shard and i in self._EXPERT_SLOTS
                            for i in range(len(blobs))]
        return specs, flags

    def _place(self, tree, specs):
        return place_tree(tree, specs, self.mesh)

    def _axes_context(self):
        axes = dict(data=self.data_axis, expert=self.expert_axis)
        if self.seq_axis:
            axes["seq"] = self.seq_axis
        return context.axis_context(**axes)

    def _batch_spec(self, batch):
        return _batch_specs(batch, (self.data_axis, self.expert_axis),
                            seq_axis=self.seq_axis)

    def _sharded_step(self, batch_example):
        net, updater, lr_fn = self.local_net, self.updater, self.lr_fn
        da, ea, ep = self.data_axis, self.expert_axis, self.ep
        sa = self.seq_axis
        # every non-expert mesh axis a token shard lives on; expert-param
        # grads skip "expert" (each column owns distinct experts) but pay
        # the 1/ep loss-normalization factor (module docstring)
        other = [da] + ([sa] if sa else [])
        flags = self._expert_flags
        with_stats = self.stepstats is not None
        loss_fn = self._wrapped_loss(net)

        def pmean_over(x, axes):
            for a in axes:
                x = jax.lax.pmean(x, a)
            return x

        def reduce_grads(grads):
            def red(g, is_expert):
                if is_expert:
                    return pmean_over(g, other) / ep
                return pmean_over(g, [ea] + other)
            return jax.tree_util.tree_map(red, grads, flags)

        def step(params, state, history, batch, it, rng):
            flat_idx = jax.lax.axis_index(da)
            for a in ([sa] if sa else []) + [ea]:
                flat_idx = flat_idx * axis_size(a) \
                    + jax.lax.axis_index(a)
            rng = jax.random.fold_in(rng, flat_idx)

            def lf(p):
                loss, (blobs, new_state) = loss_fn(p, state, batch, rng)
                return loss, new_state
            (loss, state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            grads = reduce_grads(grads)
            if with_stats:
                # per-data-worker loss (averaged over its expert/seq
                # columns first): the loss-skew detector's input — a
                # token shard training differently from its peers
                from ..obs.divergence import gather_worker_scalar
                aux = {"worker_loss": gather_worker_scalar(
                    pmean_over(loss, [ea] + ([sa] if sa else [])), da)}
            else:
                aux = {}
            loss = pmean_over(loss, [ea] + other)
            state = pmean_over(state, [ea] + other)
            params, history = updater(params, grads, history, lr_fn(it), it)
            return params, state, history, loss, it + 1, aux

        bspec = self._batch_spec(batch_example)
        pspec, hspec = self._param_specs, self._history_specs
        sharded = shard_map(
            step, mesh=self.mesh,
            in_specs=(pspec, P(), hspec, bspec, P(), P()),
            out_specs=(pspec, P(), hspec, P(), P(), P()),
            check_vma=False)
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _build_train_step(self):
        return None              # built lazily on the first batch

    def _register_comms(self, cm):
        """Three traffic classes per step (module docstring): replicated
        params pmean over ALL axes; expert-sharded params pmean over the
        non-expert axes only; and the MoE dispatch/combine all_to_all
        pairs over the expert axis (fwd + bwd), costed from the local
        activation shapes."""
        from ..obs.comms import (tree_bytes, ring_allreduce_bytes,
                                 all_to_all_bytes)
        super()._register_comms(cm)
        ep = self.ep
        n_other = max(1, self.mesh.size // ep)
        eb = rb = 0
        for ln, blobs in self.params.items():
            flags = self._expert_flags.get(ln) or [False] * len(blobs)
            for b, is_expert in zip(blobs, flags):
                if is_expert:
                    eb += int(b.nbytes)
                else:
                    rb += int(b.nbytes)
        rb += tree_bytes(self.state)
        cm.set_topology(axes=dict(self.mesh.shape))
        cm.register("allreduce_dense", ring_allreduce_bytes(rb, self.mesh.size),
                    axis="all",
                    note="replicated-param grads + state pmean per step")
        if eb:
            cm.register("allreduce_expert", ring_allreduce_bytes(eb, n_other),
                        axis=self.data_axis,
                        note="expert-sharded grads pmean over non-expert "
                             "axes (global expert bytes)")
        a2a = 0
        itemsize = np.dtype(self.net.compute_dtype
                            or self.net.dtype).itemsize
        for lp, impl, bottoms, _ in self.local_net.layers:
            if lp.type == "MoE" and getattr(impl, "expert_parallel", False):
                act = 1
                for d in self.local_net.blob_shapes[bottoms[0]]:
                    act *= int(d)
                # dispatch + combine, forward and backward: 4 all_to_alls
                # of the (capacity-padded ~ input-sized) token buffer
                a2a += 4 * all_to_all_bytes(act * itemsize, ep)
        if a2a:
            cm.register("moe_all_to_all", a2a, axis=self.expert_axis,
                        note="token dispatch/combine fwd+bwd per step "
                             "(analytic, from local activation shapes)")

    def _shard(self, batch):
        return shard_batch(batch, self.mesh,
                           (self.data_axis, self.expert_axis),
                           seq_axis=self.seq_axis, global_feed=True)

    def train_step(self, batch):
        import time as _time
        self.check_batch(batch, split_across_hosts=False)
        if not getattr(self, "_feed_checked", False):
            self._feed_checked = True
            check_global_feed(batch)
        self.rng, key = jax.random.split(self.rng)
        t0 = _time.perf_counter()
        with self._axes_context():
            if self._jit_train is None:
                self._jit_train = self._sharded_step(batch)
            dev = self._shard(batch)
            if self._it_dev is None:
                self._it_dev = jnp.asarray(self.iter, jnp.int32)
            (self.params, self.state, self.history, loss,
             self._it_dev, aux) = self._jit_train(
                self.params, self.state, self.history, dev,
                self._it_dev, key)
        self.iter += 1
        host_s = _time.perf_counter() - t0
        self._timing["train_step"] += host_s
        self._obs_step(host_s, loss, batch, aux=aux or None)
        return loss

    def _build_eval_step(self):
        net = self.local_test_net
        da, ea = self.data_axis, self.expert_axis
        tf = self.test_input_transform
        compiled = {}

        sa = self.seq_axis
        axes = [ea, da] + ([sa] if sa else [])

        def ev(params, state, batch):
            if tf is not None:
                batch = tf(batch)
            blobs, _ = net.apply(params, state, batch, train=False)
            out = {}
            for b in net.output_blobs:
                v = jnp.asarray(blobs[b], jnp.float32)
                for a in axes:
                    v = jax.lax.pmean(v, a)
                out[b] = v
            return out

        def stepper(params, state, batch):
            key = tuple(sorted((k, tuple(np.shape(v)))
                               for k, v in batch.items()))
            with self._axes_context():
                if key not in compiled:
                    bspec = self._batch_spec(batch)
                    compiled[key] = jax.jit(shard_map(
                        ev, mesh=self.mesh,
                        in_specs=(self._param_specs, P(), bspec),
                        out_specs=P(), check_vma=False))
                return compiled[key](params, state, self._shard(batch))

        return stepper
