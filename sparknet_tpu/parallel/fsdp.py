"""ZeRO/FSDP sharded data parallelism: one big model over the data axis.

`DataParallelSolver` replicates params + optimizer state on every device,
so model scale is capped by one chip's HBM and remat is the only pressure
valve. `FSDPSolver` removes the cap the ZeRO way (Rajbhandari et al.,
2020, stage 3 for params + stage 1/2 for grads/optimizer state):

  * every eligible weight blob lives dim0-SHARDED across the "data" axis
    (each device owns rows [w*d0/n, (w+1)*d0/n)); optimizer history
    shards identically, so per-device residency for params + Adam state
    drops from (1 + n_hist) * P to (1 + n_hist) * P / n;
  * the forward/backward needs full weights, so the step all-gathers
    them at use (`gather_full`) — a transient that XLA frees after the
    last consumer, never a resident replica;
  * the gradient consensus becomes a reduce-scatter (`scatter_grads`):
    each device receives only the mean of ITS shard's rows, paying
    (n-1)/n * B on the wire where DP's allreduce pays 2(n-1)/n * B;
  * the optimizer update runs elementwise on each device's own shard —
    the update FLOPs and memory also divide by n.

Collectives are issued per reverse-order bucket (`overlap.plan_buckets`,
the same plan the DP allreduce overlaps with): deep layers' grads finish
backward first, so their scatters start while shallow layers still
differentiate, and the per-bucket concatenation amortizes ring latency.

Numerics contract (tests/test_fsdp.py): psum_scatter/n is bitwise the
pmean each DP device computes (same per-element additions in the same
ring order), and the sharded elementwise update on shard rows is the
same arithmetic the replicated update does on those rows — so fsdp=on
at fp32 is BIT-FOR-BIT fsdp=off, and fsdp=off is untouched code.

Sharding is an implementation detail of the STEP: params/history enter
and leave the jit as global jax.Arrays with their full logical shape
(NamedSharding over the mesh, 1/n of the bytes per device), so the tree
view, `np.asarray` snapshot gathers, eval (which auto-reshards the
params into its replicated specs), and the manifest format are all
unchanged. Elastic membership and bounded staleness are REFUSED: a dead
worker's param shard is unrecoverable mid-step, so FSDP's failure story
is the checkpoint/restore path, not the masked consensus.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..resilience.elastic import (masked_consensus, masked_consensus_stats,
                                  masked_scalar_mean)
from ..obs.divergence import _sq_sum, gather_worker_scalar
from ..solver.updates import accum_init, accum_add, apply_clip
from .mesh import DATA_AXIS
from . import context
from .compat import shard_map
from .data_parallel import (DataParallelSolver, _batch_specs, place_tree)
from .overlap import plan_buckets


def fsdp_enabled(default=False):
    """SPARKNET_FSDP=on|off — shard params + optimizer state over the
    data axis (default off: the replicated DP path, untouched)."""
    v = os.environ.get("SPARKNET_FSDP", "").strip().lower()
    if not v:
        return default
    return v in ("1", "on", "true", "yes")


def fsdp_min_size(default=2048):
    """SPARKNET_FSDP_MIN_SIZE — smallest element count worth sharding;
    blobs under it stay replicated (a 1-element collective costs more
    latency than its bytes save)."""
    v = os.environ.get("SPARKNET_FSDP_MIN_SIZE", "").strip()
    return int(v) if v else default


def plan_param_specs(tree, n, axis=DATA_AXIS, min_size=None):
    """Per-leaf sharding decision for params (or their congruent
    optimizer history): dim0-shard any leaf whose leading dim divides
    the axis size and whose element count clears ``min_size``;
    everything else stays replicated. Returns a tree of PartitionSpecs
    congruent with ``tree`` (P(axis) = dim0-sharded, P() = replicated)."""
    if min_size is None:
        min_size = fsdp_min_size()

    def spec(x):
        shape = tuple(np.shape(x))
        if n > 1 and shape and shape[0] % n == 0 and \
                int(np.prod(shape)) >= min_size:
            return P(axis)
        return P()

    return jax.tree_util.tree_map(spec, tree)


def _is_spec(s):
    return isinstance(s, P)


def _spec_leaves(specs):
    return jax.tree_util.tree_flatten(specs, is_leaf=_is_spec)[0]


def sharded_bytes(tree, specs, n):
    """(per-device bytes, replicated-equivalent bytes) for ``tree``
    placed per ``specs`` — the residency the fsdp obs event reports."""
    per_dev = total = 0
    for x, s in zip(jax.tree_util.tree_leaves(tree), _spec_leaves(specs)):
        b = int(np.prod(np.shape(x))) * np.dtype(x.dtype).itemsize
        total += b
        per_dev += b // n if len(s) else b
    return per_dev, total


def gather_full(tree, specs, axis):
    """All-gather the dim0-sharded leaves back to their full logical
    shape (tiled: shard rows concatenate along dim 0 in axis-index
    order, the exact inverse of the scatter); replicated leaves pass
    through untouched. Issued leaf-by-leaf so XLA can schedule each
    gather against the first op that consumes the weight."""

    def one(s, x):
        if len(s):
            return jax.lax.all_gather(x, axis, axis=0, tiled=True)
        return x

    return jax.tree_util.tree_map(one, specs, tree, is_leaf=_is_spec)


def take_shard(tree, specs, axis, n):
    """Slice this device's own dim0 block out of FULL leaves — the
    consensus-side twin of `gather_full`, used when a full consensus
    already exists (the divergence-stats path): pmean-then-slice is
    bitwise psum_scatter/n, so both grad paths land identical shards."""
    w = jax.lax.axis_index(axis)

    def one(s, x):
        if not len(s):
            return x
        blk = x.shape[0] // n
        return jax.lax.dynamic_slice_in_dim(x, w * blk, blk, 0)

    return jax.tree_util.tree_map(one, specs, tree, is_leaf=_is_spec)


def scatter_grads(grads, valid, axis, specs, n):
    """The FSDP gradient consensus: dim0-sharded leaves reduce-scatter
    (each device keeps the cross-worker mean of its own shard rows,
    (n-1)/n * B on the wire vs the allreduce's 2(n-1)/n * B); replicated
    leaves take the same masked pmean the DP path uses. Collectives are
    issued per reverse-order bucket (`overlap.plan_buckets` — deep
    layers first), each bucket's sharded leaves fused into ONE
    psum_scatter payload; per-element additions are unchanged by the
    concatenation, so the result is bitwise the per-leaf form."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    sharded = [len(s) > 0 for s in _spec_leaves(specs)]
    plan = plan_buckets(grads)
    out = [None] * len(leaves)
    for bucket in plan["buckets"]:
        shard_ent = [e for e in bucket if sharded[e[0]]]
        rep_ent = [e for e in bucket if not sharded[e[0]]]
        if shard_ent:
            bufs = [leaves[i].reshape(n, -1) for i, _, _, _ in shard_ent]
            cols = [b.shape[1] for b in bufs]
            ps = jax.lax.psum_scatter(
                jnp.concatenate(bufs, axis=1), axis,
                scatter_dimension=0, tiled=False)
            off = 0
            # static n as a same-dtype scalar: /n folds into the scatter
            # epilogue and keeps the psum_scatter/n == pmean bit contract
            inv = np.dtype(ps.dtype).type(n)
            for (i, shape, _, _), c in zip(shard_ent, cols):
                blk = (shape[0] // n,) + tuple(shape[1:])
                out[i] = (ps[off:off + c] / inv).reshape(blk)
                off += c
        if rep_ent:
            flat = jnp.concatenate(
                [leaves[i].ravel() for i, _, _, _ in rep_ent])
            flat, _ = masked_consensus(flat, valid, axis)
            off = 0
            for i, shape, _, size in rep_ent:
                out[i] = flat[off:off + size].reshape(shape)
                off += size
    return jax.tree_util.tree_unflatten(treedef, out)


def sharded_sq_norm(grads, specs, axis):
    """Global squared L2 norm of a mixed shard/replicated gradient tree:
    sharded leaves' partial sums psum over the axis (every device holds
    disjoint rows), replicated leaves count once. Feeds the gradient
    clip so `clip_gradients` semantics survive sharding (the norm is the
    GLOBAL one, not the shard's)."""
    shard_sq = jnp.zeros((), jnp.float32)
    rep_sq = jnp.zeros((), jnp.float32)
    for x, s in zip(jax.tree_util.tree_leaves(grads), _spec_leaves(specs)):
        ss = jnp.sum(jnp.square(jnp.asarray(x, jnp.float32)))
        if len(s):
            shard_sq = shard_sq + ss
        else:
            rep_sq = rep_sq + ss
    return jax.lax.psum(shard_sq, axis) + rep_sq


class FSDPSolver(DataParallelSolver):
    """DataParallelSolver whose params + optimizer history live sharded.

    Same construction surface, same train_step/eval/snapshot/restore
    surface; only the compiled step differs (gather-at-use /
    reduce-scatter / sharded update). ``min_shard_size`` overrides
    SPARKNET_FSDP_MIN_SIZE for tests."""

    def __init__(self, solver_param, mesh=None, axis=DATA_AXIS,
                 min_shard_size=None, **kw):
        if kw.get("staleness") is not None:
            raise ValueError(
                "FSDP refuses bounded staleness: a lagging worker holds "
                "the only copy of its param shard, so discounting it "
                "corrupts the model instead of degrading gracefully")
        super().__init__(solver_param, mesh=mesh, axis=axis, **kw)
        n = self.mesh.shape[self.axis]
        self._min_shard_size = min_shard_size
        self.fsdp_specs = plan_param_specs(
            self.params, n, self.axis, min_size=min_shard_size)
        self.fsdp_hist_specs = plan_param_specs(
            self.history, n, self.axis, min_size=min_shard_size)
        self._place_sharded()
        self._fsdp_logged = False
        if self.metrics is not None:
            sl = sum(len(s) > 0 for s in _spec_leaves(self.fsdp_specs))
            nl = len(_spec_leaves(self.fsdp_specs))
            pd, tot = sharded_bytes(self.params, self.fsdp_specs, n)
            hd, htot = sharded_bytes(self.history, self.fsdp_hist_specs, n)
            self.metrics.log(
                "fsdp", kind="plan", axis=self.axis, world=n,
                sharded_leaves=int(sl), total_leaves=int(nl),
                param_bytes_per_device=int(pd),
                param_bytes_replicated=int(tot),
                hist_bytes_per_device=int(hd),
                hist_bytes_replicated=int(htot),
                min_size=int(min_shard_size if min_shard_size is not None
                             else fsdp_min_size()))

    # a dead worker's shard is unrecoverable mid-run: FSDP's failure
    # story is snapshot/restore, never the masked consensus
    def arm_elastic(self, *a, **kw):
        raise ValueError(
            "FSDP shards each param over the workers; evicting one "
            "loses its shard. Use snapshots + restore (--resume auto) "
            "for fault tolerance, or run elastic training with fsdp=off")

    def arm_staleness(self, *a, **kw):
        raise ValueError(
            "FSDP refuses bounded staleness (sharded params cannot "
            "tolerate a discounted worker); run with fsdp=off")

    def _place_sharded(self):
        """Pin params/history to their shard layout (1/n of the bytes
        per device). Called at construction and after restore — the
        boundaries where leaves are host/replicated arrays."""
        self.params = place_tree(self.params, self.fsdp_specs, self.mesh)
        self.history = place_tree(self.history, self.fsdp_hist_specs,
                                  self.mesh)

    def restore(self, state_path, reshard="strict"):
        super().restore(state_path, reshard=reshard)
        self._place_sharded()

    def load_weights(self, caffemodel_path):
        super().load_weights(caffemodel_path)
        self.params = place_tree(self.params, self.fsdp_specs, self.mesh)

    def _write_snapshot_files(self, *a, **kw):
        # snapshots write the FULL logical tree. Single-process sharded
        # jax.Arrays gather transparently under np.asarray; a data axis
        # spanning processes needs the explicit replicate-gather first
        # (each leaf is briefly full on every host — snapshot-time only)
        if jax.process_count() > 1:
            rep = NamedSharding(self.mesh, P())
            params, history = self.params, self.history
            g = jax.tree_util.tree_map(lambda x: jax.device_put(x, rep),
                                       params)
            h = jax.tree_util.tree_map(lambda x: jax.device_put(x, rep),
                                       history)
            self.params, self.history = g, h
            try:
                return super()._write_snapshot_files(*a, **kw)
            finally:
                self.params, self.history = params, history
        return super()._write_snapshot_files(*a, **kw)

    def train_step(self, batch):
        out = super().train_step(batch)
        if not self._fsdp_logged and self.metrics is not None:
            # execution proof for the smoke/CI assertion: the params the
            # STEP returned really are sharded (per-device resident bytes
            # measured off the live arrays, not the plan)
            self._fsdp_logged = True
            per_dev = total = 0
            for x in jax.tree_util.tree_leaves(self.params):
                total += int(x.nbytes)
                shards = getattr(x, "addressable_shards", None)
                per_dev += int(shards[0].data.nbytes) if shards \
                    else int(x.nbytes)
            self.metrics.log(
                "fsdp", kind="exec", axis=self.axis,
                world=int(self.mesh.shape[self.axis]), iter=self.iter,
                param_bytes_per_device=per_dev,
                param_bytes_replicated=total)
        return out

    # -- compiled step -----------------------------------------------------
    def _sharded_step(self, batch_example):
        iter_size = int(self.param.iter_size)
        net, updater, lr_fn = self.local_net, self.updater, self.lr_fn
        axis = self.axis
        n = self.mesh.shape[axis]
        specs, hist_specs = self.fsdp_specs, self.fsdp_hist_specs
        with_stats = self.stepstats is not None
        loss_fn = self._wrapped_loss(net)

        def one_grad(params, state, batch, rng):
            def lf(p):
                loss, (blobs, new_state) = loss_fn(p, state, batch, rng)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            return loss, grads, new_state

        clip_fn = None
        if float(updater.clip) >= 0:
            def clip_fn(grads):
                return apply_clip(grads, float(updater.clip),
                                  sharded_sq_norm(grads, specs, axis))

        def step(params, state, history, batch, it, rng, alive, lag):
            w = jax.lax.axis_index(axis)
            valid = alive[w]
            rng = jax.random.fold_in(rng, w)
            # the all-gather: full weights exist only inside the step —
            # XLA frees each one after its last forward/backward consumer
            full = gather_full(params, specs, axis)
            if iter_size == 1:
                loss, grads, state = one_grad(full, state, batch, rng)
            else:
                def body(carry, micro):
                    acc, state, i = carry
                    loss, g, state = one_grad(
                        full, state, micro, jax.random.fold_in(rng, i))
                    return (accum_add(acc, g), state, i + 1), loss
                (grads, state, _), losses = jax.lax.scan(
                    body, (accum_init(full), state, 0), batch)
                loss = jnp.mean(losses)
            if with_stats:
                # divergence stats need the full consensus anyway:
                # reuse it and slice our shard (bitwise psum_scatter/n)
                gfull, aux = masked_consensus_stats(grads, valid, axis)
                aux["ref_sq"] = _sq_sum(gfull)
                aux["worker_loss"] = gather_worker_scalar(loss, axis)
                grads = take_shard(gfull, specs, axis, n)
            else:
                grads = scatter_grads(grads, valid, axis, specs, n)
                aux = {}
            loss = masked_scalar_mean(loss, valid, axis)
            # BN running stats etc. stay replicated, same as DP
            state, _ = masked_consensus(state, valid, axis)
            # the sharded update: elementwise on this device's own rows
            params, history = updater(params, grads, history, lr_fn(it),
                                      it, clip_fn=clip_fn)
            return params, state, history, loss, aux

        bspec = _batch_specs(batch_example, axis,
                             batch_dim=0 if iter_size == 1 else 1)
        with context.axis_context(data=axis), \
                context.world_context(axis=axis, size=n, elastic=False):
            sharded = shard_map(
                step, mesh=self.mesh,
                in_specs=(specs, P(), hist_specs, bspec, P(), P(), P(), P()),
                out_specs=(specs, P(), hist_specs, P(), P()),
                check_vma=False)
            return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _register_comms(self, cm):
        """FSDP per step: one all-gather of the sharded params (forward)
        + a reduce-scatter of their grads (backward tail) + the plain
        allreduce for whatever stayed replicated. Each leg moves
        (n-1)/n * B per chip under the ring model — together the same
        2(n-1)/n * B the DP allreduce moves, but the resident copy is
        gone. Registered per reverse-order bucket like DP so `sparknet
        report` decomposes overlapped vs exposed bytes."""
        from ..obs.comms import (tree_bytes, ring_allreduce_bytes,
                                 ring_reduce_scatter_bytes,
                                 ring_all_gather_bytes)
        from ..solver.solver import Solver
        Solver._register_comms(self, cm)
        n = self.mesh.shape[self.axis]
        cm.set_topology(axes=dict(self.mesh.shape))
        leaves = jax.tree_util.tree_leaves(self.params)
        sharded = [len(s) > 0 for s in _spec_leaves(self.fsdp_specs)]
        plan = plan_buckets(self.params)
        sb = tree_bytes(self.state)
        for bi, bucket in enumerate(plan["buckets"]):
            shard_b = sum(sz * np.dtype(dt).itemsize
                          for i, _, dt, sz in bucket if sharded[i])
            rep_b = sum(sz * np.dtype(dt).itemsize
                        for i, _, dt, sz in bucket if not sharded[i])
            last = bi == len(plan["buckets"]) - 1
            if shard_b:
                cm.register(
                    "fsdp_allgather_params",
                    ring_all_gather_bytes(shard_b, n),
                    axis=self.axis, bucket=bi, overlappable=True,
                    note="param all-gather at use; hides under the "
                         "previous layer's compute")
                cm.register(
                    "fsdp_reduce_scatter_grads",
                    ring_reduce_scatter_bytes(shard_b, n),
                    axis=self.axis, bucket=bi, overlappable=not last,
                    note="grad reduce-scatter, issued as backward "
                         "drains; ring model per chip")
            if rep_b:
                cm.register(
                    "allreduce_grads_bucket",
                    ring_allreduce_bytes(rep_b, n),
                    axis=self.axis, bucket=bi, overlappable=not last,
                    note="replicated-leaf grad pmean (blobs under the "
                         "shard threshold)")
        cm.register(
            "allreduce_state", ring_allreduce_bytes(sb, n),
            axis=self.axis,
            note="pmean(state) per step, ring model per chip")
