"""PipelineLMSolver — transformer_lm trained with its trunk as a GPipe
pipeline over a "pipe" mesh axis.

Completes VERDICT round-2 item 4: pipeline parallelism was a tested but
orphaned primitive (parallel/pipeline.py); this makes it a usable solver
strategy reachable from the zoo/CLI (`sparknet lm --pipeline-stages S`).

Structure (zoo.transformer_lm_pieces):
  prefix  (embed)      — replicated, computed identically on every stage
  blocks  (x L)        — ONE CompiledNet traced once; its params stacked on
                         a leading (L, ...) dim, sharded P("pipe") so each
                         stage owns L/S consecutive blocks; the forward is
                         parallel.pipeline.pipeline_apply (GPipe schedule:
                         M microbatches, ppermute between stages)
  suffix  (head+loss)  — replicated

The optimizer is the stock caffe-semantics Updater applied to the flat
{prefix..., blocks..., suffix...} param dict — stacked leaves update
elementwise, so SGD/momentum/Adam math is identical to the unpipelined
net's. Gradient equivalence against a single-device zoo.transformer_lm
step (same param values, same batch) is asserted by
tests/test_pipeline_solver.py.

No reference twin (SURVEY.md section 2c: PP absent from the CNN-era
reference); the design target is the framework's own axis map (README).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..graph.compiler import CompiledNet, TRAIN
from ..solver.lr_policy import make_lr_fn
from ..solver.updates import Updater
from .pipeline import pipeline_apply, stack_params
from .data_parallel import check_global_feed, place_tree
from .mesh import make_mesh


def _flat(prefix_name, layer_params):
    return {f"{prefix_name}/{ln}": list(blobs)
            for ln, blobs in layer_params.items()}


def _unflat(flat, prefix_name):
    plen = len(prefix_name) + 1
    return {k[plen:]: v for k, v in flat.items()
            if k.startswith(prefix_name + "/")}


class PipelineLMSolver:
    """Minimal Solver-shaped driver (train_step / step / params / iter)
    for the pipelined LM. Deliberately NOT a Solver subclass: the graph is
    three CompiledNets composed functionally, not one net, so the base
    class's net-centric checkpoint/test machinery doesn't apply."""

    def __init__(self, solver_param, mesh=None, num_layers=4,
                 num_microbatches=None, axis="pipe", dtype=jnp.float32,
                 log_fn=print, metrics=None, compute_dtype=None,
                 **lm_kwargs):
        from ..models import zoo
        self.param = solver_param
        self.log = log_fn or (lambda *a: None)
        if jax.process_count() > 1 and int(solver_param.random_seed) < 0:
            # the pipe axis spans hosts: every host must hold the SAME
            # stacked params and batch (global-feed discipline, like
            # Seq/ExpertParallelSolver)
            raise ValueError(
                "multi-process PipelineLMSolver requires an explicit "
                "SolverParameter.random_seed: hosts must agree on param "
                "init and rng streams")
        self._own_metrics = isinstance(metrics, str)
        if isinstance(metrics, str):
            from ..utils.metrics import MetricsLogger
            metrics = MetricsLogger(metrics)
        self.metrics = metrics
        from ..obs import Tracer
        self.tracer = Tracer(self.metrics)
        self.stepstats = self.comms = self.memstats = None
        self._comms_registered = False
        if self.metrics is not None:
            from ..obs import StepAccounting, CommsMeter, MemoryMonitor
            self.stepstats = StepAccounting(self.metrics)
            self.comms = CommsMeter(self.metrics)
            self.memstats = MemoryMonitor(self.metrics)
        self.mesh = mesh if mesh is not None else make_mesh({axis: -1})
        self.axis = axis
        S = self.mesh.shape[axis]
        if num_layers % S:
            raise ValueError(f"num_layers {num_layers} not divisible by "
                             f"pipeline stages {S}")
        self.num_layers = num_layers
        self.num_microbatches = num_microbatches or max(2 * S, 1)
        prefix_np, block_np, suffix_np = zoo.transformer_lm_pieces(
            **lm_kwargs)
        self.prefix = CompiledNet(prefix_np, TRAIN, dtype=dtype,
                                  compute_dtype=compute_dtype)
        self.suffix = CompiledNet(suffix_np, TRAIN, dtype=dtype,
                                  compute_dtype=compute_dtype)
        self.batch_size, self.seq_len = self.prefix.feed_shapes()["data"]
        if self.batch_size % self.num_microbatches:
            raise ValueError(
                f"batch {self.batch_size} not divisible by "
                f"microbatches {self.num_microbatches}")
        # the block runs on MICROBATCHES inside the gpipe schedule — its
        # static shapes must be (B/M, S, E)
        mb = self.batch_size // self.num_microbatches
        d_model = self.suffix.feed_shapes()["x"][2]
        self.block = CompiledNet(
            block_np, TRAIN, dtype=dtype, compute_dtype=compute_dtype,
            feed_shapes={"x": (mb, self.seq_len, d_model)})

        seed = int(solver_param.random_seed)
        self.rng = jax.random.PRNGKey(seed if seed >= 0 else 0)
        self.rng, kp, ks = jax.random.split(self.rng, 3)
        prefix_p, _ = self.prefix.init(kp)
        suffix_p, _ = self.suffix.init(ks)
        block_ps = []
        for i in range(num_layers):
            self.rng, kb = jax.random.split(self.rng)
            bp, _ = self.block.init(kb)
            block_ps.append(bp)
        self.params = {**_flat("prefix", prefix_p),
                       **_flat("blocks", stack_params(block_ps)),
                       **_flat("suffix", suffix_p)}
        mults = {ln: [(1.0, 1.0)] * len(v) for ln, v in self.params.items()}
        self.updater = Updater(solver_param, mults)
        self.history = self.updater.init(self.params)
        # place params/history on the mesh up front (stage-sharded blocks,
        # replicated ends); required for multi-process, where jit cannot
        # shard host-local arrays across hosts itself
        pspec = {ln: [P(self.axis) if ln.startswith("blocks/") else P()
                      for _ in blobs]
                 for ln, blobs in self.params.items()}
        hspec = {ln: [[pspec[ln][i]] * len(slot)
                      for i, slot in enumerate(self.history[ln])]
                 for ln in self.history}
        self.params = place_tree(self.params, pspec, self.mesh)
        self.history = place_tree(self.history, hspec, self.mesh)
        self.lr_fn = make_lr_fn(solver_param)
        self.iter = 0
        self._it_dev = None
        self._jit_train = None
        self._last_loss = None
        self.snapshot_prefix = None   # set to enable periodic snapshots

    # -- forward/loss ------------------------------------------------------
    def _loss_fn(self):
        prefix, block, suffix = self.prefix, self.block, self.suffix
        mesh, M, axis = self.mesh, self.num_microbatches, self.axis

        def block_fn(bp, h):
            blobs, _ = block.apply(bp, {}, {"x": h}, train=True)
            return blobs["res2"]

        def loss_fn(params, batch, rng):
            pp = _unflat(params, "prefix")
            bp = _unflat(params, "blocks")
            sp_ = _unflat(params, "suffix")
            blobs, _ = prefix.apply(pp, {}, batch, train=True)
            h = pipeline_apply(block_fn, bp, blobs["embed"], mesh, M,
                               axis=axis)
            loss, (sblobs, _) = suffix.loss_fn(
                sp_, {}, {"x": h, "label": batch["label"]}, rng)
            return loss

        return loss_fn

    def _build_train_step(self):
        loss_fn = self._loss_fn()
        updater, lr_fn = self.updater, self.lr_fn

        def step(params, history, batch, it, rng):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch, rng))(params)
            params, history = updater(params, grads, history, lr_fn(it), it)
            return params, history, loss, it + 1

        rep = NamedSharding(self.mesh, P())
        piped = NamedSharding(self.mesh, P(self.axis))
        pshard = {ln: [piped if ln.startswith("blocks/") else rep
                       for _ in blobs]
                  for ln, blobs in self.params.items()}
        hshard = {ln: [[pshard[ln][i]] * len(slot)
                       for i, slot in enumerate(self.history[ln])]
                  for ln in self.history}
        return jax.jit(step,
                       in_shardings=(pshard, hshard, rep, rep, rep),
                       out_shardings=(pshard, hshard, rep, rep),
                       donate_argnums=(0, 1))

    # -- public API --------------------------------------------------------
    def smoothed_loss(self):
        """Latest step loss (one fetch), or None before any step — same
        accessor Solver exposes, so drivers stay solver-agnostic."""
        if self._last_loss is None:
            return None
        return float(self._last_loss)

    def _register_comms(self, cm):
        """GPipe stage traffic: every microbatch activation crosses each
        stage boundary once forward (ppermute) and its gradient once
        backward — per chip that is M microbatch activations out per
        direction per step."""
        from ..obs.comms import tree_bytes
        S = self.mesh.shape[self.axis]
        mb = self.batch_size // self.num_microbatches
        d_model = self.suffix.feed_shapes()["x"][2]
        act = mb * self.seq_len * d_model * 4       # f32 carrier
        cm.set_topology(strategy=type(self).__name__,
                        n_devices=self.mesh.size,
                        axes=dict(self.mesh.shape),
                        param_bytes=tree_bytes(self.params))
        if S > 1:
            cm.register("pipeline_ppermute",
                        2 * self.num_microbatches * act, axis=self.axis,
                        note="microbatch activations fwd + grads bwd, "
                             "per chip per step")

    def _obs_step(self, host_s, result, batch):
        if self.stepstats is None:
            return
        if not self._comms_registered:
            self._comms_registered = True
            try:
                self._register_comms(self.comms)
            except Exception as e:
                self.log(f"comms registration failed: {e!r}")
        from ..obs.comms import tree_bytes
        it = self.iter - 1
        self.comms.add_h2d(tree_bytes(batch))
        self.comms.tick(it)
        sampled = self.stepstats.observe(it, host_s, result=result,
                                         jit_fn=self._jit_train, batch=batch)
        if sampled and self.memstats is not None:
            try:
                self.memstats.sample(it, jit_fns=(self._jit_train,))
            except Exception as e:
                self.log(f"memstats sampling failed: {e!r}")

    def close(self):
        """Flush observability summaries; close an owned metrics stream.
        Mirrors Solver.close() so drivers stay solver-agnostic."""
        self.memstats = None
        if self.stepstats is not None:
            try:
                self.stepstats.flush(self.iter)
            finally:
                self.stepstats = None
        if self.comms is not None:
            try:
                self.comms.flush(self.iter - 1)
            finally:
                self.comms = None
        if self._own_metrics and self.metrics is not None:
            self.metrics.close()
            self.metrics = None

    def train_step(self, batch):
        import time
        if self._jit_train is None:
            self._jit_train = self._build_train_step()
        if jax.process_count() > 1 and not getattr(self, "_feed_checked",
                                                   False):
            self._feed_checked = True
            check_global_feed(batch)
        self.rng, key = jax.random.split(self.rng)
        if self._it_dev is None:
            self._it_dev = jnp.asarray(self.iter, jnp.int32)
        t0 = time.perf_counter()
        batch = place_tree({k: np.asarray(v) for k, v in batch.items()},
                           {k: P() for k in batch}, self.mesh)
        self.params, self.history, loss, self._it_dev = self._jit_train(
            self.params, self.history, batch, self._it_dev, key)
        self.iter += 1
        self._last_loss = loss
        self._obs_step(time.perf_counter() - t0, loss, batch)
        return loss

    def step(self, num_iters, data_iter):
        import time
        sp = self.param
        t_last, it_last = time.time(), self.iter
        for _ in range(num_iters):
            loss = self.train_step(next(data_iter))
            if sp.display and (self.iter - 1) % sp.display == 0:
                v = float(loss)
                lr = float(self.lr_fn(self.iter - 1))
                self.log(f"Iteration {self.iter - 1}, loss = {v:.6g}, "
                         f"lr = {lr:.6g}")
                if self.metrics:
                    dt = time.time() - t_last
                    steps = self.iter - it_last
                    toks = steps * self.batch_size * self.seq_len
                    self.metrics.log(
                        "train", iter=self.iter - 1, loss=v, lr=lr,
                        tokens_per_sec=round(toks / dt, 1) if dt > 0
                        else None)
                    t_last, it_last = time.time(), self.iter
            if sp.snapshot and self.snapshot_prefix \
                    and self.iter % int(sp.snapshot) == 0:
                self.snapshot(self.snapshot_prefix)

    # -- checkpointing (npz — the pipelined param layout is not a net) -----
    def snapshot(self, prefix):
        flat = {}
        for ln, blobs in self.params.items():
            for i, b in enumerate(blobs):
                flat[f"p/{ln}@{i}"] = np.asarray(b)
        for ln, blobs in self.history.items():
            for i, slots in enumerate(blobs):
                for s, h in enumerate(slots):
                    flat[f"h/{ln}@{i}@{s}"] = np.asarray(h)
        path = f"{prefix}_iter_{self.iter}.lm.npz"
        # crash-safe: a relaunch must never see a torn .lm.npz (SPK301)
        from ..resilience.checkpoint import atomic_write_bytes
        atomic_write_bytes(
            path, lambda f: np.savez(f, __iter__=self.iter, **flat))
        self.log(f"Snapshotting to {path}")
        return path

    def restore(self, path):
        z = np.load(path)
        self.iter = int(z["__iter__"])
        self._it_dev = None
        new_p = {ln: list(blobs) for ln, blobs in self.params.items()}
        new_h = {ln: [list(slots) for slots in blobs]
                 for ln, blobs in self.history.items()}
        for k in z.files:
            if k == "__iter__":
                continue
            kind, rest = k.split("/", 1)
            if kind == "p":
                ln, i = rest.rsplit("@", 1)
                ref = new_p[ln][int(i)]
                new_p[ln][int(i)] = jnp.asarray(z[k], ref.dtype)
            else:
                ln, i, s = rest.rsplit("@", 2)
                ref = new_h[ln][int(i)][int(s)]
                new_h[ln][int(i)][int(s)] = jnp.asarray(z[k], ref.dtype)
        self.params, self.history = new_p, new_h
