"""dp x sp solver: data parallelism composed with sequence parallelism.

The long-context training runner: batch dim sharded over a "data" mesh
axis, sequence dim sharded over a "seq" axis. Inside the shard_map the
net's sequence-aware layers pick the "seq" axis up from parallel.context
— Attention(ring=True) runs ring attention (parallel/ring.py: K/V blocks
rotate via ppermute, O(S/sp) memory per chip), PositionalEmbed offsets
its table lookup by the shard's global position, and SoftmaxWithLoss's
per-token mean distributes exactly over equal shards, so

    pmean_{data,seq}(local loss) == the single-device loss

and one grads-pmean over both axes makes the update identical to
single-device training on the global batch (test_seq_parallel.py asserts
the whole loss CURVE matches to tolerance).

The reference has no sequence dimension at all (CNN-era; SURVEY.md
section 5 lists long-context as a framework extension); the analog of
this file's job there is P2PSync's single data axis (parallel.cpp), which
here is just the "data" half of the mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..solver.solver import Solver
from ..obs.divergence import consensus_stats, _sq_sum, gather_worker_scalar
from .data_parallel import _rebatch, _batch_specs, shard_batch, \
    check_global_feed, check_seq_shardable_losses
from . import context
from .compat import shard_map, axis_size


class SeqParallelSolver(Solver):
    """Solver whose step runs under shard_map over ("data", "seq"):
    batch dim 0 sharded over data, dim 1 (sequence) sharded over seq;
    params/state/history replicated; grads pmean'd over both axes.

    Multi-process feeding discipline: EVERY host passes the full global
    batch (token blobs are bytes-per-element small, unlike image
    batches) and shard_batch's callback path hands each host's devices
    their (data, seq) blocks — per-host batch slicing can't express a
    sequence axis that spans hosts. check_batch therefore validates
    against GLOBAL shapes on every host."""

    def __init__(self, solver_param, mesh=None, data_axis="data",
                 seq_axis="seq", **kw):
        from .mesh import make_mesh
        if jax.process_count() > 1 and int(solver_param.random_seed) < 0:
            # every replicated input (params at init, the dropout key per
            # step) must be IDENTICAL across hosts; an unset seed falls
            # back to per-host clock entropy and training silently desyncs
            raise ValueError(
                "multi-process SeqParallelSolver requires an explicit "
                "SolverParameter.random_seed: hosts must agree on param "
                "init and rng streams")
        self.mesh = mesh if mesh is not None else \
            make_mesh({data_axis: 1, seq_axis: -1})
        self.data_axis, self.seq_axis = data_axis, seq_axis
        if int(solver_param.iter_size) > 1:
            raise ValueError("SeqParallelSolver does not support "
                             "iter_size > 1")
        super().__init__(solver_param, **kw)
        check_seq_shardable_losses(self.net, "SeqParallelSolver")
        dp = self.mesh.shape[data_axis]
        sp = self.mesh.shape[seq_axis]
        self.local_net = _rebatch(self.net, dp, seq=sp)
        self.local_test_net = _rebatch(self.test_net, dp, seq=sp) \
            if self.test_net is not None else None

    def _axes_context(self):
        return context.axis_context(data=self.data_axis, seq=self.seq_axis)

    def _batch_spec(self, batch):
        return _batch_specs(batch, self.data_axis,
                            seq_axis=self.seq_axis)

    def _sharded_step(self, batch_example):
        net, updater, lr_fn = self.local_net, self.updater, self.lr_fn
        da, sa = self.data_axis, self.seq_axis
        with_stats = self.stepstats is not None
        loss_fn = self._wrapped_loss(net)

        def step(params, state, history, batch, it, rng):
            # distinct rng stream per shard (dropout etc.)
            flat_idx = jax.lax.axis_index(da) * axis_size(sa) \
                + jax.lax.axis_index(sa)
            rng = jax.random.fold_in(rng, flat_idx)

            def lf(p):
                loss, (blobs, new_state) = loss_fn(p, state, batch, rng)
                return loss, new_state
            (loss, state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            # seq shards hold partial grads of the same data-worker's
            # batch slice: average over seq first, THEN measure the
            # between-data-worker divergence (the gradient noise) around
            # the data-axis pmean when stats are on
            g_seq = jax.lax.pmean(grads, sa)
            if with_stats:
                grads, aux = consensus_stats(g_seq, da)
                aux["ref_sq"] = _sq_sum(grads)
                aux["worker_loss"] = gather_worker_scalar(
                    jax.lax.pmean(loss, sa), da)
            else:
                grads = jax.lax.pmean(g_seq, da)
                aux = {}
            loss = jax.lax.pmean(jax.lax.pmean(loss, sa), da)
            state = jax.lax.pmean(jax.lax.pmean(state, sa), da)
            params, history = updater(params, grads, history, lr_fn(it), it)
            return params, state, history, loss, it + 1, aux

        bspec = self._batch_spec(batch_example)
        sharded = shard_map(
            step, mesh=self.mesh,
            in_specs=(P(), P(), P(), bspec, P(), P()),
            out_specs=(P(), P(), P(), P(), P(), P()),
            check_vma=False)
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _build_train_step(self):
        return None              # built lazily on the first batch

    def _register_comms(self, cm):
        """Grads/state pmean over both axes (costed as one ring over the
        full mesh), plus ring attention's neighbor ppermute traffic —
        each attention layer rotates its local K/V shard around the seq
        ring once per step (forward; backward re-runs the ring, x2)."""
        from ..obs.comms import tree_bytes, ring_allreduce_bytes
        from .ring import ring_attention_comm_bytes
        super()._register_comms(cm)
        nd = self.mesh.size
        sp = self.mesh.shape[self.seq_axis]
        gb = tree_bytes(self.params) + tree_bytes(self.state)
        cm.set_topology(axes=dict(self.mesh.shape))
        cm.register("allreduce_grads", ring_allreduce_bytes(gb, nd),
                    axis=f"{self.data_axis}x{self.seq_axis}",
                    note="pmean(grads)+pmean(state) per step")
        if sp > 1:
            itemsize = np.dtype(self.net.compute_dtype
                                or self.net.dtype).itemsize
            ring_b = 0
            for lp, impl, bottoms, _ in self.local_net.layers:
                if getattr(impl, "ring", False):
                    b, s_local = self.local_net.blob_shapes[bottoms[0]][:2]
                    block = (b, s_local, getattr(impl, "inner", 0))
                    ring_b += ring_attention_comm_bytes(block, sp,
                                                        itemsize=itemsize)
            if ring_b:
                # backward replays the K/V rotation: ~2x forward traffic
                cm.register("ring_attention_ppermute", 2 * ring_b,
                            axis=self.seq_axis,
                            note="K/V block rotation, fwd+bwd, per chip "
                                 "(analytic, from local activation shapes)")

    def _shard(self, batch):
        return shard_batch(batch, self.mesh, self.data_axis,
                           seq_axis=self.seq_axis, global_feed=True)

    def train_step(self, batch):
        import time as _time
        self.check_batch(batch, split_across_hosts=False)
        t0 = _time.perf_counter()
        if not getattr(self, "_feed_checked", False):
            self._feed_checked = True
            check_global_feed(batch)
        self.rng, key = jax.random.split(self.rng)
        with self._axes_context():
            if self._jit_train is None:
                self._jit_train = self._sharded_step(batch)
            dev = self._shard(batch)
            if self._it_dev is None:     # device-resident counter, like
                self._it_dev = jnp.asarray(self.iter, jnp.int32)  # Solver
            (self.params, self.state, self.history, loss,
             self._it_dev, aux) = self._jit_train(
                self.params, self.state, self.history, dev,
                self._it_dev, key)
        self.iter += 1
        host_s = _time.perf_counter() - t0
        self._timing["train_step"] += host_s
        self._obs_step(host_s, loss, batch,
                       aux=dict(aux, kind="grads") if aux else None)
        return loss

    def _build_eval_step(self):
        net = self.local_test_net
        da, sa = self.data_axis, self.seq_axis
        tf = self.test_input_transform
        compiled = {}

        def ev(params, state, batch):
            if tf is not None:
                batch = tf(batch)
            blobs, _ = net.apply(params, state, batch, train=False)
            return {b: jax.lax.pmean(jax.lax.pmean(
                jnp.asarray(blobs[b], jnp.float32), sa), da)
                    for b in net.output_blobs}

        def stepper(params, state, batch):
            # no np.asarray: test() feeds device arrays and a forced
            # fetch would serialize its pipelined eval loop
            key = tuple(sorted((k, tuple(np.shape(v)))
                               for k, v in batch.items()))
            with self._axes_context():
                if key not in compiled:
                    bspec = self._batch_spec(batch)
                    compiled[key] = jax.jit(shard_map(
                        ev, mesh=self.mesh, in_specs=(P(), P(), bspec),
                        out_specs=P(), check_vma=False))
                return compiled[key](params, state, self._shard(batch))

        return stepper
