"""Device mesh construction + multi-host bring-up.

The reference's cluster substrate was spark-ec2 + JVM broadcast (SURVEY L7);
here the substrate is a `jax.sharding.Mesh` whose axes name the parallelism
strategies. Axis names used throughout the framework:

  "data"   data parallelism (gradient psum / local-SGD pmean)
  "model"  tensor parallelism (reserved; used by sharded InnerProduct)
  "seq"    sequence/context parallelism (ring attention)
  "pipe"   pipeline parallelism (reserved)
  "host"   host fault domains (hierarchical local SGD: per-step pmean
           inside a host over "data", tau-interval masked averaging
           across "host" — see parallel/multihost.py)
"""

import os

import numpy as np
import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
HOST_AXIS = "host"


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {axis_name: size}. A single -1 size is inferred
    from the device count (like a reshape). Default: all devices on "data".

    >>> make_mesh({"data": -1})
    >>> make_mesh({"data": 2, "seq": 4})
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {DATA_AXIS: n})
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1], dtype=np.int64))
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes, dtype=np.int64))
    if total > n:
        raise ValueError(f"mesh {axes} needs {total} devices, have {n}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def mesh_axis_size(mesh, axis):
    return mesh.shape[axis] if axis in mesh.shape else 1


def make_tp_mesh(tp, devices=None):
    """The 2-D (data, model) mesh of the tensor-parallel lever
    (`--tp N` / SPARKNET_TP): "model" gets ``tp`` devices (the
    Megatron group — keep it inside one chip ring so the row-split
    psums ride ICI), "data" the rest."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"need tp >= 1, got {tp}")
    return make_mesh({DATA_AXIS: -1, MODEL_AXIS: tp}, devices=devices)


def make_host_device_mesh(hosts=None, per_host=None, device_axis=DATA_AXIS,
                          devices=None):
    """Build the 2-D ``(host, device)`` mesh the hierarchical runtime
    trains on: axis "host" indexes fault domains (normally one jax
    process each), ``device_axis`` (default "data") the devices inside
    one. Row h of the mesh holds host h's local devices, so the "host"
    collectives cross DCN and the inner per-step pmean stays on ICI.

    Multi-process: hosts defaults to jax.process_count(), per_host to
    the local device count, and devices are grouped by owning process.
    Single-process: hosts x per_host partitions the local devices into
    VIRTUAL fault domains — how the tests (and laptop runs) exercise the
    two-tier path without a pod."""
    devices = list(devices if devices is not None else jax.devices())
    # group rows by owning process: jax.devices() order is not
    # contractually process-major, the mesh layout must be
    devices.sort(key=lambda d: (d.process_index, d.id))
    if hosts is None:
        hosts = jax.process_count()
    hosts = int(hosts)
    if hosts < 1:
        raise ValueError(f"need >= 1 host, got {hosts}")
    if per_host is None:
        if len(devices) % hosts:
            raise ValueError(f"{len(devices)} devices not divisible by "
                             f"{hosts} hosts")
        per_host = len(devices) // hosts
    per_host = int(per_host)
    need = hosts * per_host
    if need > len(devices):
        raise ValueError(f"host mesh {hosts}x{per_host} needs {need} "
                         f"devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(hosts, per_host)
    return Mesh(arr, (HOST_AXIS, device_axis))


def is_local_mesh(mesh):
    """True when every device of ``mesh`` belongs to THIS process —
    compiled programs over it never touch the cross-host fabric, so a
    surviving host can keep training after its peers died (the
    shrink-to-survivors path of the hierarchical runtime)."""
    me = jax.process_index()
    return all(d.process_index == me for d in mesh.devices.flat)


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Multi-host bring-up over DCN — the analog of the reference's
    spark-submit cluster launch (SETUP.md). On TPU pods the three args are
    discovered from the metadata server; env vars override for manual runs.

    No-op when running single-process (the common dev path)."""
    coordinator_address = coordinator_address or os.environ.get(
        "SPARKNET_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("SPARKNET_NUM_PROCESSES", 0)) or None
    if process_id is None:
        pid = os.environ.get("SPARKNET_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if coordinator_address is None and num_processes is None:
        return False  # single-process
    if distributed_initialized():
        return True   # already initialized (CLI + app both call this)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def distributed_initialized():
    """Has jax.distributed been brought up in this process? The public
    module does not re-export the client state on every jax vintage, so
    probe the private module too (a second initialize raises)."""
    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        try:
            from jax._src.distributed import global_state as state
        except Exception:
            state = None
    return state is not None and getattr(state, "client", None) is not None


def local_batch_slice(global_batch_size, mesh=None, axis=DATA_AXIS):
    """(start, size) of this host's slice of the global batch — the analog of
    Spark's per-worker RDD partition (CifarApp.scala repartition :64): each
    host loads only its own shard of every global batch."""
    pcount = jax.process_count()
    pid = jax.process_index()
    if global_batch_size % pcount:
        raise ValueError(f"global batch {global_batch_size} not divisible by "
                         f"{pcount} hosts")
    per = global_batch_size // pcount
    return pid * per, per
