"""Trace-time parallelism context.

Layers that can exploit a mesh axis (Attention's ring mode, sharded
InnerProduct) need to know, while being traced, which named axes the
surrounding shard_map provides. jax deliberately hides this, so the
distributed runners publish it here before tracing the net body. The axis
names get baked into the traced computation — exactly once, at compile time.
"""

import contextlib
import threading

_state = threading.local()


def current_axes():
    """Mapping {logical_axis: mesh_axis_name or None} in effect."""
    return getattr(_state, "axes", {})


@contextlib.contextmanager
def axis_context(**axes):
    """e.g. with axis_context(data="data", seq="seq"): trace the step."""
    prev = current_axes()
    merged = dict(prev)
    merged.update(axes)
    _state.axes = merged
    try:
        yield merged
    finally:
        _state.axes = prev


def axis(name):
    return current_axes().get(name)


def current_world():
    """Trace-time world info published by the distributed solvers:
    {"axis": mesh axis name, "size": N workers, "elastic": bool}.
    Layers that fold per-worker statistics across the data axis (e.g. a
    cross-replica batch norm) consult ``elastic`` to know that the
    surrounding round masks invalid workers out of its collectives —
    and that they should do the same rather than a plain pmean."""
    return getattr(_state, "world", {})


@contextlib.contextmanager
def world_context(**info):
    """e.g. with world_context(axis="data", size=8, elastic=True): trace
    the round body."""
    prev = current_world()
    _state.world = dict(prev, **info)
    try:
        yield _state.world
    finally:
        _state.world = prev


# -- host topology (multi-host runtime) -------------------------------------
# Unlike the trace-time axis/world contexts above, the host topology is a
# process-wide constant: one process == one fault domain, fixed at
# jax.distributed bring-up. parallel/multihost.py publishes it once;
# everything host-side (heartbeats, coordinated restart, per-host data
# slicing) reads it from here instead of re-deriving it from jax.
_host_topology = None


def publish_host_topology(info):
    """Record this process's host topology (parallel/multihost.py calls
    this after jax.distributed bring-up). ``info``: a mapping with at
    least process_id / num_processes / local_device_count /
    global_device_count."""
    global _host_topology
    _host_topology = dict(info)
    return _host_topology


def current_host():
    """The published host topology dict, or a single-host default when
    the multihost runtime never initialized (the common dev path)."""
    if _host_topology is not None:
        return dict(_host_topology)
    return {"process_id": 0, "num_processes": 1,
            "local_device_count": None, "global_device_count": None}
