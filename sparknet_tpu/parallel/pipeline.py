"""GPipe-style pipeline parallelism over a "pipe" mesh axis.

sparknet_tpu extension (SURVEY.md section 2c: PP absent from the
reference); completes the mesh-axis set next to dp (pmean), tp (gspmd),
sp (ring/Ulysses) and ep (MoE all_to_all).

The model's repeated trunk (e.g. transformer blocks) is expressed as ONE
``block_fn(block_params, x) -> x`` applied L times with stacked params —
leaves shaped (L, ...). Stages shard that stack over the "pipe" axis
(leading dim, P("pipe")), so each device owns L/S consecutive blocks and
applies them with an inner ``lax.scan``. The batch is split into M
microbatches; the classic GPipe schedule runs M + S - 1 ticks, each tick
being block_fn on every stage followed by one ``ppermute`` shifting
activations to the next stage. Stage 0 injects microbatch t at tick t;
the last stage collects microbatch t at tick t + S - 1; a final masked
``psum`` replicates the collected outputs. Warm-up/drain ticks compute on
zeros — their outputs are never collected and never contribute gradient,
so autodiff through the scan + ppermute chain is exact (bubble cost
(S-1)/(M+S-1) of compute, the GPipe trade).

Embedding/head layers (stage-heterogeneous) stay OUTSIDE the pipeline:
compute them replicated (or data-parallel) before/after ``pipeline_apply``
— they are a tiny fraction of LM FLOPs.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from .compat import shard_map


def gpipe(block_fn, local_params, microbatches, axis):
    """The SPMD schedule; call INSIDE shard_map over ``axis``.

    local_params: this stage's stacked block params, leaves (L_local, ...).
    microbatches: (M, mb, ...) — full input, identical on every stage.
    -> (M, mb, ...) outputs of the final stage, identical on every stage.
    """
    S = lax.psum(1, axis)
    d = lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + S - 1

    def stage(x):
        def body(h, p):
            return block_fn(p, h), None
        out, _ = lax.scan(body, x, local_params)
        return out

    zero_mb = jnp.zeros_like(microbatches[0])
    # pad the injection stream past M with zeros (drain ticks)
    feed = jnp.concatenate(
        [microbatches, jnp.zeros((S - 1,) + microbatches.shape[1:],
                                 microbatches.dtype)]) if S > 1 \
        else microbatches

    def tick(carry, t):
        state, out_buf = carry
        x = jnp.where(d == 0, feed[t], state)
        y = stage(x)
        # last stage holds microbatch t-(S-1) at tick t
        m = t - (S - 1)
        valid = jnp.logical_and(d == S - 1,
                                jnp.logical_and(m >= 0, m < M))
        mi = jnp.clip(m, 0, M - 1)
        out_buf = out_buf.at[mi].set(
            jnp.where(valid, y, out_buf[mi]))
        state = lax.ppermute(y, axis,
                             [(i, (i + 1) % S) for i in range(S)])
        return (state, out_buf), None

    out0 = jnp.zeros_like(microbatches)
    (_, out_buf), _ = lax.scan(tick, (zero_mb, out0), jnp.arange(T))
    # replicate the last stage's collected outputs to every stage
    return lax.psum(jnp.where(d == S - 1, out_buf, jnp.zeros_like(out_buf)),
                    axis)


def pipeline_apply(block_fn, stacked_params, x, mesh, num_microbatches,
                   axis="pipe"):
    """Run a stack of L identical blocks as an S-stage pipeline.

    stacked_params: pytree, leaves (L, ...), L divisible by mesh axis size
    (sharded P(axis) on dim 0 — each stage gets its consecutive blocks).
    x: (B, ...) with B divisible by num_microbatches.
    -> (B, ...) after all L blocks, bitwise-independent of S (tested).
    """
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    S = mesh.shape[axis]
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if L % S:
        raise ValueError(
            f"block count {L} not divisible by pipeline stages {S}")
    mb = x.reshape(M, B // M, *x.shape[1:])

    def inner(params, xs):
        return gpipe(block_fn, params, xs, axis)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    out = shard_map(
        inner, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(), check_vma=False,
    )(stacked_params, mb)
    return out.reshape(B, *x.shape[1:])


def stack_params(per_block_params):
    """[block0_pytree, block1_pytree, ...] (identical structures) ->
    one pytree with leaves stacked on a new leading (L) dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_block_params)
