"""Bucketed gradient allreduce: overlap the DP consensus with backward.

The single whole-tree pmean in DataParallelSolver's step is one giant
collective whose every input is the LAST gradient backward produces
(the first layer's), so XLA's latency-hiding scheduler cannot start any
of it until backward fully drains: the entire 2(N-1)/N * B ring transfer
is exposed on the critical path. Splitting the gradient tree into
fixed-size buckets in REVERSE flatten order restores the dependency
structure the scheduler needs: the first bucket holds the deepest
layers' grads, which backward finishes first, so its allreduce issues
while the remaining layers' backward is still running. Only the
last-issued bucket — the stem/embedding grads — is structurally exposed.

Numerics: masked_consensus / weighted_consensus (resilience/elastic.py)
are elementwise tree_maps followed by pmean; concatenating leaves into
flat per-dtype buffers and running THE SAME functions over the bucket
list is bit-for-bit the unbucketed consensus per element (pmean is
elementwise; concatenation changes neither values nor reduce order
across the axis). tests/test_overlap.py pins that equality exactly.

The stats consensus path (masked_consensus_stats) needs the per-LAYER
tree for its divergence decomposition, so it stays unbucketed — a
documented trade: `--metrics` runs measure gradient noise instead of
maximizing overlap.

Gates: SPARKNET_OVERLAP=on|off (default on — bit-for-bit safe),
SPARKNET_BUCKET_MB (default 4; ~4MB amortizes ring latency without
delaying the first issue, the bucket-size sweet spot most DDP
implementations converged on).
"""

import os

import jax
import jax.numpy as jnp

DEFAULT_BUCKET_MB = 4


def overlap_enabled():
    v = os.environ.get("SPARKNET_OVERLAP", "on").strip().lower()
    if v in ("on", "1", ""):
        return True
    if v in ("off", "0"):
        return False
    raise ValueError(
        f"SPARKNET_OVERLAP={v!r}: expected on|off")


def bucket_bytes():
    mb = os.environ.get("SPARKNET_BUCKET_MB", "").strip()
    mb = float(mb) if mb else float(DEFAULT_BUCKET_MB)
    if mb <= 0:
        raise ValueError(f"SPARKNET_BUCKET_MB={mb}: must be > 0")
    return int(mb * (1 << 20))


def plan_buckets(tree, max_bytes=None):
    """Partition ``tree``'s leaves into contiguous per-dtype buckets of
    at most ``max_bytes`` each, walking leaves in REVERSE flatten order
    (flatten order is layer order, and backward produces the last
    layers' grads first — so bucket 0 is ready earliest). A leaf larger
    than ``max_bytes`` gets a bucket of its own; dtypes never mix inside
    a bucket (concatenation must not upcast). Works on abstract values:
    only shape/dtype are read, so the plan can be built under a trace."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if max_bytes is None:
        max_bytes = bucket_bytes()
    buckets = []
    cur, cur_bytes, cur_dtype = [], 0, None
    for idx in reversed(range(len(leaves))):
        leaf = leaves[idx]
        dt = jnp.result_type(leaf)
        size = 1
        for d in leaf.shape:
            size *= int(d)
        nb = size * dt.itemsize
        if cur and (dt != cur_dtype or cur_bytes + nb > max_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append((idx, tuple(leaf.shape), dt, size))
        cur_dtype = dt
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return {"treedef": treedef, "n_leaves": len(leaves),
            "buckets": buckets}


def bucket_sizes(plan):
    """Per-bucket payload bytes, in issue order — what _register_comms
    feeds the ring model per bucket."""
    return [sum(size * dt.itemsize for _, _, dt, size in b)
            for b in plan["buckets"]]


def to_buckets(plan, tree):
    """Tree -> list of flat 1-D per-dtype buffers, in issue order."""
    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for b in plan["buckets"]:
        flats = [leaves[idx].ravel() for idx, _, _, _ in b]
        out.append(flats[0] if len(flats) == 1 else jnp.concatenate(flats))
    return out


def from_buckets(plan, buckets):
    """Inverse of to_buckets: bucket list -> the original tree. ravel/
    slice/reshape are layout no-ops to XLA, so the roundtrip adds no
    copies beyond the concatenation itself."""
    leaves = [None] * plan["n_leaves"]
    for b, flat in zip(plan["buckets"], buckets):
        off = 0
        for idx, shape, _, size in b:
            leaves[idx] = flat[off:off + size].reshape(shape)
            off += size
    return jax.tree_util.tree_unflatten(plan["treedef"], leaves)


def bucketed_consensus(consensus_fn, grads, weight, axis):
    """Run ``consensus_fn`` (masked_consensus or weighted_consensus —
    both tree-generic) over the bucketed form of ``grads`` and restore
    the tree. Returns the same (consensus, n) pair as the direct call,
    bit-for-bit (see module docstring)."""
    plan = plan_buckets(grads)
    out, n = consensus_fn(to_buckets(plan, grads), weight, axis)
    return from_buckets(plan, out), n
