"""jax API compatibility shims for the parallel layer.

shard_map graduated from ``jax.experimental.shard_map`` into the top
``jax`` namespace across jax releases, renaming ``check_rep`` to
``check_vma`` on the way. The mesh solvers must run on both vintages
(the CI image pins an older jax than TPU pods ship), so every shard_map
call in this package goes through this wrapper instead of ``jax.*``.
"""

import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=None):
    """jax.shard_map where available, else jax.experimental.shard_map
    with check_vma mapped onto the old check_rep flag."""
    try:
        sm = jax.shard_map          # new-style (deprecation getattr may
    except AttributeError:          # raise on older jax)
        sm = None
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as old_sm
    kw = {} if check_vma is None else {"check_rep": bool(check_vma)}
    return old_sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)


def axis_size(name):
    """jax.lax.axis_size where available (newer jax), else the classic
    psum-of-ones — only valid inside shard_map/pmap, like the original."""
    try:
        return jax.lax.axis_size(name)
    except AttributeError:
        return jax.lax.psum(1, name)
