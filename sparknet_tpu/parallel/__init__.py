"""parallel — the distributed heart of sparknet_tpu.

Replaces BOTH of the reference's communication mechanisms with XLA
collectives over a named device mesh:

  * the Spark driver loop (broadcast weights -> tau local SGD steps per
    worker -> collect & average; CifarApp.scala:92-135, Net.scala:14-47)
    becomes `LocalSGDSolver`: one jitted "round" under shard_map whose only
    communication is a single pmean over the ICI mesh per round;
  * Caffe's intra-node GPU tree allreduce (parallel.cpp P2PSync:271-437)
    becomes `DataParallelSolver`: per-step gradient psum inside the compiled
    train step.

Long-context sequence parallelism (absent in the CNN-era reference but
first-class here) lives in `ring`: ring attention via ppermute and
Ulysses-style all-to-all head/sequence resharding. `gspmd` shards
weights+optimizer state (tp/ZeRO-style), `ops.moe` adds expert
parallelism over an "expert" axis, and `pipeline` adds GPipe microbatch
pipelining over a "pipe" axis — the full dp/tp/sp/ep/pp set, each
exercised by the driver's multichip dryrun.
"""

import importlib

__all__ = [
    "make_mesh", "mesh_axis_size", "distributed_init", "local_batch_slice",
    "make_host_device_mesh", "is_local_mesh",
    "axis_context", "current_axes", "world_context", "current_world",
    "publish_host_topology", "current_host",
    "context", "multihost",
    "init_runtime", "host_mesh", "auto_host_mesh", "survivor_mesh",
    "needs_host_relay", "local_batch_rows", "my_host_rows",
    "DataParallelSolver", "LocalSGDSolver", "shard_batch",
    "FSDPSolver", "fsdp_enabled", "plan_param_specs",
    "GSPMDSolver", "default_param_rule", "transformer_tp_rule",
    "SeqParallelSolver",
    "ExpertParallelSolver",
    "ring_attention", "ulysses_attention", "sequence_sharded_apply",
    "gpipe", "pipeline_apply", "stack_params", "PipelineLMSolver",
]

# lazy exports (PEP 562): ops.attention imports parallel.{context,ring} while
# parallel.data_parallel imports solver -> graph -> ops; deferring the
# data_parallel import breaks the cycle.
_EXPORTS = {
    "make_mesh": "mesh", "mesh_axis_size": "mesh",
    "distributed_init": "mesh", "local_batch_slice": "mesh",
    "make_host_device_mesh": "mesh", "is_local_mesh": "mesh",
    "axis_context": "context", "current_axes": "context",
    "world_context": "context", "current_world": "context",
    "publish_host_topology": "context", "current_host": "context",
    "init_runtime": "multihost", "host_mesh": "multihost",
    "auto_host_mesh": "multihost", "survivor_mesh": "multihost",
    "needs_host_relay": "multihost", "local_batch_rows": "multihost",
    "my_host_rows": "multihost",
    "DataParallelSolver": "data_parallel", "LocalSGDSolver": "data_parallel",
    "shard_batch": "data_parallel",
    "FSDPSolver": "fsdp", "fsdp_enabled": "fsdp",
    "plan_param_specs": "fsdp",
    "GSPMDSolver": "gspmd", "default_param_rule": "gspmd",
    "transformer_tp_rule": "gspmd",
    "SeqParallelSolver": "seq_parallel",
    "ExpertParallelSolver": "expert_parallel",
    "ring_attention": "ring", "ulysses_attention": "ring",
    "sequence_sharded_apply": "ring",
    "gpipe": "pipeline", "pipeline_apply": "pipeline",
    "stack_params": "pipeline",
    "PipelineLMSolver": "pipeline_solver",
}


def __getattr__(name):
    if name in _EXPORTS:
        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    if name in ("mesh", "context", "ring", "data_parallel", "multihost"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
