"""GSPMD solver: sharding-annotation parallelism (pjit), no shard_map.

The third strategy next to DataParallelSolver (explicit shard_map collectives)
and LocalSGDSolver (the SparkNet algorithm): annotate the shardings of
params / optimizer state / batch over a (data, model) mesh and let XLA's
SPMD partitioner insert the collectives. This is the idiomatic "scaling
book" recipe — pick a mesh, annotate, let XLA do comm placement — and is
how tensor parallelism enters the framework: large weight blobs shard their
output dimension across the "model" axis (Megatron-style column split for
InnerProduct y = x @ W^T), optimizer history shards identically (ZeRO-ish
for free), the batch shards across "data".

Nothing in reference SparkNet could express this: its only sharding was
whole-model replication (SURVEY.md section 2c).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..solver.solver import Solver
from .mesh import DATA_AXIS, MODEL_AXIS, HOST_AXIS


def default_param_rule(axis_size, min_size=2 ** 14):
    """Shard dim 0 (Caffe's num_output dim for conv & IP weights) over
    "model" when divisible and the blob is big enough to be worth it."""
    def rule(layer_name, idx, shape):
        if shape and shape[0] % axis_size == 0 and \
                int(np.prod(shape)) >= min_size:
            return P(MODEL_AXIS)
        return P()
    return rule


def transformer_tp_rule(axis_size, axis=MODEL_AXIS):
    """Megatron-style tensor parallelism for `zoo.transformer_lm` (and
    `transformer_lm_pieces`) weight names — the opt-in "model" axis of
    the FSDP/TP/precision lever set (SPARKNET_TP / `--tp`).

    Column-split (output dim 0 over "model"): attn wqkv (+ bias), ffn1
    (+ bias), lm_head (+ bias), and the vocab dim of the embedding
    tables — each device computes its own slice of heads/hidden/logits.
    Row-split (input dim 1 over "model"): attn wo and ffn2, whose
    partial products XLA's SPMD partitioner completes with the psum the
    explicit Megatron recipe writes by hand; their biases (added after
    the reduce) stay replicated, as do the LayerNorms. A dim that does
    not divide ``axis_size`` stays replicated rather than erroring —
    the rule degrades blob-by-blob."""
    def col(shape):
        return shape and shape[0] % axis_size == 0

    def row(shape):
        return len(shape) == 2 and shape[1] % axis_size == 0

    def rule(layer_name, idx, shape):
        if axis_size <= 1:
            return P()
        base = layer_name.rsplit("/", 1)[-1]
        if base == "attn":
            # blobs: wqkv (3*inner, embed), bqkv (3*inner,),
            #        wo (embed, inner), bo (embed,)
            if idx in (0, 1) and col(shape):
                return P(axis)
            if idx == 2 and row(shape):
                return P(None, axis)
            return P()
        if base in ("ffn1", "lm_head") and col(shape):
            return P(axis)
        if base == "ffn2" and idx == 0 and row(shape):
            return P(None, axis)
        if base in ("tok_embed", "pos_embed") and idx == 0 and \
                len(shape) == 2 and col(shape):
            return P(axis)
        return P()
    return rule


class GSPMDSolver(Solver):
    """Solver whose compiled step carries sharding annotations.

    mesh must have DATA_AXIS and (optionally) MODEL_AXIS. param_rule:
    fn(layer_name, blob_idx, shape) -> PartitionSpec for that weight blob.
    """

    def __init__(self, solver_param, mesh=None, param_rule=None,
                 seq_axis=None, **kw):
        from .mesh import make_mesh
        self.mesh = mesh if mesh is not None else \
            make_mesh({DATA_AXIS: -1, MODEL_AXIS: 1})
        if param_rule is not None:
            self.param_rule = param_rule
        elif MODEL_AXIS in self.mesh.shape:
            self.param_rule = default_param_rule(
                self.mesh.shape[MODEL_AXIS])
        else:
            # no tensor-parallel axis on this mesh (e.g. the (host,
            # data) fault-domain mesh): replicate every weight blob
            self.param_rule = lambda lname, i, shape: P()
        # optional third axis: shard dim 1 (sequence) of rank>=2 feed
        # blobs — the annotation-style sp that composes dp x tp x sp on
        # one mesh. XLA's SPMD partitioner places the attention/loss
        # collectives itself (no ring schedule; use SeqParallelSolver
        # when you want O(S/sp) attention memory via ppermute).
        self.seq_axis = seq_axis
        super().__init__(solver_param, **kw)
        self._shard_state()

    # -- sharding layout ---------------------------------------------------
    def param_sharding(self):
        out = {}
        for lname, blobs in self.params.items():
            out[lname] = [
                NamedSharding(self.mesh,
                              self.param_rule(lname, i, tuple(b.shape)))
                for i, b in enumerate(blobs)]
        return out

    def _shard_state(self):
        ps = self.param_sharding()
        self.params = {l: [jax.device_put(b, s)
                           for b, s in zip(bs, ps[l])]
                       for l, bs in self.params.items()}
        # history blobs mirror their param's sharding (sharded opt state)
        self.history = {l: [[jax.device_put(h, ps[l][i]) for h in slot]
                            for i, slot in enumerate(hs)]
                        for l, hs in self.history.items()}
        rep = NamedSharding(self.mesh, P())
        self.state = {l: [jax.device_put(a, rep) for a in arrs]
                      for l, arrs in self.state.items()}

    def _batch_sharding(self, batch):
        # a 2-D (host, data) mesh (parallel.multihost.host_mesh) shards
        # the batch dim over host x data — the fault-domain-major layout
        # where each host's processes feed their own rows
        batch_axes = (HOST_AXIS, DATA_AXIS) \
            if HOST_AXIS in self.mesh.shape else DATA_AXIS
        out = {}
        for k, v in batch.items():
            nd = np.ndim(v)
            if not nd:
                spec = P()
            elif self.seq_axis is not None and nd >= 2:
                spec = P(batch_axes, self.seq_axis)
            else:
                spec = P(batch_axes)
            out[k] = NamedSharding(self.mesh, spec)
        return out

    def _memory_step_fn(self, batch):
        # the annotated jit only exists after a first step traced the
        # batch shardings; without one there is nothing to analyse
        return getattr(self, "_jit", None)

    def _memory_step_args(self, batch):
        batch = {k: jax.device_put(np.asarray(v), self._batch_sh[k])
                 for k, v in batch.items()}
        return (self.params, self.state, self.history, batch,
                jnp.asarray(self.iter, jnp.int32), self.rng)

    # -- compiled step -----------------------------------------------------
    def _build_train_step(self):
        fn = self._train_step_fn()
        ps = self.param_sharding()
        ps_tree = {l: list(v) for l, v in ps.items()}
        hist_sh = {l: [[ps[l][i]] * len(slot)
                       for i, slot in enumerate(self.history[l])]
                   for l in self.history}
        rep = NamedSharding(self.mesh, P())
        state_sh = {l: [rep] * len(v) for l, v in self.state.items()}
        self._batch_sh = None

        def stepped(params, state, history, batch, it, rng):
            if self._batch_sh is None:
                self._batch_sh = self._batch_sharding(batch)
                self._jit = jax.jit(
                    fn,
                    in_shardings=(ps_tree, state_sh, hist_sh,
                                  self._batch_sh, rep, rep),
                    out_shardings=(ps_tree, state_sh, hist_sh, rep, rep),
                    donate_argnums=(0, 1, 2))
            if jax.process_count() > 1:
                # each host holds only ITS slice of the batch axis; the
                # global array assembles from per-host shards (same
                # mechanism as data_parallel.shard_batch)
                batch = {k: jax.make_array_from_process_local_data(
                             self._batch_sh[k], np.asarray(v))
                         for k, v in batch.items()}
            else:
                batch = {k: jax.device_put(np.asarray(v), self._batch_sh[k])
                         for k, v in batch.items()}
            return self._jit(params, state, history, batch, it, rng)

        return stepped
