"""Distributed solvers: per-step allreduce DP and tau-step local SGD.

Two strategies, one mesh:

`DataParallelSolver` — synchronous data parallelism. The whole of the
reference's P2PSync machinery (parallel.cpp:271-437: tree topology from P2P
DMA pairs, weights pushed down-tree at on_start, gradients summed up-tree at
on_gradients_ready, one solver thread per GPU) is a single `lax.pmean` of
the gradients inside the compiled step; XLA lowers it to an ICI allreduce.

`LocalSGDSolver` — the SparkNet algorithm itself (CifarApp.scala:92-135):
broadcast weights, tau local SGD steps per worker on its own data shard,
collect and average. Here "broadcast" is replicated-in, "collect/average"
is one `lax.pmean` of the params per round, and the tau inner steps run as a
`lax.scan` — the entire round is ONE compiled XLA program with exactly one
collective, versus the reference's 2 full-model transfers through a JVM
driver per round (spark.driver.maxResultSize=30G, ImageNetApp.scala:42).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..solver.solver import Solver
from ..solver.updates import accum_init, accum_add
from ..obs.divergence import (tree_sq_dist, _sq_sum,
                              gather_worker_scalar)
from ..resilience.elastic import (masked_consensus, masked_consensus_stats,
                                  masked_scalar_mean, tree_finite,
                                  staleness_discount, weighted_consensus,
                                  weighted_consensus_stats)
from .mesh import DATA_AXIS
from . import context
from .compat import shard_map


def shard_batch(batch, mesh, axis=DATA_AXIS, batch_dim=0, seq_axis=None,
                seq_dim=1, global_feed=False):
    """Place a batch dict onto the mesh, sharded along the batch dimension —
    the analog of an RDD partition landing on its executor. With
    ``seq_axis``, rank>=2 blobs are additionally sharded along ``seq_dim``
    (the dp x sp placement of SeqParallelSolver).

    Single-process: ``batch`` is the global batch; device_put scatters it.
    Multi-process (jax.process_count() > 1), two feeding disciplines:
      * global_feed=False — each host passes only ITS slice of the batch
        axis (see mesh.local_batch_slice — the per-worker RDD partition of
        CifarApp.scala:56-64); the global array is assembled from per-host
        shards without any host holding the full batch. Right for image
        batches.
      * global_feed=True — each host passes the FULL global batch and its
        devices pull their blocks via make_array_from_callback. Right when
        the batch is small but sharded along dims a per-host batch slice
        can't express (the sequence axis: a seq mesh axis spanning hosts
        needs per-host SEQUENCE blocks, which hosts can cheaply slice from
        the whole token array).
    Single-process, already-on-device jax arrays are resharded without a
    host round trip; the multihost assembly paths need host-resident data
    and will fetch a device-resident input first.

    ``axis`` may be a tuple of mesh axis names — the batch dim shards
    over their product (the (host, data) layout of the hierarchical
    runtime). A mesh made purely of THIS process's devices (a survivor
    that shrank away its dead peers — mesh.is_local_mesh) always takes
    the single-process device_put path: the global-assembly calls would
    wait on processes that no longer exist.
    """
    multihost = jax.process_count() > 1
    if multihost:
        from .mesh import is_local_mesh
        if is_local_mesh(mesh):
            multihost = False
    out = {}
    for k, v in batch.items():
        if not isinstance(v, jax.Array):
            v = np.asarray(v)
        s = _one_spec(np.ndim(v), axis, batch_dim, seq_axis, seq_dim)
        sharding = NamedSharding(mesh, s)
        if multihost and np.ndim(v):
            if global_feed:
                arr = np.asarray(v)
                out[k] = jax.make_array_from_callback(
                    arr.shape, sharding, lambda idx, a=arr: a[idx])
            else:
                out[k] = jax.make_array_from_process_local_data(
                    sharding, np.asarray(v))
        else:
            out[k] = jax.device_put(v, sharding)
    return out


def place_tree(tree, specs, mesh):
    """Place every leaf of ``tree`` on ``mesh`` per the matching
    PartitionSpec in ``specs`` (a pytree of specs with the same
    structure, or prefixes of it). Single-process: device_put.
    Multi-process: every host holds the full value (seed-identical
    init — the global-feed discipline), so the global array assembles
    via make_array_from_callback."""
    multihost = jax.process_count() > 1

    def put(spec, sub):
        sh = NamedSharding(mesh, spec)

        def one(x):
            if multihost:
                arr = np.asarray(x)
                return jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, a=arr: a[idx])
            return jax.device_put(x, sh)
        # sub may be a SUBTREE (specs as a prefix tree: e.g. one spec per
        # param covering all its history slots)
        return jax.tree_util.tree_map(one, sub)

    return jax.tree_util.tree_map(put, specs, tree,
                                  is_leaf=lambda s: isinstance(s, P))


def check_seq_shardable_losses(net, solver_name):
    """Sequence-sharded exactness (pmean of per-shard means == global
    mean) requires every shard to normalize by the same token count; a
    loss with ignore_label normalizes by its LOCAL valid count, so shards
    with more padding would weigh their tokens more — silently biased
    gradients. Refuse rather than mis-train."""
    for lp, impl, _, _ in net.layers:
        if getattr(impl, "ignore_label", None) is not None and \
                net.loss_weights.get(lp.name) and \
                any(net.loss_weights[lp.name]):
            raise ValueError(
                f"layer {lp.name!r}: ignore_label losses normalize by "
                f"the per-shard valid-token count, which breaks "
                f"{solver_name}'s equal-shard loss/grad exactness "
                "(shards with more padding would be over-weighted). "
                "Drop ignore_label or mask labels on the host instead.")


def check_global_feed(batch):
    """First-step agreement check for the global-feed discipline (every
    host passes the SAME full batch; devices pull their own blocks): a
    per-host rng would desync silently — devices would pull blocks from
    their own host's divergent copy — so one cross-host checksum
    comparison surfaces it. Call once, on the first fed batch."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    sums = np.array([np.asarray(v, np.float64).sum()
                     for _, v in sorted(batch.items())])
    gathered = multihost_utils.process_allgather(sums)
    if not np.allclose(gathered, gathered[0]):
        raise ValueError(
            "global-feed batches differ across hosts (first-step "
            "checksum mismatch): every host must construct the identical "
            "global batch")


def _rebatch(net, n, seq=1):
    """Compile a per-shard twin of ``net``: identical params/layers and
    precision, feed blobs with leading (batch) dim divided by ``n`` (and,
    for ``seq > 1``, dim 1 divided by ``seq``)."""
    from ..graph.compiler import CompiledNet
    local = {}
    for name, s in net.feed_shapes().items():
        if not s:
            local[name] = s
            continue
        if s[0] % n:
            raise ValueError(
                f"feed blob {name!r} batch {s[0]} not divisible by mesh "
                f"axis size {n}")
        out = [s[0] // n] + list(s[1:])
        if seq > 1 and len(s) >= 2:
            # rank-1 (per-example) blobs need no sequence shard: _one_spec
            # already leaves them replicated along the seq axis
            if s[1] % seq:
                raise ValueError(
                    f"feed blob {name!r} seq dim {s[1]} not divisible "
                    f"by seq axis size {seq}")
            out[1] = s[1] // seq
        local[name] = tuple(out)
    return CompiledNet(net.net_param, net.phase, feed_shapes=local,
                       dtype=net.dtype, compute_dtype=net.compute_dtype)


def _one_spec(ndim, axis, batch_dim=0, seq_axis=None, seq_dim=1):
    if not ndim:
        return P()
    spec = [None] * ndim
    if batch_dim < ndim:
        spec[batch_dim] = axis
    if seq_axis is not None and seq_dim < ndim:
        spec[seq_dim] = seq_axis
    return P(*spec)


def _batch_specs(batch, axis, batch_dim=0, seq_axis=None, seq_dim=1):
    return {k: _one_spec(np.ndim(v), axis, batch_dim, seq_axis, seq_dim)
            for k, v in batch.items()}


class DataParallelSolver(Solver):
    """Solver whose train step runs under shard_map over the "data" axis:
    batch sharded, params/state/history replicated, grads pmean'd.

    pmean (not psum) keeps the effective lr identical to single-device
    training on the same *global* batch, matching Caffe's semantics where
    the loss is already normalized by the full batch size."""

    def __init__(self, solver_param, mesh=None, axis=DATA_AXIS,
                 staleness=None, s_decay=0.5, **kw):
        from .mesh import make_mesh
        self.mesh = mesh if mesh is not None else make_mesh({axis: -1})
        self.axis = axis
        super().__init__(solver_param, **kw)
        # the per-shard nets: same params, feed blobs at batch/n — the graph
        # each device traces (the user-facing self.net keeps global shapes)
        n = self.mesh.shape[axis]
        self.local_net = _rebatch(self.net, n)
        self.local_test_net = _rebatch(self.test_net, n) \
            if self.test_net is not None else None
        if staleness is not None:
            # async bounded staleness at step granularity (the LocalSGD
            # round-granularity twin — see LocalSGDSolver)
            self.arm_staleness(staleness, decay=s_decay)

    # -- compiled steps ----------------------------------------------------
    def _sharded_step(self, batch_example):
        iter_size = int(self.param.iter_size)
        net, updater, lr_fn = self.local_net, self.updater, self.lr_fn
        axis = self.axis
        n_workers = self.mesh.shape[axis]
        # metrics on -> also measure per-worker gradient divergence around
        # the averaging consensus (obs/divergence.py): the between-shard
        # gradient noise, per layer, plus the per-worker loss vector —
        # all replicated scalars, fetched only at step-sample points
        with_stats = self.stepstats is not None
        # elastic membership armed -> every collective is validity-masked
        # (resilience/elastic.py): a worker the host evicted, or whose
        # grads/loss went non-finite this step, is excluded from the
        # consensus with its weight renormalized over the live count —
        # bit-for-bit the old pmean when every worker is valid
        elastic_on = self.elastic is not None
        # async bounded staleness -> the gradient consensus additionally
        # discounts each shard by its version lag (step-granularity
        # versions; lag is a traced input, zero recompiles)
        async_on = self.staleness is not None and elastic_on
        s_bound, s_decay = self.staleness, self.s_decay
        loss_fn = self._wrapped_loss(net)   # device-side input transform
        # (shape-polymorphic vmap, so the global-net transform applies
        # unchanged to each shard's slice)
        # bucketed grad consensus (parallel/overlap.py): reverse-order
        # per-dtype buckets let XLA start allreducing deep layers' grads
        # while shallow layers' backward still runs — bit-for-bit the
        # whole-tree consensus, so it defaults on. The stats variants
        # take the bucketed result as a precomputed consensus and keep
        # their per-layer divergence decomposition on the raw tree.
        from .overlap import bucketed_consensus, overlap_enabled
        overlap_on = overlap_enabled()

        def grad_consensus(consensus_fn, grads, weight):
            if overlap_on:
                return bucketed_consensus(consensus_fn, grads, weight, axis)
            return consensus_fn(grads, weight, axis)

        def one_grad(params, state, batch, rng):
            def lf(p):
                loss, (blobs, new_state) = loss_fn(p, state, batch, rng)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            return loss, grads, new_state

        def step(params, state, history, batch, it, rng, alive, lag):
            # per-device rng stream (dropout must differ across shards)
            w = jax.lax.axis_index(axis)
            my_alive = alive[w]
            rng = jax.random.fold_in(rng, w)
            if iter_size == 1:
                loss, grads, state = one_grad(params, state, batch, rng)
            else:
                def body(carry, micro):
                    acc, state, i = carry
                    loss, g, state = one_grad(
                        params, state, micro, jax.random.fold_in(rng, i))
                    # fp32 accumulation regardless of param dtype (the
                    # mixed-precision contract; bitwise the old
                    # zeros_like path for fp32 params)
                    return (accum_add(acc, g), state, i + 1), loss
                (grads, state, _), losses = jax.lax.scan(
                    body, (accum_init(params), state, 0), batch)
                loss = jnp.mean(losses)
            # validity: the host-declared alive bit AND (with elasticity
            # armed) the on-device finite check — a NaN'd shard can't
            # poison the consensus even before the host evicts it
            if elastic_on:
                finite = jnp.logical_and(tree_finite(grads),
                                         jnp.isfinite(loss))
                valid = my_alive * finite.astype(jnp.float32)
            else:
                valid = my_alive
            if async_on:
                sweight = valid * staleness_discount(lag[w], s_bound,
                                                     s_decay)
                inc = (sweight > 0).astype(jnp.float32)
            else:
                sweight = valid
                inc = valid
            # THE collective: replaces P2PSync's up-tree gradient sum —
            # with stats on, masked_consensus_stats is the same masked
            # average plus each live shard's drift from it (the
            # gradient noise)
            if with_stats:
                if async_on:
                    pre = grad_consensus(weighted_consensus, grads,
                                         sweight) if overlap_on else None
                    grads, aux = weighted_consensus_stats(
                        grads, valid, sweight, axis, consensus=pre)
                else:
                    pre = grad_consensus(masked_consensus, grads,
                                         valid) if overlap_on else None
                    grads, aux = masked_consensus_stats(
                        grads, valid, axis, consensus=pre)
                aux["ref_sq"] = _sq_sum(grads)
                aux["worker_loss"] = gather_worker_scalar(loss, axis)
            elif elastic_on:
                if async_on:
                    grads, _ = grad_consensus(weighted_consensus, grads,
                                              sweight)
                    n_live = jax.lax.psum(inc, axis)
                else:
                    grads, n_live = grad_consensus(masked_consensus, grads,
                                                   valid)
                aux = {"valid": jax.lax.all_gather(valid, axis),
                       "n_live": n_live,
                       "worker_loss": gather_worker_scalar(loss, axis)}
                if async_on:
                    aux["weight"] = jax.lax.all_gather(sweight, axis)
            else:
                grads, _ = grad_consensus(masked_consensus, grads, valid)
                aux = {}
            loss = masked_scalar_mean(loss, inc, axis)
            # BN running stats etc. must stay replicated
            if async_on:
                state, _ = weighted_consensus(state, sweight, axis)
            else:
                state, _ = masked_consensus(state, valid, axis)
            params, history = updater(params, grads, history, lr_fn(it), it)
            return params, state, history, loss, aux

        bspec = _batch_specs(batch_example, axis,
                             batch_dim=0 if iter_size == 1 else 1)
        with context.axis_context(data=axis), \
                context.world_context(axis=axis, size=n_workers,
                                      elastic=elastic_on):
            sharded = shard_map(
                step, mesh=self.mesh,
                in_specs=(P(), P(), P(), bspec, P(), P(), P(), P()),
                out_specs=(P(), P(), P(), P(), P()),
                check_vma=False)
            return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _build_train_step(self):
        # built lazily on first batch (need shapes for specs)
        return None

    def _memory_step_fn(self, batch):
        if self._jit_train is None:
            self._jit_train = self._sharded_step(
                {k: np.asarray(v) for k, v in batch.items()})
        return self._jit_train

    def _memory_step_args(self, batch):
        dev_batch = shard_batch(
            batch, self.mesh, self.axis,
            batch_dim=0 if int(self.param.iter_size) == 1 else 1)
        return (self.params, self.state, self.history, dev_batch,
                jnp.asarray(self.iter, jnp.int32), self.rng,
                self._alive_mask(), self._staleness_lag())

    def _register_comms(self, cm):
        """Per-step DP sync: the grads+state pmean over the data axis —
        the P2PSync replacement, costed with the same ring model as
        bench.py's projection. With bucketed overlap on (the default,
        parallel/overlap.py) the gradient volume is registered per
        bucket in issue order; every bucket but the last-issued one
        (the stem/embedding grads backward finishes last) can hide
        under the backward tail, so the meter marks them overlappable
        and `sparknet report` decomposes overlapped vs exposed bytes."""
        from ..obs.comms import (tree_bytes, ring_allreduce_bytes,
                                 broadcast_collect_bytes)
        from .overlap import bucket_sizes, overlap_enabled, plan_buckets
        super()._register_comms(cm)
        n = self.mesh.shape[self.axis]
        gb = tree_bytes(self.params)
        sb = tree_bytes(self.state)
        cm.set_topology(axes=dict(self.mesh.shape))
        if overlap_enabled():
            sizes = bucket_sizes(plan_buckets(self.params))
            for bi, nb in enumerate(sizes):
                extra = {}
                if bi == len(sizes) - 1:
                    # the paper comparison rides the grad volume (its
                    # per-round weight movement), not the BN state —
                    # which may be empty and hence unregistered
                    extra["paper_broadcast_collect_bytes"] = \
                        broadcast_collect_bytes(gb, n)
                cm.register(
                    "allreduce_grads_bucket", ring_allreduce_bytes(nb, n),
                    axis=self.axis, bucket=bi,
                    overlappable=bi < len(sizes) - 1,
                    note="bucketed pmean(grads), issued as backward "
                         "drains; ring model per chip", **extra)
            cm.register(
                "allreduce_state", ring_allreduce_bytes(sb, n),
                axis=self.axis,
                note="pmean(state) per step, ring model per chip")
        else:
            cm.register(
                "allreduce_grads", ring_allreduce_bytes(gb + sb, n),
                axis=self.axis,
                note="pmean(grads)+pmean(state) per step, ring model "
                     "per chip",
                paper_broadcast_collect_bytes=broadcast_collect_bytes(gb, n))

    def train_step(self, batch):
        batch = {k: np.asarray(v) for k, v in batch.items()}
        iter_size = int(self.param.iter_size)
        self.check_batch(batch, leading=(iter_size,) if iter_size > 1 else ())
        if self._jit_train is None:
            self._jit_train = self._sharded_step(batch)
        self.rng, key = jax.random.split(self.rng)
        import time as _t
        t0 = _t.perf_counter()
        dev_batch = shard_batch(batch, self.mesh, self.axis,
                                batch_dim=0 if int(self.param.iter_size) == 1
                                else 1)
        self.params, self.state, self.history, loss, aux = self._jit_train(
            self.params, self.state, self.history, dev_batch,
            jnp.asarray(self.iter, jnp.int32), key, self._alive_mask(),
            self._staleness_lag())
        self.iter += 1
        host_s = _t.perf_counter() - t0
        self._timing["train_step"] += host_s
        if self.staleness is not None and self.elastic is not None:
            # step-granularity version clocks: the DP twin of the
            # LocalSGD round bookkeeping (park/unpark events flow from
            # the policy itself)
            it = self.iter - 1
            slow = self.chaos.slow_worker_spec(it) \
                if self.chaos is not None else None
            self.elastic.advance_versions(it, host_s, slow=slow)
            self.elastic.observe_staleness(it)
        self._obs_step(host_s, loss, batch,
                       aux=dict(aux, kind="grads") if aux else None)
        if aux and self.elastic is not None and self.stepstats is None:
            # metrics off: _obs_step never fetches the aux, but the
            # membership controller still needs the validity vector
            self._observe_sync_round(dict(aux, kind="grads"))
        return self._chaos_loss(loss)

    def _build_eval_step(self):
        net = self.local_test_net
        axis = self.axis
        tf = self.test_input_transform

        def ev(params, state, batch):
            if tf is not None:
                batch = tf(batch)
            blobs, _ = net.apply(params, state, batch, train=False)
            # test scores are batch means -> pmean across equal shards
            return {b: jax.lax.pmean(jnp.asarray(blobs[b], jnp.float32), axis)
                    for b in net.output_blobs}

        compiled = {}

        def stepper(params, state, batch):
            batch = {k: np.asarray(v) for k, v in batch.items()}
            key = tuple(sorted((k, v.shape) for k, v in batch.items()))
            if key not in compiled:
                bspec = {k: (P(axis) if v.ndim else P())
                         for k, v in batch.items()}
                compiled[key] = jax.jit(shard_map(
                    ev, mesh=self.mesh, in_specs=(P(), P(), bspec),
                    out_specs=P(), check_vma=False))
            dev = shard_batch(batch, self.mesh, self.axis)
            return compiled[key](params, state, dev)

        return stepper


class LocalSGDSolver(Solver):
    """tau-step local SGD with periodic weight averaging — the SparkNet
    outer loop compiled to one XLA program per round.

    round(params, ...) under shard_map:
      each "worker" (mesh slot on the data axis) runs tau sequential solver
      steps on its own tau batches via lax.scan, with its own lr schedule
      positions (global iter advances tau per round, matching the reference
      where each worker's native solver advances its own iter counter);
      then params (and optionally history) are pmean'd.

    average_history=True also averages optimizer state each round; the
    reference does NOT (each Caffe worker keeps its own momentum, only
    weights go through the driver — Net.scala:134-154), so default False.

    unroll: scan unroll factor for the tau inner steps. None (default)
    picks per platform: full unroll on CPU meshes — XLA:CPU pessimizes
    convolutions inside While loops ~10x (measured: 27.7s vs 2.8s for 10
    cifar10_full steps), which would poison the virtual-mesh experiments —
    and 1 on TPU, where the rolled loop compiles fast and runs at full
    speed.

    host_axis: arms the HIERARCHICAL two-tier mode over a 2-D
    (host_axis, axis) mesh (parallel.multihost.host_mesh): the local-SGD
    "worker" becomes a whole host — its devices run per-step gradient
    pmean over ``axis`` (synchronous DP inside the fault domain, over
    ICI), hosts diverge for tau steps, and the round's collect & average
    is the masked consensus over ``host_axis`` (over DCN) with a
    PER-HOST alive mask. Membership — eviction, readmission, quorum —
    operates at host granularity, matching the real production failure
    unit (preemption/OOM kill whole processes, not single chips). With
    one device per host the inner tier is skipped at trace time, so the
    round is bit-for-bit the single-tier SparkNet round it generalizes.

    staleness: arms the ASYNCHRONOUS bounded-staleness mode (`--staleness
    s` next to `--tau`): workers push versioned contributions and the
    round's collect & average becomes a staleness-weighted consensus
    (resilience/elastic.py) — a worker ``lag`` rounds behind the fastest
    live peer is discounted by ``s_decay ** lag``, parked (excluded,
    still a member) once ``lag > s``, and resynced from the replicated
    consensus after the cooldown. The round never blocks on a straggler:
    a chaos ``slow_worker``'s injected seconds land on its own virtual
    clock (its lag grows) instead of the host loop, so round latency
    tracks the median worker, not the max. s=0 is BIT-FOR-BIT the
    synchronous masked round (the same guarantee style as the all-valid
    masked pmean); the lag vector is a traced input, so staleness
    changes cost zero recompiles.
    """

    def __init__(self, solver_param, mesh=None, axis=DATA_AXIS, tau=10,
                 average_history=False, unroll=None, host_axis=None,
                 staleness=None, s_decay=0.5, **kw):
        from .mesh import make_mesh, make_host_device_mesh
        self.host_axis = host_axis
        if mesh is None:
            mesh = make_host_device_mesh(device_axis=axis) \
                if host_axis is not None else make_mesh({axis: -1})
        self.mesh = mesh
        self.axis = axis
        if host_axis is not None and host_axis not in self.mesh.shape:
            raise ValueError(f"host_axis {host_axis!r} not in mesh axes "
                             f"{tuple(self.mesh.shape)}")
        # membership granularity: per-host in hierarchical mode (the
        # alive mask indexes fault domains), per-device-worker otherwise
        self.elastic_axis = host_axis if host_axis is not None else axis
        self.elastic_unit = "host" if host_axis is not None else "worker"
        self.tau = int(tau)
        self.unroll = unroll
        self.average_history = bool(average_history)
        # cross-host transport for the tau-consensus: None = the
        # compiled masked collective; a heartbeat.FileConsensus when
        # arm_heartbeat decided the backend needs the relay
        self._relay = None
        super().__init__(solver_param, **kw)
        self._jit_round = None
        self._round_idx = 0
        if staleness is not None:
            self.arm_staleness(staleness, decay=s_decay)

    def _build_round(self, batch_example):
        net, updater, lr_fn = self.net, self.updater, self.lr_fn
        axis, tau = self.axis, self.tau
        # two-tier wiring: the tau-interval consensus (and the alive
        # mask) runs over sync_axis; intra > 1 arms the per-step
        # gradient pmean over ``axis`` inside each fault domain. Both
        # collapse at trace time in the degenerate configurations, so
        # hosts=1 or one-device-per-host is the single-tier program
        # bit-for-bit (the PR 4 masked-pmean guarantee style).
        host_axis = self.host_axis
        sync_axis = host_axis if host_axis is not None else axis
        n_workers = self.mesh.shape[sync_axis]
        intra = self.mesh.shape[axis] if host_axis is not None else 1
        unroll = self.unroll
        if unroll is None:
            # True = fully unroll regardless of tau (works on every jax
            # vintage; integer 0 is rejected by older lax.scan). unroll=tau
            # would seem equivalent but lowers tau==1 through the While
            # path (jax excludes unroll==1 from its full-unroll shortcut),
            # which XLA:CPU pessimizes ~10x like any conv-in-loop
            unroll = True if all(d.platform == "cpu"
                                 for d in self.mesh.devices.flat) else 1
        average_history = self.average_history
        # metrics on -> measure the paper's tau drift where it happens:
        # each worker's L2 distance from the post-average consensus,
        # computed on-device BEFORE the averaging collective (the average
        # itself comes from masked_consensus_stats, so the extra cost is
        # one elementwise pass + scalar collectives, never a host gather)
        with_stats = self.stepstats is not None
        # elastic membership armed -> the collect & average is quorum-
        # based (resilience/elastic.py): host-evicted or non-finite
        # workers are excluded and the weights renormalize over the live
        # count — bit-for-bit the old pmean when every worker is valid
        elastic_on = self.elastic is not None
        # async bounded staleness armed -> the average is additionally
        # weighted by each worker's version lag (a traced input like the
        # alive mask — zero recompiles); all-lag-zero weights are
        # exactly 1.0, so s=0 stays the synchronous round bit for bit
        async_on = self.staleness is not None and elastic_on
        s_bound, s_decay = self.staleness, self.s_decay
        loss_fn = self._wrapped_loss(net)

        def one_step(params, state, history, batch, it, rng):
            def lf(p):
                loss, (blobs, new_state) = loss_fn(p, state, batch, rng)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            if intra > 1:
                # tier 1, per STEP: devices inside one fault domain are
                # a synchronous DP group (grads pmean'd over ICI), so
                # params/history stay replicated within the host and the
                # host is ONE logical local-SGD worker
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, axis), grads)
            params, history = updater(params, grads, history, lr_fn(it), it)
            return params, new_state, history, loss

        def intra_mean(x):
            """Fold a per-device value to its host's mean — a trace-time
            no-op outside hierarchical mode (bit-for-bit single-tier)."""
            if intra <= 1:
                return x
            return jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, axis), x)

        def round_fn(params, state, history, batches, it0, rng, alive, lag):
            params_in = params          # the round's broadcast weights
            w = jax.lax.axis_index(sync_axis)
            my_alive = alive[w]
            rng = jax.random.fold_in(rng, w)
            if intra > 1:
                # distinct dropout/augmentation streams per device inside
                # the host (their grads average, like any DP group)
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis) + 1)

            def body(carry, inp):
                params, state, history = carry
                batch, i = inp
                params, state, history, loss = one_step(
                    params, state, history, batch, it0 + i,
                    jax.random.fold_in(rng, i))
                return (params, state, history), loss

            (params, state, history), losses = jax.lax.scan(
                body, (params, state, history),
                (batches, jnp.arange(tau, dtype=jnp.int32)),
                unroll=unroll)
            # validity: the host-declared alive bit AND (with elasticity
            # armed) the on-device finite check over this worker's
            # replica — a replica that went NaN mid-round can never
            # poison the consensus, even before the host evicts it. In
            # hierarchical mode the fault domain is valid only when
            # EVERY one of its devices is (pmin over the intra axis).
            if elastic_on:
                finite = jnp.logical_and(tree_finite(params),
                                         jnp.all(jnp.isfinite(losses)))
                finite = finite.astype(jnp.float32)
                if intra > 1:
                    finite = jax.lax.pmin(finite, axis)
                valid = my_alive * finite
            else:
                valid = my_alive
            if async_on:
                # bounded staleness: this worker's push is discounted by
                # its version lag; over the bound the discount is 0 and
                # the same where-mask that excludes dead workers applies
                # — stale and dead degrade identically. valid stays the
                # MEMBERSHIP bit (a parked-but-healthy worker must not
                # accrue "nonfinite" eviction streaks).
                sweight = valid * staleness_discount(lag[w], s_bound,
                                                     s_decay)
                inc = (sweight > 0).astype(jnp.float32)
            else:
                sweight = valid
                inc = valid
            # the per-worker (per-host, hierarchically) round loss: mean
            # over tau steps, folded over the host's devices
            local_loss = intra_mean(jnp.mean(losses))
            # tier 2, per ROUND — collect & average
            # (CifarApp.scala:131-133) == one masked weighted average
            # over sync_axis (== pmean when all workers are valid) —
            # with stats on, masked_consensus_stats IS that average plus
            # each live worker's drift from the result (the paper's tau
            # drift), and ref_sq is the consensus round update's sq norm
            if with_stats:
                if async_on:
                    params, aux = weighted_consensus_stats(
                        params, valid, sweight, sync_axis)
                else:
                    params, aux = masked_consensus_stats(params, valid,
                                                         sync_axis)
                aux["ref_sq"] = tree_sq_dist(params, params_in)[1]
                aux["worker_loss"] = gather_worker_scalar(local_loss,
                                                          sync_axis)
            elif elastic_on:
                if async_on:
                    params, _ = weighted_consensus(params, sweight,
                                                   sync_axis)
                    n_live = jax.lax.psum(inc, sync_axis)
                else:
                    params, n_live = masked_consensus(params, valid,
                                                      sync_axis)
                aux = {"valid": jax.lax.all_gather(valid, sync_axis),
                       "n_live": n_live,
                       "worker_loss": gather_worker_scalar(local_loss,
                                                           sync_axis)}
                if async_on:
                    aux["weight"] = jax.lax.all_gather(sweight, sync_axis)
            else:
                params, _ = masked_consensus(params, valid, sync_axis)
                aux = {}
            # BN running stats differ per device (each saw its own
            # shard): fold within the host first, then the masked
            # cross-host consensus (staleness-weighted in async mode,
            # like the params they ran under)
            if async_on:
                state, _ = weighted_consensus(intra_mean(state), sweight,
                                              sync_axis)
                if average_history:
                    history, _ = weighted_consensus(history, sweight,
                                                    sync_axis)
            else:
                state, _ = masked_consensus(intra_mean(state), valid,
                                            sync_axis)
                if average_history:
                    # history is already replicated within a host
                    # (identical pmean'd grads drive identical updates),
                    # so only the cross-host average is needed
                    history, _ = masked_consensus(history, valid,
                                                  sync_axis)
            # the round loss is the mean over the INCLUDED workers' tau
            # steps — without the collective the P() out_spec would hand
            # back whichever worker's mean sits on the fetching host's
            # first device (observably different across hosts/modes)
            return params, state, history, \
                masked_scalar_mean(local_loss, inc, sync_axis), aux

        shard_axes = (host_axis, axis) if host_axis is not None else axis
        bspec = _batch_specs(batch_example, shard_axes, batch_dim=1)
        world_kw = dict(axis=axis, size=self.mesh.shape[axis],
                        elastic=elastic_on)
        if host_axis is not None:
            world_kw.update(host_axis=host_axis, hosts=n_workers)
        with context.axis_context(data=axis), \
                context.world_context(**world_kw):
            sharded = shard_map(
                round_fn, mesh=self.mesh,
                in_specs=(P(), P(), P(), bspec, P(), P(), P(), P()),
                out_specs=(P(), P(), P(), P(), P()),
                check_vma=False)
            return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _register_comms(self, cm):
        """The SparkNet tradeoff itself: ONE param-sized averaging pmean
        per tau-step round (vs. DP's per-step grad allreduce). In
        hierarchical mode the round average crosses hosts (DCN) while a
        per-step gradient pmean stays inside each host (ICI) — both
        registered so the report shows the two tiers' volumes apart."""
        from ..obs.comms import (tree_bytes, ring_allreduce_bytes,
                                 broadcast_collect_bytes)
        super()._register_comms(cm)
        sync_axis = self.host_axis if self.host_axis is not None \
            else self.axis
        n = self.mesh.shape[sync_axis]
        pb = tree_bytes(self.params) + tree_bytes(self.state)
        if self.average_history:
            pb += tree_bytes(self.history)
        cm.set_topology(axes=dict(self.mesh.shape), tau=self.tau)
        cm.register(
            "param_average", ring_allreduce_bytes(pb, n), axis=sync_axis,
            steps_per_round=self.tau,
            note="one weight-averaging pmean per tau-step round "
                 "(the paper's broadcast+collect)"
                 + (" across hosts" if self.host_axis is not None else ""),
            paper_broadcast_collect_bytes=broadcast_collect_bytes(pb, n))
        if self.host_axis is not None and self.mesh.shape[self.axis] > 1:
            gb = tree_bytes(self.params)
            cm.register(
                "intra_host_grad_pmean",
                ring_allreduce_bytes(gb, self.mesh.shape[self.axis]),
                axis=self.axis, steps_per_round=1,
                note="per-step gradient pmean inside each fault domain "
                     "(tier 1 of hierarchical local SGD)")

    def _round_latencies(self, round_s):
        """Per-worker latencies for the finished round. A single fused
        XLA program has no native per-worker timer, so the base vector is
        the round wall time for every worker; a chaos-injected stall with
        a worker attribution (stall_worker=W) lands its seconds on W
        alone — its peers finished a stall early, exactly the shape a
        per-host timer would report for a real straggler. In
        hierarchical mode the vector is per-HOST (the membership unit),
        and a chaos slow_host's injected seconds land on that host."""
        n = self.mesh.shape[self.elastic_axis]
        if n <= 1 or round_s is None:
            return None
        lat = [float(round_s)] * n
        if self.chaos is not None:
            if self.staleness is not None:
                # async mode: the straggler's injected seconds never
                # blocked the host loop (round_s IS the median pace), so
                # its latency is attributed VIRTUALLY — the per-worker
                # timer a real async runtime would report
                spec = self.chaos.slow_worker_spec(self._round_idx)
                if spec is not None and 0 <= spec[0] < n:
                    lat[spec[0]] = float(round_s) + float(spec[1])
                return lat
            rep = self.chaos.pop_stall()
            rep = self.chaos.pop_slow_worker() or rep
            if self.host_axis is not None:
                rep = self.chaos.pop_slow_host() or rep
            if rep and rep[0] is not None and 0 <= rep[0] < n:
                w, sec = rep
                base = max(0.0, float(round_s) - float(sec))
                lat = [base] * n
                lat[w] = float(round_s)
        return lat

    def shrink_to_survivors(self):
        """Rebuild the mesh over the live workers' devices — the
        recompile path for a PERSISTENT eviction (ElasticPolicy
        shrink_after), so dead slots stop burning compute. Params/state/
        history are pulled to host and re-placed on the shrunk mesh by
        the next round's jit; membership resets to the new world (the
        evicted device left the mesh, so readmission is over). Callers
        must size subsequent round batches off the NEW world:
        (tau, live*per_worker_batch). Returns True when the mesh
        changed."""
        if self.elastic is None:
            raise ValueError("shrink_to_survivors needs arm_elastic()")
        if self.host_axis is None and len(self.mesh.shape) != 1:
            raise ValueError("mesh shrink supports pure data-axis meshes")
        live = self.elastic.live()
        old = self.mesh.shape[self.elastic_axis]
        if len(live) == old:
            return False
        if self.host_axis is not None:
            # hierarchical: drop the dead HOST rows. When only this
            # process's row survives, the result is a purely local mesh
            # and later rounds never touch the cross-host fabric a dead
            # peer would hang (parallel.multihost.survivor_mesh).
            from .multihost import survivor_mesh
            new_mesh = survivor_mesh(self.mesh, live, device_axis=self.axis)
        else:
            from .mesh import make_mesh
            devices = list(self.mesh.devices.reshape(-1)[live])
            new_mesh = make_mesh({self.axis: len(live)}, devices=devices)
        # host round trip: donated buffers live on the OLD mesh; numpy
        # copies re-place cleanly when the shrunk round first runs
        self.params = jax.device_get(self.params)
        self.state = jax.device_get(self.state)
        self.history = jax.device_get(self.history)
        self.mesh = new_mesh
        self._jit_round = None
        self._jit_train = None
        self._jit_eval = None
        self._comms_registered = False      # re-register with the new n
        self.elastic.reset_world(len(live))
        if self.metrics is not None:
            self.metrics.log("membership", kind="mesh_shrunk",
                             from_world=old, to_world=len(live),
                             unit=self.elastic_unit)
        self.log(f"elastic: mesh shrunk {old} -> {len(live)} "
                 f"{self.elastic_unit}s; the next round recompiles at "
                 "the new world size")
        return True

    def _mesh_host_procs(self):
        """mesh host row -> owning process id (None when a row's
        devices span processes, or on 1-D meshes)."""
        if self.host_axis is None:
            return None
        rows = self.mesh.devices
        procs = []
        for h in range(rows.shape[0]):
            owners = {d.process_index for d in rows[h].flat}
            procs.append(owners.pop() if len(owners) == 1 else None)
        return procs

    def _heartbeat_gate(self, timeout=None):
        """The no-hang contract: arrive at this round's rendezvous and
        wait until every live peer host arrived or its lease expired.
        Lease-dead hosts are evicted at host granularity (zero
        recompiles — the alive mask is an input); when a dead PROCESS
        owns devices of the training mesh, the survivors additionally
        shrink the mesh before dispatching, because a collective over a
        dead process's devices would hang forever. QuorumLost
        propagates to run(), which drives the coordinated restart.

        In the async bounded-staleness mode the caller passes
        ``timeout=0``: arrival is still announced (peers read our round
        version from it) and lease-expired peers are still evicted, but
        the round NEVER waits for stragglers — that is the whole
        point; their contributions are staleness-discounted at the
        exchange instead."""
        from ..resilience.elastic import QuorumLost
        hb = self.heartbeat
        if getattr(self, "_grow_pending", False):
            # late joiner (--grow): fast-forward to the running world's
            # front before the first gate — incumbents' gates accept
            # any arrival at round >= theirs, so the no-hang contract
            # holds from the joiner's very first rendezvous
            self._grow_pending = False
            front = hb.peer_round_max()
            if front >= 0:
                self.log(f"grow: fast-forwarding from round "
                         f"{self._round_idx} to the running world's "
                         f"front (round {front + 1})")
                self._round_idx = front + 1
        if self._relay is not None and self.elastic is not None:
            # grow-mid-run: a fresh out-of-world lease is a late-started
            # --grow process asking in. Admission is pure host-side
            # bookkeeping (the alive mask and the view arrays extend),
            # so the compiled round never recompiles.
            for j in hb.poll_joiners():
                if hb.admit_host(j):
                    self.elastic.admit(j, self._round_idx, via="grow")
        if self.elastic is not None and self.elastic.n == hb.n:
            expect = set(self.elastic.live())
        else:
            expect = set(range(hb.n))
        res = hb.gate(self._round_idx, expect=expect, timeout=timeout)
        if self.health is not None:
            alive_now, ages = hb.view()
            self.health.observe_hosts(self._round_idx, alive=alive_now,
                                      lease_age_s=ages,
                                      lease_s=hb.lease_s,
                                      wait_s=res.wait_s)
        quorum_err = None
        for h in res.dead:
            if self.elastic is None or not (0 <= h < self.elastic.n):
                continue
            try:
                self.elastic.evict(h, self._round_idx, "lease_expired")
            except QuorumLost as e:
                quorum_err = e          # survivors still shrink/snapshot
        if res.dead and self.host_axis is not None and \
                jax.process_count() > 1 and self._relay is None:
            from .mesh import is_local_mesh
            if not is_local_mesh(self.mesh):
                procs = self._mesh_host_procs()
                dead_rows = [h for h, p in enumerate(procs)
                             if p in res.dead]
                if dead_rows and quorum_err is None and \
                        self.elastic is not None:
                    self.shrink_to_survivors()
        if quorum_err is not None:
            raise quorum_err

    def _train_round_relay(self, batches):
        """The cross-host tier over the rendezvous directory
        (heartbeat.FileConsensus): run the LOCAL compiled round (tier 1
        — this fault domain's devices, per-step pmean), then post the
        result and adopt the masked cross-host average. Same math as
        the compiled masked consensus, on the transport the paper
        itself used (a driver-mediated collect & broadcast every tau
        steps)."""
        import math as _m
        import time as _t
        t0 = _t.perf_counter()
        if self._jit_round is None:
            self._jit_round = self._build_round(batches)
        self.rng, key = jax.random.split(self.rng)
        shard_axes = (self.host_axis, self.axis) \
            if self.host_axis is not None else self.axis
        dev = shard_batch(batches, self.mesh, shard_axes, batch_dim=1)
        self.params, self.state, self.history, loss, _ = self._jit_round(
            self.params, self.state, self.history, dev,
            jnp.asarray(self.iter, jnp.int32), key, self._alive_mask(),
            self._staleness_lag())
        self.iter += self.tau
        # tier 2: fetch (replicated locally — one local device read),
        # exchange through the directory, adopt the consensus
        leaves_p, tdef_p = jax.tree_util.tree_flatten(
            jax.device_get(self.params))
        leaves_s, tdef_s = jax.tree_util.tree_flatten(
            jax.device_get(self.state))
        payload = [np.asarray(x) for x in leaves_p + leaves_s]
        tdef_h = None
        if self.average_history:
            leaves_h, tdef_h = jax.tree_util.tree_flatten(
                jax.device_get(self.history))
            payload += [np.asarray(x) for x in leaves_h]
        local_loss = float(jax.device_get(loss))
        valid = _m.isfinite(local_loss) and \
            all(np.all(np.isfinite(x)) for x in payload)
        alive = self.elastic.live() if self.elastic is not None \
            else list(range(self.heartbeat.n))
        xt0 = _t.perf_counter()
        consensus, aux = self._relay.exchange(
            self._round_idx, payload, valid, local_loss, alive)
        if self.metrics is not None:
            # the cross-host IO tier, timed on its own: the fleet
            # merger renders this as the consensus/relay track and
            # critpath.py splits it out of the round's wall time
            self.metrics.log(
                "relay_io", round=self._round_idx,
                host=self.heartbeat.host,
                seconds=round(_t.perf_counter() - xt0, 4),
                bytes=int(sum(x.nbytes for x in payload)),
                mono=self.heartbeat.clock.monotonic())
        np_ = len(leaves_p)
        ns = np_ + len(leaves_s)
        self.params = jax.tree_util.tree_unflatten(tdef_p, consensus[:np_])
        self.state = jax.tree_util.tree_unflatten(tdef_s,
                                                  consensus[np_:ns])
        if tdef_h is not None:
            self.history = jax.tree_util.tree_unflatten(tdef_h,
                                                        consensus[ns:])
        wl = np.asarray(aux["worker_loss"], np.float64)
        vv = np.asarray(aux["valid"], np.float64) > 0
        round_loss = float(np.nanmean(wl[vv])) if vv.any() \
            else local_loss
        host_s = _t.perf_counter() - t0
        self._timing["train_round"] += host_s
        self._obs_step(host_s, round_loss, batches)
        out = self._chaos_loss(jnp.float32(round_loss))
        self._observe_sync_round(
            dict(aux, kind="params"),
            round_s=_t.perf_counter() - t0, round_idx=self._round_idx)
        self._round_idx += 1
        return out

    def train_round(self, batches):
        """One outer round. ``batches``: dict of arrays with leading axes
        (tau, global_batch, ...) — tau steps, batch dim sharded across
        workers (over host x device in hierarchical mode; multi-process
        callers feed their own host rows). Returns mean per-worker loss
        over the round."""
        import time as _t
        batches = {k: np.asarray(v) for k, v in batches.items()}
        async_on = self.staleness is not None
        if self.heartbeat is not None:
            # the round gate: never dispatch a cross-host collective
            # until every supposedly-live peer host has arrived (or its
            # lease expired and it was evicted) — a dead peer must cost
            # an eviction, not a hang inside the collective. The async
            # mode gates with timeout=0: arrival is announced and
            # lease-dead peers are evicted, but stragglers are never
            # waited for (their pushes get staleness-discounted instead)
            self._heartbeat_gate(timeout=0.0 if async_on else None)
        if self._relay is not None:
            return self._train_round_relay(batches)
        if self._jit_round is None:
            self._jit_round = self._build_round(batches)
        self.rng, key = jax.random.split(self.rng)
        t0 = _t.perf_counter()
        shard_axes = (self.host_axis, self.axis) \
            if self.host_axis is not None else self.axis
        dev = shard_batch(batches, self.mesh, shard_axes, batch_dim=1)
        self.params, self.state, self.history, loss, aux = self._jit_round(
            self.params, self.state, self.history, dev,
            jnp.asarray(self.iter, jnp.int32), key, self._alive_mask(),
            self._staleness_lag())
        self.iter += self.tau
        host_s = _t.perf_counter() - t0
        self._timing["train_round"] += host_s
        self._obs_step(host_s, loss, batches)
        loss = self._chaos_loss(loss)   # may stall (the injected straggler)
        if self.chaos is not None and not async_on:
            # a chaos slow_worker under the SYNCHRONOUS barrier is a
            # real per-round host stall: the collect & average waits for
            # the straggler, so round latency tracks the max worker —
            # exactly the failure mode the async mode absorbs
            self.chaos.maybe_slow_worker(self._round_idx)
        aux = dict(aux, kind="params") if aux else None
        if async_on and self.elastic is not None:
            aux = self._observe_staleness_round(
                aux, _t.perf_counter() - t0)
        if aux:
            # once per sync round (rounds are coarse; the fetch is a few
            # scalars): divergence event + straggler/skew/trend detectors
            self._observe_sync_round(
                aux, round_s=_t.perf_counter() - t0,
                round_idx=self._round_idx)
        self._round_idx += 1
        return loss

    def _observe_staleness_round(self, aux, round_s):
        """Async-mode per-round bookkeeping: advance the per-worker
        version clocks (a chaos slow_worker pays its seconds on ITS
        clock, never the host loop's), run the park/unpark controller,
        attach the lag/park state to the round aux (drift attribution +
        the health detectors), and emit the ``staleness`` metrics event
        the report/monitor staleness sections render. QuorumLost (a
        chronically-parked worker evicted below quorum) propagates."""
        el = self.elastic
        slow = self.chaos.slow_worker_spec(self._round_idx) \
            if self.chaos is not None else None
        lag_used = el.lag()             # the lag the round's weights saw
        el.advance_versions(self._round_idx, round_s, slow=slow)
        el.observe_staleness(self._round_idx)
        aux = dict(aux) if aux else {"kind": "params"}
        aux["lag"] = [int(x) for x in lag_used]
        aux["parked"] = [int(w) for w in np.nonzero(el.parked)[0]]
        if self.metrics is not None:
            self.metrics.log(
                "staleness", round=self._round_idx, s=el.staleness,
                version=[int(v) for v in el.version],
                lag=[int(x) for x in el.lag()],
                parked=aux["parked"],
                park_rounds=[int(r) for r in el.park_rounds],
                weight=[round(float(x), 4)
                        for x in el.consensus_weights()])
        return aux

    def run(self, num_rounds, batch_fn, test_data_fn=None, test_every=10,
            snapshot_prefix=None, snapshot_every=0, resume=None,
            reshard="strict",
            sigint="stop", sighup="snapshot", sigterm="snapshot_stop"):
        """The reference driver loop (CifarApp.scala:92-135): for each round,
        optionally test (every ``test_every`` rounds, :98), then train tau
        steps per worker. ``batch_fn(tau)`` -> batches dict as above.

        Fault tolerance (the opposite of the reference's
        spark.task.maxFailures=1 contract):
          * resume="auto" restores the newest valid snapshot under the
            prefix before the first round (a path restores that
            snapshot); reshard="auto" additionally accepts a snapshot
            stamped by a DIFFERENT world and re-partitions it for this
            one (resilience/checkpoint.reshard_for_world) instead of
            refusing with WorldMismatch
          * signals are polled BETWEEN rounds: SIGHUP snapshots, SIGINT
            stops cleanly, SIGTERM (a preemption notice) snapshots then
            stops — pair with `--resume auto` on relaunch
          * snapshot_every=N also snapshots every N completed rounds
          * an armed RecoveryPolicy (arm_recovery) rolls a NaN/exploding
            round back and redoes it instead of averaging poison
          * an armed ElasticPolicy (arm_elastic) makes every round
            quorum-based: sick workers are evicted from the consensus
            and readmitted after a cooldown; QuorumLost (exit 4) aborts
            the loop after a best-effort snapshot. With shrink_after
            set, persistent evictions shrink the mesh over the
            survivors — batch_fn must then size batches off
            solver.mesh.shape (the live world).
        """
        from ..utils.signals import SignalPolicy
        from ..resilience import checkpoint
        from ..resilience.elastic import QuorumLost
        prefix = snapshot_prefix or (self.param.snapshot_prefix
                                     if self.param.has("snapshot_prefix")
                                     else None)
        if resume == "auto":
            if prefix:
                checkpoint.resume_auto(self, prefix, log_fn=self.log,
                                       reshard=reshard)
            else:
                self.log("resume auto: no snapshot prefix; starting fresh")
        elif resume:
            self.restore(resume, reshard=reshard)
        r = 0
        with SignalPolicy(sigint=sigint, sighup=sighup,
                          sigterm=sigterm) as policy:
            while r < num_rounds:
                if test_data_fn is not None and r % test_every == 0 \
                        and self.test_net is not None:
                    scores = self.test(test_data_fn())
                    for k, v in scores.items():
                        self.log(f"round {r}: test {k} = {v}")
                try:
                    loss = self.train_round(batch_fn(self.tau))
                except QuorumLost:
                    # the consensus up to here is good — keep it. The
                    # designated writer commits it; every survivor then
                    # barriers on the manifest's sha256 (coordinated
                    # restart), so all of them exit 4 holding the SAME
                    # resumable snapshot for the supervisor relaunch.
                    if prefix:
                        self.snapshot(prefix=prefix)
                        self.coordinated_restart(prefix)
                    raise
                if self.elastic is not None and self.elastic.should_shrink():
                    self.shrink_to_survivors()
                v = float(loss)
                if self.watchdog is not None:
                    self.watchdog.beat(v)
                if self.recovery is not None and \
                        self.recovery.observe(self, v):
                    self.log(f"round {r}: rolled back to iter {self.iter}; "
                             "redoing the round")
                    continue
                self.log(f"round {r}: mean local loss = {v:.6g}")
                r += 1
                if self.chaos is not None:
                    self.chaos.maybe_sigterm(r)
                action = policy.pending()
                if prefix and (action in ("snapshot", "snapshot_stop") or
                               (snapshot_every and
                                r % snapshot_every == 0)):
                    self.snapshot(prefix=prefix)
                if action in ("stop", "snapshot_stop"):
                    self.log(f"stopping on signal after round {r}")
                    break
