"""Solver suite: Caffe-parity optimizers + lr policies + orchestration
(replaces the reference caffe::Solver hierarchy, solver.cpp + solvers/*)."""

from .solver import Solver, resolve_nets
from .updates import Updater, canonical_type, SOLVER_TYPES
from .lr_policy import make_lr_fn

__all__ = ["Solver", "resolve_nets", "Updater", "canonical_type",
           "SOLVER_TYPES", "make_lr_fn"]
