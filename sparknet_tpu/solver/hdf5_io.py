"""HDF5 checkpoint format — bit-compatible with the reference's layout.

Net weights (net.cpp ToHDF5 :926-974):      /data/<layer_name>/<param_idx>
Solver state (sgd_solver.cpp :278-297):     /iter, /learned_net,
                                            /current_step, /history/<i>
History datasets follow caffe's history_ vector order: slot-major — all
learnable params' slot 0 (in net order), then all slot 1 (AdaDelta/Adam
push their second round of blobs after the first, sgd_solver.cpp PreSolve /
adadelta_solver.cpp), only layers that own their params.
"""

import numpy as np


def _h5py():
    import h5py
    return h5py


def owned_param_keys(net):
    """[(layer_name, idx)] in net order — learnable-param order."""
    keys = []
    for lp, impl, bottoms, tops in net.layers:
        for key in net.param_refs.get(lp.name, ()):
            if key[0] == lp.name:
                keys.append(key)
    return keys


def history_order(net, history):
    """Yield (layer_name, param_idx, slot_idx) in caffe history_ order:
    slot-major over learnable params."""
    keys = owned_param_keys(net)
    n_slots = max((len(history[l][i]) for l, i in keys), default=0)
    for s in range(n_slots):
        for (l, i) in keys:
            if s < len(history[l][i]):
                yield l, i, s


def save_net_hdf5(path, net, params):
    h5 = _h5py()
    with h5.File(path, "w") as f:
        data = f.create_group("data")
        for lp, impl, bottoms, tops in net.layers:
            owned = [k for k in net.param_refs.get(lp.name, ())
                     if k[0] == lp.name]
            g = data.create_group(lp.name)
            for (lname, i) in owned:
                g.create_dataset(str(i),
                                 data=np.asarray(params[lname][i],
                                                 np.float32))


def load_net_hdf5(path, net, params):
    """Copy matching datasets into params (CopyTrainedLayersFromHDF5:
    layers matched by name, missing layers ignored)."""
    h5 = _h5py()
    import jax.numpy as jnp
    out = {k: list(v) for k, v in params.items()}
    with h5.File(path, "r") as f:
        data = f["data"]
        for lname in data:
            if lname not in out:
                continue
            g = data[lname]
            for i_str in g:
                i = int(i_str)
                if i < len(out[lname]):
                    arr = np.asarray(g[i_str])
                    out[lname][i] = jnp.asarray(
                        arr.reshape(out[lname][i].shape),
                        out[lname][i].dtype)
    return out


def save_state_hdf5(path, iter_, learned_net, net, history,
                    current_step=0):
    h5 = _h5py()
    with h5.File(path, "w") as f:
        # caffe's hdf5_save_int writes native int (32-bit) and the
        # learned_net string as fixed-length C chars (util/hdf5.cpp
        # hdf5_save_string) — match exactly so old H5LT readers accept it
        f.create_dataset("iter", data=np.int32(iter_))
        f.create_dataset("learned_net",
                         data=np.bytes_(learned_net.encode()
                                        if isinstance(learned_net, str)
                                        else learned_net))
        f.create_dataset("current_step", data=np.int32(current_step))
        g = f.create_group("history")
        for n, (lname, i, s) in enumerate(history_order(net, history)):
            g.create_dataset(str(n),
                             data=np.asarray(history[lname][i][s],
                                             np.float32))


def load_state_hdf5(path, net, history):
    """-> (iter, learned_net, new_history)."""
    h5 = _h5py()
    import jax.numpy as jnp
    new_history = {k: [list(slot) for slot in v] for k, v in history.items()}
    order = list(history_order(net, history))
    with h5.File(path, "r") as f:
        it = int(np.asarray(f["iter"]))
        learned = f["learned_net"][()]
        if isinstance(learned, bytes):
            learned = learned.decode()
        g = f["history"]
        if len(g) != len(order):
            # caffe CHECK_EQ(state_history_size, history_.size()): e.g. a
            # 1-slot SGD state restored into a 2-slot Adam solver
            raise ValueError(
                f"{path}: solver state has {len(g)} history blobs, this "
                f"solver ({len(order)} expected) is a different type — "
                f"restore with the solver type that wrote the snapshot")
        for n, (lname, i, s) in enumerate(order):
            ref = new_history[lname][i][s]
            arr = np.asarray(g[str(n)])
            new_history[lname][i][s] = jnp.asarray(
                arr.reshape(ref.shape), ref.dtype)
    return it, learned, new_history
