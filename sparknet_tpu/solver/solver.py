"""Solver: training orchestration around ONE jitted train step.

The TPU-native replacement for the reference Solver::Step loop
(solver.cpp:193-253): ClearParamDiffs / iter_size x ForwardBackward / loss
smoothing / ApplyUpdate all collapse into a single compiled XLA program per
step — grads via jax.grad, iter_size accumulation via lax.scan, the lr
schedule traced on the iteration index (no recompiles). Evaluation mirrors
the SparkNet-added Solver::TestAndStoreResult (solver.cpp:414-444): run the
TEST-phase net test_iter times and average its output blobs.

Buffer donation keeps params/history resident in HBM across steps — the
analog of Caffe never leaving the GPU between iterations, minus the JVM/JNA
weight copies (Net.scala:126-148) that the reference paid per sync round.
"""

import collections
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..proto import Message, text_format, wire
from ..graph.compiler import CompiledNet, TRAIN, TEST, array_to_blob, \
    blob_to_array
from .lr_policy import make_lr_fn
from .updates import Updater, canonical_type, accum_init, accum_add


def resolve_nets(sp, base_dir="", net_param=None):
    """Resolve train/test NetParameters from a SolverParameter, honoring the
    field precedence of reference solver.cpp InitTrainNet/InitTestNets:
    train_net_param > train_net > net_param > net."""
    def load(path):
        return text_format.load(os.path.join(base_dir, path), "NetParameter")

    train = test = None
    if net_param is not None:
        train = test = net_param
    elif sp.has("train_net_param"):
        train = sp.train_net_param
    elif sp.has("train_net"):
        train = load(sp.train_net)
    elif sp.has("net_param"):
        train = test = sp.net_param
    elif sp.has("net"):
        train = test = load(sp.net)
    if train is None:
        raise ValueError("solver specifies no train net")
    if sp.test_net_param:
        test = sp.test_net_param[0]
    elif sp.test_net:
        test = load(sp.test_net[0])
    return train, test


def sp_test_scheduled(sp):
    """Does the solver schedule testing (test_iter/test_interval set)?"""
    return bool(sp.test_iter) or int(sp.test_interval) > 0


class Solver:
    """Drives training of one net per the SolverParameter schedule.

    data iterators yield batch dicts {blob_name: array}; see
    CompiledNet.feed_blobs() for required keys.
    """

    def __init__(self, solver_param, net_param=None, feed_shapes=None,
                 test_feed_shapes=None, base_dir="", dtype=jnp.float32,
                 log_fn=print, metrics=None, compute_dtype=None,
                 tracer=None):
        self.param = solver_param
        self.log = log_fn or (lambda *a: None)
        # structured observability hooks, armed by default from the CLI:
        # a JSONL MetricsLogger (or path), a span Tracer over it, step
        # accounting + comms metering (sparknet_tpu.obs), and an optional
        # Watchdog that step() beats once per iteration
        self._own_metrics = isinstance(metrics, str)
        if isinstance(metrics, str):
            from ..utils.metrics import MetricsLogger
            metrics = MetricsLogger(metrics)
        self.metrics = metrics
        from ..obs import Tracer
        self.tracer = tracer if tracer is not None else Tracer(self.metrics)
        self.stepstats = self.comms = None
        self._comms_registered = False
        # training-dynamics health layer (obs divergence/health/memstats):
        # armed by default with metrics; sharded solvers compute the
        # divergence aux inside their compiled sync round and this base
        # class fetches/emits it on the step-sample cadence
        self.divergence = self.health = self.memstats = None
        self.last_divergence = None
        if self.metrics is not None:
            from ..obs import (StepAccounting, CommsMeter, DivergenceMeter,
                               HealthMonitor, MemoryMonitor)
            self.stepstats = StepAccounting(self.metrics)
            self.comms = CommsMeter(self.metrics)
            self.divergence = DivergenceMeter(self.metrics)
            self.health = HealthMonitor(self.metrics, log_fn=self.log,
                                        solver=self)
            self.memstats = MemoryMonitor(self.metrics)
        self.watchdog = None
        # resilience hooks (sparknet_tpu.resilience): keep-N snapshot
        # retention (None = keep all), an optional RecoveryPolicy armed via
        # arm_recovery(), an optional ElasticPolicy armed via arm_elastic()
        # (quorum-based sync rounds on sharded solvers), and the
        # process-wide chaos injector (None unless --chaos /
        # SPARKNET_CHAOS armed one)
        self.snapshot_keep = None
        self.recovery = None
        self.elastic = None
        # bounded-staleness async mode (resilience/elastic.py, ISSUE 7):
        # None = synchronous rounds; an int s >= 0 (arm_staleness) makes
        # the sharded consensus a staleness-weighted average — workers
        # push versioned contributions, stale ones are discounted, over-
        # stale ones are parked, and the round never waits on a straggler
        self.staleness = None
        self.s_decay = 0.5
        # host-level fault domains (resilience/heartbeat.py), armed via
        # arm_heartbeat(): leased liveness for every peer process, the
        # pre-round rendezvous gate, and the coordinated-restart barrier
        self.heartbeat = None
        from ..resilience.chaos import active_chaos
        self.chaos = active_chaos()
        train_np, test_np = resolve_nets(solver_param, base_dir, net_param)
        # NetState from the solver (reference solver.cpp InitTrainNet /
        # InitTestNets: train_state / test_state merge into the filter
        # state — e.g. mnist_autoencoder_solver's per-test-net
        # 'test-on-train'/'test-on-test' stages select among same-named
        # Data layers). Like the single test_net, only test_state[0] is
        # instantiated here.
        ts = solver_param.train_state \
            if solver_param.has("train_state") else None
        self.net = CompiledNet(train_np, TRAIN, feed_shapes=feed_shapes,
                               dtype=dtype, compute_dtype=compute_dtype,
                               level=int(ts.level) if ts else 0,
                               stages=tuple(ts.stage) if ts else ())
        self.test_net = None
        if test_np is not None:
            es = solver_param.test_state[0] \
                if solver_param.test_state else None
            try:
                self.test_net = CompiledNet(
                    test_np, TEST,
                    feed_shapes=test_feed_shapes or feed_shapes, dtype=dtype,
                    compute_dtype=compute_dtype,
                    level=int(es.level) if es else 0,
                    stages=tuple(es.stage) if es else ())
            except ValueError:
                # a shared `net` whose data layer is TRAIN-only has no
                # TEST-phase graph; without a test_iter schedule the
                # reference never instantiates test nets at all
                # (solver.cpp InitTestNets), so train-only it is
                if sp_test_scheduled(solver_param):
                    raise
                self.log("No TEST-phase net; training without a test net")

        seed = int(solver_param.random_seed)
        self.rng = jax.random.PRNGKey(seed if seed >= 0 else
                                      int(time.time_ns() % (2 ** 31)))
        self.rng, init_key = jax.random.split(self.rng)
        self.params, self.state = self.net.init(init_key)

        mults = {}
        for lname, refs in self.net.param_refs.items():
            owned = [k for k in refs if k[0] == lname]
            if owned:
                mults[lname] = [
                    (self.net.param_meta[k][2], self.net.param_meta[k][3])
                    for k in owned]
        self.updater = Updater(solver_param, mults)
        self.history = self.updater.init(self.params)
        self.lr_fn = make_lr_fn(solver_param)
        self.iter = 0
        self._smoothed = collections.deque(
            maxlen=max(1, int(solver_param.average_loss)))
        self._jit_train = None
        self._jit_eval = None
        self._timing = collections.defaultdict(float)
        # optional on-device input transforms (data/device_transform.py):
        # pure fns applied to the feed dict INSIDE the jitted step, letting
        # the host ship raw uint8 records + tiny offset arrays instead of
        # float32 crops (3-4x fewer H2D bytes)
        self.input_transform = None
        self.test_input_transform = None
        self._raw_feed_shapes = None
        # async-dispatch discipline: fetching ANY value from the device is
        # a full host round trip (~100 ms on a remote-tunnel TPU), so the
        # step loop only materializes a loss at display points, or every
        # _sync_stride steps when display is off. Dispatches queue ahead in
        # between — that queue IS the transfer/compute overlap. The NaN
        # watchdog consequently sees losses with up to that much lag.
        self._sync_stride = max(1, int(os.environ.get(
            "SPARKNET_SYNC_STRIDE", "100")))
        # iteration counter kept ON DEVICE: feeding a fresh host scalar
        # every step is a blocking H2D put; a resident counter is free
        self._it_dev = None

    def smoothed_loss(self):
        """Mean of the average_loss-window losses (one device fetch), or
        None before any step — the value the display line prints."""
        if not self._smoothed:
            return None
        return float(jnp.mean(jnp.stack(
            [jnp.asarray(x) for x in self._smoothed])))

    def set_input_transform(self, fn, raw_overrides=None, test_fn=None):
        """Install on-device input transforms (before any step compiles).
        fn/test_fn: pure fn(batch dict) -> net feed dict; raw_overrides:
        {blob: raw shape} check_batch overrides for the pre-transform feed
        (e.g. the uint8 source extent + '#y'/'#x'/'#flip' aux arrays)."""
        self.input_transform = fn
        self.test_input_transform = test_fn
        self._raw_feed_shapes = dict(raw_overrides) if raw_overrides else None

    def _set_net_knob(self, attr, value):
        """Set a trace-time perf knob on every CompiledNet this solver
        owns and DROP the compiled steps. The policy is read once per
        trace (graph/compiler.py), so flipping it under a live jit would
        silently keep serving the old trace; rebuilding gives the new
        policy a FRESH executable whose cache starts empty — a
        mid-process toggle costs exactly one recompile and cannot leak
        stale cache entries (tests/test_remat.py asserts both)."""
        for name in ("net", "test_net", "local_net", "local_test_net"):
            n = getattr(self, name, None)
            if n is not None:
                setattr(n, attr, value)
        self._jit_train = None
        self._jit_eval = None
        if hasattr(self, "_jit_round"):
            self._jit_round = None

    def set_remat(self, policy):
        """Set the remat policy (the --remat CLI knob): "none", "dots"
        (save matmul outputs, recompute elementwise tails), or "full".
        Overrides the SPARKNET_REMAT env-var fallback."""
        from ..graph.compiler import REMAT_POLICIES
        if policy not in REMAT_POLICIES:
            raise ValueError(
                f"remat policy {policy!r}: want one of {REMAT_POLICIES}")
        self._set_net_knob("remat", policy)

    def set_scan(self, mode):
        """Set the scan-over-layers mode: "auto" (TPU only), "on", or
        "off". Overrides the SPARKNET_SCAN env-var fallback."""
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"scan mode {mode!r}: want auto|on|off")
        self._set_net_knob("scan", mode)

    def _wrapped_loss(self, net):
        """net.loss_fn with the device-side input transform folded in."""
        tf = self.input_transform
        if tf is None:
            return net.loss_fn

        def lf(params, state, batch, rng):
            return net.loss_fn(params, state, tf(batch), rng)
        return lf

    # -- compiled steps ----------------------------------------------------
    def _build_train_step(self):
        return jax.jit(self._train_step_fn(), donate_argnums=(0, 1, 2))

    def _memory_step_fn(self, batch):
        """The lowerable jit behind train_step (None when this solver
        wraps its jit in a closure and no step has traced yet)."""
        if self._jit_train is None:
            self._jit_train = self._build_train_step()
        return self._jit_train

    def _memory_step_args(self, batch):
        return (self.params, self.state, self.history, batch,
                jnp.asarray(self.iter, jnp.int32), self.rng)

    def compiled_memory_stats(self, batch):
        """Per-device memory footprint of the COMPILED train step from
        XLA's memory_analysis: argument/output/temp/aliased bytes plus
        the peak-HBM proxy arg + out + temp - aliased (params, state
        and history are donated, so their output copies alias the
        inputs). This is the number that says whether a model FITS —
        bench rows and the FSDP does-not-fit proof both read it. On
        backends whose executable does not expose a memory analysis,
        returns None. Lowering does not execute anything; the
        persistent compile cache absorbs the second compile."""
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        fn = self._memory_step_fn(batch)
        if fn is None or not hasattr(fn, "lower"):
            return None
        try:
            ma = fn.lower(*self._memory_step_args(batch)) \
                   .compile().memory_analysis()
        except NotImplementedError:
            return None
        if ma is None:
            return None
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        ali = int(ma.alias_size_in_bytes)
        return {"argument_bytes": arg, "output_bytes": out,
                "temp_bytes": tmp, "alias_bytes": ali,
                "peak_bytes": arg + out + tmp - ali}

    def _train_step_fn(self):
        """The pure (uncompiled) train step — subclasses re-jit it with
        sharding annotations (parallel.gspmd) or wrap it in shard_map."""
        iter_size = int(self.param.iter_size)
        net, updater, lr_fn = self.net, self.updater, self.lr_fn
        loss_fn = self._wrapped_loss(net)

        def one_grad(params, state, batch, rng):
            def lf(p):
                loss, (blobs, new_state) = loss_fn(p, state, batch, rng)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            return loss, grads, new_state

        def step(params, state, history, batch, it, rng):
            if iter_size == 1:
                loss, grads, state = one_grad(params, state, batch, rng)
            else:
                # batch leading axis = iter_size micro-batches; accumulate
                # grads like reference solver.cpp:221-223 summing diffs —
                # in fp32 regardless of param dtype (updates.accum_init,
                # the mixed-precision contract; bitwise the old zeros_like
                # path for fp32 params).
                def body(carry, micro):
                    acc, state, i = carry
                    loss, g, state = one_grad(
                        params, state, micro, jax.random.fold_in(rng, i))
                    return (accum_add(acc, g), state, i + 1), loss
                (grads, state, _), losses = jax.lax.scan(
                    body, (accum_init(params), state, 0), batch)
                loss = jnp.mean(losses)
            rate = lr_fn(it)
            params, history = updater(params, grads, history, rate, it)
            return params, state, history, loss, it + 1

        return step

    def _build_debug_fn(self):
        """SolverParameter.debug_info — per-blob/param mean-|x| dump in
        the reference format (net.cpp ForwardDebugInfo :658 + param
        grads from BackwardDebugInfo). Deviations, documented: the
        reference prints EVERY step mid-pass; here the dump runs at
        display points only (each dump is a device fetch — per-step
        dumps would serialize the async dispatch pipeline this solver is
        built on), BEFORE the displayed iteration's update is applied,
        so data/diff norms describe the same params that produced the
        displayed loss. Dropout-style rng layers draw a different key
        than the training step did, so their norms are same-distribution
        rather than bit-identical. One fused jit computes every norm in
        a single device program."""
        net = self.net
        tf = self.input_transform

        # static label lists, in net layer order (jit outputs are lists
        # of scalars in the same order). Labels carry the layer's SLOT
        # index (the reference prints every slot, shared or owned);
        # positional index into params[ln] rides along separately.
        fwd_keys = [(lp.name, t) for lp, _, _, tops in net.layers
                    for t in tops]
        prm_keys = []            # (label_lname, slot, owner, owner_pos)
        for lp, _, _, _ in net.layers:
            for slot, key in enumerate(net.param_refs[lp.name]):
                owner = key[0]
                owner_owned = [k for k in net.param_refs.get(owner, [])
                               if k[0] == owner]
                if key in owner_owned:
                    prm_keys.append((lp.name, slot, owner,
                                     owner_owned.index(key)))

        def dbg(params, state, batch, rng):
            b = tf(batch) if tf is not None else batch

            def lf(p):
                loss, (blobs, _) = net.loss_fn(p, state, b, rng)
                return loss, blobs
            (loss, blobs), grads = jax.value_and_grad(
                lf, has_aux=True)(params)

            def mabs(x):
                return jnp.mean(jnp.abs(jnp.asarray(x, jnp.float32)))
            fwd = [mabs(blobs[t]) if t in blobs else jnp.float32(0)
                   for _, t in fwd_keys]
            prm = [mabs(params[ow][pos]) for _, _, ow, pos in prm_keys]
            gds = [mabs(grads[ow][pos]) for _, _, ow, pos in prm_keys]
            return fwd, prm, gds

        return jax.jit(dbg), fwd_keys, prm_keys

    def _print_debug_info(self, batch):
        if jax.process_count() > 1:
            if not getattr(self, "_dbg_warned", False):
                self._dbg_warned = True
                self.log("debug_info dump is single-process only; "
                         "skipping (per-host batch slices cannot feed "
                         "the global-shape debug program)")
            return
        if getattr(self, "_jit_debug", None) is None:
            self._jit_debug = self._build_debug_fn()
        dbg, fwd_keys, prm_keys = self._jit_debug
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        # ONE bulk fetch: per-line float() would pay a host round trip
        # per printed norm (~100 ms each on remote-tunnel rigs)
        fwd, prm, grads = jax.device_get(
            dbg(self.params, self.state, batch, self.rng))
        for (lname, t), v in zip(fwd_keys, fwd):
            self.log(f"    [Forward] Layer {lname}, top blob {t} "
                     f"data: {float(v):.6g}")
        for (lname, slot, _, _), v in zip(prm_keys, prm):
            self.log(f"    [Forward] Layer {lname}, param blob {slot} "
                     f"data: {float(v):.6g}")
        for (lname, slot, _, _), v in zip(prm_keys, grads):
            self.log(f"    [Backward] Layer {lname}, param blob {slot} "
                     f"diff: {float(v):.6g}")

    def _build_eval_step(self):
        net = self.test_net
        tf = self.test_input_transform

        def ev(params, state, batch):
            if tf is not None:
                batch = tf(batch)
            blobs, _ = net.apply(params, state, batch, train=False)
            return {b: blobs[b] for b in net.output_blobs}

        return jax.jit(ev)

    def arm_watchdog(self, stall_seconds=300.0, **kw):
        """Start a stall/NaN watchdog that step() beats each iteration.
        With kill_on_stall and a configured snapshot_prefix, the exit path
        gets a best-effort emergency snapshot by default."""
        from ..utils.watchdog import Watchdog
        kw.setdefault("metrics", self.metrics)
        if kw.get("kill_on_stall") and "emergency_snapshot" not in kw \
                and self.param.has("snapshot_prefix"):
            kw["emergency_snapshot"] = self.snapshot
        self.watchdog = Watchdog(stall_seconds=stall_seconds, **kw).start()
        return self.watchdog

    # -- resilience (sparknet_tpu.resilience) ------------------------------
    def arm_recovery(self, policy=None, **kw):
        """Install a divergence RecoveryPolicy (NaN/explosion -> rollback
        to last-known-good). The state at arm time becomes the first
        known-good point, so even a first-step NaN has somewhere to go."""
        if policy is None:
            from ..resilience.recovery import RecoveryPolicy
            kw.setdefault("metrics", self.metrics)
            kw.setdefault("log_fn", self.log)
            policy = RecoveryPolicy(**kw)
        self.recovery = policy
        policy.note_good(self)
        return policy

    def arm_elastic(self, policy=None, **kw):
        """Install an elastic membership controller
        (resilience/elastic.py): the sync collectives become validity-
        masked quorum averages, sick workers are evicted/readmitted,
        and dropping below ``quorum`` raises QuorumLost (exit 4). Only
        sharded solvers (a data-axis mesh) act on it; arming rebuilds
        the compiled step/round so the membership aux is traced in.

        Hierarchical solvers (a host axis — parallel.multihost) declare
        elastic_axis/elastic_unit, so membership runs at HOST
        granularity; with the heartbeat relay armed the world spans the
        jax processes rather than the local mesh."""
        mesh = getattr(self, "mesh", None)
        axis = getattr(self, "elastic_axis", None) or \
            getattr(self, "axis", None)
        n = mesh.shape[axis] if mesh is not None and axis in mesh.shape \
            else 1
        if getattr(self, "_relay", None) is not None:
            n = self.heartbeat.n
        if policy is None:
            from ..resilience.elastic import ElasticPolicy
            kw.setdefault("metrics", self.metrics)
            kw.setdefault("log_fn", self.log)
            kw.setdefault("chaos", self.chaos)
            kw.setdefault("unit", getattr(self, "elastic_unit", "worker"))
            kw.setdefault("staleness", self.staleness)
            kw.setdefault("s_decay", self.s_decay)
            policy = ElasticPolicy(n_workers=n, **kw)
        self.elastic = policy
        self._jit_train = None
        if hasattr(self, "_jit_round"):
            self._jit_round = None
        return policy

    def arm_staleness(self, s, decay=0.5, unpark_after=1,
                      evict_parked_after=0):
        """Arm the asynchronous bounded-staleness update mode (`--
        staleness` next to `--tau`): the sharded consensus becomes a
        staleness-weighted average (resilience/elastic.py
        weighted_consensus) over versioned worker contributions — a
        worker ``lag`` rounds behind the fastest live peer is discounted
        by ``decay ** lag``, parked (weight 0, still a member) once
        ``lag > s``, resynced from the replicated consensus after
        ``unpark_after`` rounds, and evicted after
        ``evict_parked_after`` chronic parks (0 = never). s=0 is
        BIT-FOR-BIT the synchronous masked round. Arms elastic
        membership implicitly (quorum 1) when none is armed yet; the
        async file relay (heartbeat.AsyncFileConsensus) is upgraded in
        place when a synchronous relay was already armed."""
        self.staleness = max(0, int(s))
        self.s_decay = float(decay)
        if self.elastic is None:
            self.arm_elastic(quorum=1, unpark_after=unpark_after,
                             evict_parked_after=evict_parked_after)
        else:
            el = self.elastic
            el.staleness = self.staleness
            el.s_decay = self.s_decay
            el.unpark_after = max(1, int(unpark_after))
            el.evict_parked_after = max(0, int(evict_parked_after))
        if getattr(self, "_relay", None) is not None:
            from ..resilience.heartbeat import (AsyncFileConsensus,
                                                FileConsensus)
            if type(self._relay) is FileConsensus:
                self._relay = AsyncFileConsensus(
                    self._relay.coord, s=self.staleness,
                    decay=self.s_decay)
                self.log("staleness: upgraded the cross-host relay to "
                         "the versioned barrier-free delta exchange")
            elif isinstance(self._relay, AsyncFileConsensus):
                self._relay.s = self.staleness
                self._relay.decay = self.s_decay
        self._jit_train = None
        if hasattr(self, "_jit_round"):
            self._jit_round = None
        self.log(f"staleness: async bounded-staleness armed (s="
                 f"{self.staleness}, decay={self.s_decay})")
        return self.elastic

    def arm_heartbeat(self, directory, interval_s=0.5, lease_s=3.0,
                      relay="auto", grow=False, **kw):
        """Arm host-level fault domains (resilience/heartbeat.py): this
        process leases its liveness into ``directory`` (shared storage
        every host reaches), a monitor thread marks peer hosts dead on
        lease expiry, and sharded solvers gate every cross-host round
        on the rendezvous so a dead peer costs an eviction, never a
        hang inside a collective.

        relay: "auto" routes the tau-interval cross-host average
        through the directory (heartbeat.FileConsensus) when the
        backend has no multi-process collectives (multi-process CPU);
        True/False force it. Arm BEFORE arm_elastic so the membership
        world sizes to the process count.

        grow: this is a LATE JOINER (`--grow`) — an independent
        single-jax-process that grows an already-running world through
        the rendezvous dir instead of launching inside a
        jax.distributed fleet (which fixes membership at init and can
        never admit anyone). The joiner scans the fresh leases, takes
        host id max(existing)+1, forces the relay transport on, and
        fast-forwards its round counter to the running world's front
        at its first gate (LocalSGD); the incumbents' gates see the
        new lease and admit it (HeartbeatCoordinator.admit_host +
        ElasticPolicy.admit) with zero recompiles."""
        from ..resilience.heartbeat import (HeartbeatCoordinator,
                                            FileConsensus, fresh_leases)
        host = jax.process_index()
        n = jax.process_count()
        self._grow_pending = False
        if grow:
            if n > 1:
                self.log("heartbeat: WARNING — --grow ignored inside a "
                         f"{n}-process jax.distributed world (its "
                         "membership is fixed at init); launch the "
                         "joiner as a standalone single process")
            else:
                existing = fresh_leases(directory, lease_s)
                if existing:
                    host = max(existing) + 1
                    n = host + 1
                    relay = True if relay == "auto" else relay
                    self._grow_pending = True
                    self.log(f"heartbeat: joining a running world of "
                             f"{len(existing)} host(s) "
                             f"{sorted(existing)} as host {host}")
                else:
                    self.log("heartbeat: --grow found no fresh leases "
                             f"under {directory}; starting a new world")
        kw.setdefault("metrics", self.metrics)
        kw.setdefault("log_fn", self.log)
        kw.setdefault("chaos", self.chaos)
        coord = HeartbeatCoordinator(directory, host=host, n_hosts=n,
                                     interval_s=interval_s,
                                     lease_s=lease_s, **kw).start()
        self.heartbeat = coord
        if relay == "auto":
            from ..parallel.multihost import needs_host_relay
            relay = needs_host_relay()
        if relay and hasattr(self, "_train_round_relay"):
            if self.staleness is not None:
                from ..resilience.heartbeat import AsyncFileConsensus
                self._relay = AsyncFileConsensus(coord, s=self.staleness,
                                                 decay=self.s_decay)
                self.log(f"heartbeat: ASYNC relay consensus armed ({n} "
                         "hosts, versioned barrier-free delta exchange)")
            else:
                self._relay = FileConsensus(coord)
                self.log(f"heartbeat: relay consensus armed ({n} hosts "
                         "through the rendezvous directory)")
        if self.elastic is not None and self.elastic.n != n and \
                getattr(self, "_relay", None) is not None:
            self.log(f"heartbeat: WARNING — elastic world {self.elastic.n}"
                     f" != {n} processes; arm_heartbeat before "
                     "arm_elastic in relay mode")
        return coord

    def coordinated_restart(self, prefix, timeout=30.0):
        """Quorum loss in a multi-host world: barrier with every
        surviving process on the sha256 of the snapshot manifest under
        ``prefix`` before exiting 4, so a supervisor restart resumes
        ONE consistent world (resilience/heartbeat.restart_barrier).
        Single-process (or heartbeat-less) runs: a no-op True."""
        if self.heartbeat is None or jax.process_count() <= 1:
            return True
        from ..resilience.heartbeat import manifest_sha, restart_barrier
        sha = manifest_sha(prefix)
        agreed, _ = restart_barrier(self.heartbeat, sha, timeout=timeout)
        return agreed

    def _alive_mask(self):
        """The (n,) f32 alive mask the compiled step/round consumes —
        all ones without elastic membership, which keeps the masked
        average bit-for-bit the plain pmean. Sized to the mesh's
        membership axis (the host axis of hierarchical solvers); under
        the relay transport the policy world spans PROCESSES instead,
        so the local compiled round sees all-ones and membership is
        applied host-side at the exchange."""
        axis = getattr(self, "elastic_axis", None) or self.axis
        n = self.mesh.shape[axis]
        if self.elastic is not None and self.elastic.n == n:
            return jnp.asarray(self.elastic.alive_f32())
        return jnp.ones((n,), jnp.float32)

    def _staleness_lag(self):
        """The (n,) f32 per-worker version-lag vector the async compiled
        round consumes next to the alive mask — all zeros while the mode
        is off (which keeps the staleness weights exactly 1.0, the
        bit-for-bit anchor) or when the policy world spans processes
        (relay mode applies staleness host-side at the exchange)."""
        axis = getattr(self, "elastic_axis", None) or self.axis
        n = self.mesh.shape[axis]
        if self.staleness is not None and self.elastic is not None \
                and self.elastic.n == n:
            return jnp.asarray(self.elastic.lag(), jnp.float32)
        return jnp.zeros((n,), jnp.float32)

    def _observe_membership(self, aux, round_idx=None):
        """Feed the elastic membership controller one materialized
        round's validity/loss vectors. QuorumLost propagates — the run
        must stop — but nothing else may kill training."""
        if self.elastic is None or not aux:
            return
        from ..resilience.elastic import QuorumLost
        try:
            self.elastic.observe_round(
                round_idx if round_idx is not None else self.iter - 1,
                valid=aux.get("valid"),
                worker_loss=aux.get("worker_loss"))
        except QuorumLost:
            raise
        except Exception as e:
            self.log(f"elastic membership observation failed: {e!r}")

    def scale_lr(self, factor):
        """Scale the lr schedule by ``factor`` from now on. The schedule
        is traced into the compiled step, so the jitted programs are
        invalidated — one recompile per call (rollbacks are rare)."""
        base, factor = self.lr_fn, float(factor)
        self.lr_fn = lambda it: base(it) * factor
        self._jit_train = None
        if hasattr(self, "_jit_round"):
            self._jit_round = None

    def _chaos_loss(self, loss):
        """Apply armed per-step chaos injectors (stall, loss poisoning)
        to the step that just dispatched; no-op when chaos is off."""
        if self.chaos is None:
            return loss
        self.chaos.maybe_stall(self.iter - 1)
        if self.chaos.poison_loss(self.iter - 1):
            return jnp.asarray(float("nan"), jnp.float32)
        return loss

    def _maybe_recover(self, loss):
        """Feed a materialized loss to the recovery policy; True when the
        solver was rolled back (the caller should redo the work)."""
        if self.recovery is None or loss is None:
            return False
        return self.recovery.observe(self, float(loss))

    # -- observability (sparknet_tpu.obs) ----------------------------------
    def _register_comms(self, cm):
        """Declare this solver's per-round collective volume with the
        CommsMeter — overridden by sharded solvers; the base solver only
        has host->device feed traffic."""
        from ..obs.comms import tree_bytes
        cm.set_topology(strategy=type(self).__name__,
                        n_devices=jax.device_count(),
                        param_bytes=tree_bytes(self.params))

    def _obs_step(self, host_s, result, batch, aux=None):
        """Per-step hook called by every train_step/train_round variant:
        h2d byte counting, comms emission, step accounting. No-op (one
        attribute test) when metrics is off. ``aux``: the sync round's
        on-device divergence stats (sharded solvers) — fetched only at
        step-sample points, where the host already paid the device
        sync, so the async-dispatch discipline is preserved."""
        if self.stepstats is None:
            return
        if not self._comms_registered:
            self._comms_registered = True
            try:
                self._register_comms(self.comms)
            except Exception as e:      # accounting must never kill a run
                self.log(f"comms registration failed: {e!r}")
        it = self.iter - 1
        from ..obs.comms import tree_bytes
        self.comms.add_h2d(tree_bytes(batch))
        self.comms.tick(it)
        jit_fn = self._jit_train if self._jit_train is not None \
            else getattr(self, "_jit_round", None)   # LocalSGDSolver
        sampled = self.stepstats.observe(it, host_s, result=result,
                                         jit_fn=jit_fn, batch=batch)
        if sampled:
            if self.memstats is not None:
                try:
                    self.memstats.sample(it, jit_fns=(jit_fn,))
                except Exception as e:
                    self.log(f"memstats sampling failed: {e!r}")
            if aux:
                self._observe_sync_round(aux)

    def _round_latencies(self, round_s):
        """Per-worker latencies for the just-finished sync round, or None
        when the solver has no per-worker attribution. Base solvers have
        one worker; LocalSGDSolver overrides with chaos-stall (and, in
        real fleets, per-host timer) attribution."""
        return None

    def _observe_sync_round(self, aux, round_s=None, round_idx=None):
        """Fetch one sync round's on-device aux stats (a few scalars),
        feed the elastic membership controller, emit the ``divergence``
        event, and feed the health detectors. Called by _obs_step at
        sample points (per-step solvers) or once per round
        (LocalSGDSolver). Only QuorumLost — the membership verdict that
        the run must stop — escapes into the step loop."""
        if not aux:
            return None
        try:
            aux = jax.device_get(aux)
        except Exception as e:          # monitoring must never kill a run
            self.log(f"sync-round aux fetch failed: {e!r}")
            return None
        # membership first: eviction decisions (and the QuorumLost
        # abort) must not depend on the metrics stream being armed.
        # The health detectors below still judge this round against the
        # membership that was IN FORCE while it ran — a worker evicted
        # or readmitted just now must not alarm against the new mask.
        alive_during_round = self.elastic.alive.copy() \
            if self.elastic is not None else None
        self._observe_membership(aux, round_idx)
        if self.divergence is None:
            return None
        try:
            d = self.divergence.observe(
                self.iter - 1, aux, kind=aux.get("kind", "params"),
                tau=getattr(self, "tau", None), round_idx=round_idx)
            self.last_divergence = d
            if self.health is not None:
                self.health.observe_round(
                    self.iter - 1, round_idx=round_idx,
                    worker_losses=aux.get("worker_loss"),
                    latencies=self._round_latencies(round_s)
                    if round_s is not None else None,
                    divergence=d, valid=aux.get("valid"),
                    alive=alive_during_round,
                    lag=aux.get("lag"), parked=aux.get("parked"),
                    staleness=self.staleness)
            return d
        except Exception as e:          # monitoring must never kill a run
            self.log(f"divergence observation failed: {e!r}")
            return None

    def arm_health(self, **kw):
        """(Re)configure the health detectors (CLI --health-* flags).
        Replaces the default monitor, preserving the metrics sink; pass
        enabled=False to disarm."""
        if not kw.pop("enabled", True):
            self.health = None
            return None
        from ..obs import HealthMonitor
        kw.setdefault("log_fn", self.log)
        self.health = HealthMonitor(self.metrics, solver=self, **kw)
        return self.health

    def close(self):
        """Teardown: stop the watchdog thread (a leaked monitor thread
        keeps pytest and short-lived drivers alive), flush step/comms
        summaries, and close an internally-owned metrics stream.
        Idempotent; training can NOT continue afterwards with metrics."""
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        if self.heartbeat is not None:
            try:
                self.heartbeat.stop()   # the leaser thread must not
            finally:                    # outlive the run (pytest hangs)
                self.heartbeat = None
                if getattr(self, "_relay", None) is not None:
                    self._relay = None
        if self.health is not None:
            try:
                if self.health.alarms and self.metrics is not None:
                    self.metrics.log("health_summary",
                                     **self.health.summary())
            finally:
                self.health = None
        if self.elastic is not None:
            try:
                if self.metrics is not None and \
                        (self.elastic.evictions or
                         self.elastic.readmissions):
                    self.metrics.log("membership", kind="summary",
                                     **self.elastic.summary())
            finally:
                self.elastic = None
        self.divergence = self.memstats = None
        if self.stepstats is not None:
            try:
                self.stepstats.flush(self.iter)
            finally:
                self.stepstats = None
        if self.comms is not None:
            try:
                self.comms.flush(self.iter - 1)
            finally:
                self.comms = None
        if self._own_metrics and self.metrics is not None:
            self.metrics.close()
            self.metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- public API --------------------------------------------------------
    def check_batch(self, batch, leading=(), split_across_hosts=True):
        """Fail fast with blob names when a feed array has the wrong shape
        (otherwise the error is a cryptic reshape deep inside some layer).
        Multi-process: each host feeds its 1/process_count slice of the
        batch axis (shard_batch assembles the global array), so the
        expected leading batch dim shrinks accordingly — unless the
        caller feeds every host the full global batch
        (split_across_hosts=False, the SeqParallelSolver discipline)."""
        pcount = jax.process_count() if split_across_hosts else 1
        shapes = dict(self.net.feed_shapes())
        if self._raw_feed_shapes:
            # device-side transform: the host feeds the RAW source extent
            # (+ aux offset arrays), not the net's post-transform shape
            shapes.update(self._raw_feed_shapes)
        for name, want in shapes.items():
            if want is None:
                # produced on-device (e.g. a device-resident dataset feeds
                # data/label from HBM) — the host doesn't ship this blob
                continue
            if name not in batch:
                raise ValueError(f"batch missing feed blob {name!r} "
                                 f"(needs {sorted(shapes)})")
            got = tuple(np.shape(batch[name]))
            expect = tuple(leading) + tuple(want)
            if pcount > 1 and expect:
                bd = len(leading)
                if expect[bd] % pcount:
                    raise ValueError(
                        f"feed blob {name!r}: global batch {expect[bd]} not "
                        f"divisible by {pcount} hosts")
                expect = expect[:bd] + (expect[bd] // pcount,) \
                    + expect[bd + 1:]
            if got != expect:
                raise ValueError(
                    f"feed blob {name!r}: got shape {got}, net was compiled "
                    f"for {expect}"
                    + (f" (this host's slice of {pcount} hosts)"
                       if pcount > 1 else ""))

    def train_step(self, batch):
        """One optimization step; returns the (unsmoothed) loss value."""
        if self._jit_train is None:
            self._jit_train = self._build_train_step()
        iter_size = int(self.param.iter_size)
        self.check_batch(batch, leading=(iter_size,) if iter_size > 1 else ())
        self.rng, key = jax.random.split(self.rng)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        t0 = time.perf_counter()
        if self._it_dev is None:
            self._it_dev = jnp.asarray(self.iter, jnp.int32)
        self.params, self.state, self.history, loss, self._it_dev = \
            self._jit_train(self.params, self.state, self.history, batch,
                            self._it_dev, key)
        self.iter += 1
        host_s = time.perf_counter() - t0
        self._timing["train_step"] += host_s
        self._obs_step(host_s, loss, batch)
        return self._chaos_loss(loss)

    def step(self, num_iters, data_iter, test_data_fn=None):
        """Run ``num_iters`` steps (the analog of ccaffe solver_step): pulls
        batches from ``data_iter``, displays smoothed loss, runs scheduled
        tests (test_data_fn() -> fresh test batch iterator) and snapshots."""
        sp = self.param
        iter_size = int(sp.iter_size)
        # throughput windows use the WALL clock: on remote-tunnel rigs the
        # monotonic clock slews after long device waits (observed: 200
        # pipelined steps billed 43 s by perf_counter vs 1.4 s wall), and
        # an async step loop is exactly that workload. An NTP step can
        # garble one metrics window; the dt > 0 guard drops it.
        t_last, it_last = time.time(), self.iter
        for _ in range(num_iters):
            if sp.test_interval and self.iter % sp.test_interval == 0 and \
                    (self.iter > 0 or sp.test_initialization) and \
                    self.test_net is not None and test_data_fn is not None:
                scores = self.test(test_data_fn())
                for k, v in scores.items():
                    self.log(f"    Test net output: {k} = {v}")
                if self.metrics:
                    self.metrics.log("test", iter=self.iter,
                                     **{k: float(np.mean(v))
                                        for k, v in scores.items()})
                t_last, it_last = time.time(), self.iter
            if iter_size == 1:
                batch = next(data_iter)
            else:
                micros = [next(data_iter) for _ in range(iter_size)]
                batch = {k: np.stack([m[k] for m in micros])
                         for k in micros[0]}
            # debug_info dumps run on PRE-update params (the state that
            # produces this iteration's loss), like the reference's
            # mid-step prints
            if int(sp.debug_info) and sp.display \
                    and self.iter % sp.display == 0:
                micro = batch if iter_size == 1 \
                    else {k: v[0] for k, v in batch.items()}
                self._print_debug_info(micro)
            loss = self.train_step(batch)
            # deferred sync: losses stay device handles; fetching one is a
            # full round trip, so it happens at display points (or every
            # _sync_stride steps) — dispatches queue ahead in between and
            # the host never serializes transfer against compute
            self._smoothed.append(loss)
            disp = sp.display and (self.iter - 1) % sp.display == 0
            if not disp:
                if self.iter % self._sync_stride == 0:
                    v = float(loss)
                    if self.watchdog is not None:
                        self.watchdog.beat(v)
                    if self._maybe_recover(v):
                        t_last, it_last = time.time(), self.iter
                        continue        # rolled back; redo from there
                elif self.watchdog is not None:
                    self.watchdog.beat()
            if disp:
                # ONE fetch for the whole smoothing window
                sm = self.smoothed_loss()
                if self.watchdog is not None:
                    self.watchdog.beat(sm)
                if self._maybe_recover(sm):
                    # rolled back; restart the throughput window too (the
                    # iter counter went backwards)
                    t_last, it_last = time.time(), self.iter
                    continue
                lr = float(self.lr_fn(self.iter - 1))
                self.log(f"Iteration {self.iter - 1}, loss = {sm:.6g}, "
                         f"lr = {lr:.6g}")
                if self.metrics:
                    dt = time.time() - t_last
                    steps = self.iter - it_last
                    bsz = next(iter(self.net.feed_shapes().values()), (0,))
                    self.metrics.log(
                        "train", iter=self.iter - 1, loss=sm, lr=lr,
                        images_per_sec=round(steps * iter_size * bsz[0] / dt,
                                             2) if dt > 0 and bsz else None)
                    t_last, it_last = time.time(), self.iter
            if sp.snapshot and self.iter % sp.snapshot == 0 and \
                    sp.has("snapshot_prefix"):
                self.snapshot()

    def test(self, data_iter, num_iters=None):
        """Average the TEST net's output blobs over test_iter batches
        (reference solver.cpp TestAndStoreResult :414-444)."""
        with self.tracer.span("test", iter=self.iter):
            return self._test(data_iter, num_iters)

    def _test(self, data_iter, num_iters=None):
        if self._jit_eval is None:
            self._jit_eval = self._build_eval_step()
        n = num_iters or (int(self.param.test_iter[0])
                          if self.param.test_iter else 1)
        # accumulate ON DEVICE: each batch's scores stay as async jax
        # arrays, so the n eval dispatches (and their H2D feeds) pipeline;
        # the only host sync is the final fetch
        sums = None
        # sharded solvers that re-place batches themselves (the
        # global-feed path fetches host data per blob) skip the eager
        # device conversion — it would only add a transfer round trip
        to_dev = jax.process_count() == 1
        try:
            for i in range(n):
                batch = next(data_iter)
                if to_dev:
                    batch = {k: jnp.asarray(v) for k, v in batch.items()}
                out = self._jit_eval(self.params, self.state, batch)
                if sums is None:
                    sums = {k: jnp.asarray(v, jnp.float32)
                            for k, v in out.items()}
                else:
                    sums = {k: sums[k] + jnp.asarray(out[k], jnp.float32)
                            for k in sums}
        finally:
            if hasattr(data_iter, "close"):
                data_iter.close()
        return {k: np.asarray(v, np.float64) / n for k, v in sums.items()}

    # -- checkpointing (reference solver.cpp Snapshot :447-521) ------------
    def snapshot(self, prefix=None, format=None):
        """Write weights + solver state. format: "binaryproto" (default) |
        "hdf5", or taken from SolverParameter.snapshot_format (HDF5=0)."""
        with self.tracer.span("snapshot", iter=self.iter):
            return self._snapshot(prefix, format)

    def _snapshot_paths(self, prefix=None, format=None):
        """-> (model_path, state_path, format) for a snapshot at the
        current iter (reference Snapshot naming, solver.cpp:466-470)."""
        prefix = prefix or self.param.snapshot_prefix
        if format is None:
            format = "hdf5" if int(self.param.snapshot_format) == 0 \
                else "binaryproto"
        ext = ".h5" if format == "hdf5" else ""
        return (f"{prefix}_iter_{self.iter}.caffemodel{ext}",
                f"{prefix}_iter_{self.iter}.solverstate{ext}", format)

    def _write_snapshot_files(self, model_path, state_path, format,
                              learned_net=None):
        """Write the two snapshot files to the given (possibly temporary)
        paths; ``learned_net`` is the model path the state file should
        reference — the FINAL name when writing through the atomic
        checkpoint protocol."""
        from . import hdf5_io
        learned = learned_net or model_path
        if format == "hdf5":
            hdf5_io.save_net_hdf5(model_path, self.net, self.params)
            hdf5_io.save_state_hdf5(state_path, self.iter, learned,
                                    self.net, self.history)
        else:
            net_proto = self.net.params_to_netproto(self.params, self.state)
            wire.dump(net_proto, model_path)
            ss = Message("SolverState", iter=self.iter,
                         learned_net=learned, current_step=0)
            # caffe history_ vector order: slot-major over net-ordered params
            for lname, i, s in hdf5_io.history_order(self.net, self.history):
                ss.history.append(
                    array_to_blob(np.asarray(self.history[lname][i][s])))
            wire.dump(ss, state_path)

    def _snapshot_writer(self):
        """Which process commits snapshots in a multi-process world:
        the lowest-indexed LIVE host (process 0 while healthy). Params/
        state/history are replicated, so N processes writing the same
        files would race each other's renames and manifest commits —
        the bug class the multi-process SIGTERM path used to have."""
        if jax.process_count() <= 1:
            return True
        me = jax.process_index()
        hb = self.heartbeat
        if hb is not None:
            try:
                return me == min(hb.live_processes() + [me])
            except Exception:
                pass
        return me == 0

    def _snapshot(self, prefix=None, format=None):
        # every snapshot goes through the crash-safe commit protocol:
        # temp-write -> fsync -> atomic rename -> manifest (the manifest
        # covers model+state as ONE unit; see resilience/checkpoint.py).
        # Multi-process: the designated writer commits; everyone else
        # barriers on the manifest it produced (satellite: N processes
        # must never race the same snapshot files).
        from ..resilience import checkpoint
        prefix = prefix or self.param.snapshot_prefix
        if not self._snapshot_writer():
            entry = checkpoint.wait_for_manifest(prefix,
                                                 min_iter=self.iter)
            if entry is None:
                self.log(f"snapshot: writer never committed iter "
                         f"{self.iter} under {prefix!r} (timed out); "
                         "continuing without a local copy")
                return None, None
            d = os.path.dirname(prefix)
            self.log(f"snapshot: committed by the writer process "
                     f"(iter {entry.get('iter')})")
            return (os.path.join(d, entry.get("model", "")),
                    os.path.join(d, entry.get("state", "")))
        model_path, state_path = checkpoint.save_snapshot(
            self, prefix, format=format, keep=self.snapshot_keep,
            metrics=self.metrics)
        self.log(f"Snapshotting to {model_path}")
        return model_path, state_path

    def restore(self, state_path, reshard="strict"):
        """Resume from a .solverstate[.h5] (+ its learned_net weights).
        Snapshots a manifest marks partial/corrupt are refused with the
        reason; a snapshot stamped by a DIFFERENT world (process count
        or mesh shape) raises WorldMismatch with the remedy under
        ``reshard="strict"``, while ``reshard="auto"`` re-partitions it
        for this run's world (resilience/checkpoint.py): params and
        optimizer history are replicated across the consensus axis, so
        the blobs restore unchanged and only data ownership re-spreads
        (the reshard_for_world plan, emitted as a `reshard` event); the
        snapshot is re-stamped for this world at the next snapshot."""
        from . import hdf5_io
        from ..resilience import checkpoint
        world = checkpoint.world_signature(self)
        entry = checkpoint.check_restorable(
            state_path, world=world, reshard=reshard)
        self._reshard_plan = None
        if reshard == "auto" and isinstance(entry, dict):
            plan = checkpoint.reshard_for_world(entry.get("world"), world)
            if plan is not None:
                self._reshard_plan = plan
                self.log(
                    f"reshard: snapshot {state_path} written for world "
                    f"{plan['from_world']} ({plan['n_from']} slots); "
                    f"re-partitioning for this world {plan['to_world']} "
                    f"({plan['n_to']} slots, {plan['direction']})")
                if self.metrics is not None:
                    self.metrics.log(
                        "reshard", iter=int(entry.get("iter", 0)),
                        state=state_path,
                        from_world=plan["from_world"],
                        to_world=plan["to_world"],
                        n_from=plan["n_from"], n_to=plan["n_to"],
                        direction=plan["direction"],
                        owners=plan["owners"])
        self._it_dev = None          # re-seed the device iter counter
        if state_path.endswith(".h5"):
            it, learned, self.history = hdf5_io.load_state_hdf5(
                state_path, self.net, self.history)
            self.iter = it
            if learned and os.path.exists(learned):
                self.load_weights(learned)
            return
        ss = wire.load(state_path, "SolverState")
        self.iter = int(ss.iter)
        if ss.has("learned_net") and os.path.exists(ss.learned_net):
            self.load_weights(ss.learned_net)
        blobs = list(ss.history)
        new_history = {k: [list(slot) for slot in v]
                       for k, v in self.history.items()}
        order = list(hdf5_io.history_order(self.net, self.history))
        if len(blobs) != len(order):
            # caffe SGDSolver::RestoreSolverStateFromBinaryProto
            # CHECK_EQ(state.history_size(), history_.size())
            raise ValueError(
                f"{state_path}: solver state has {len(blobs)} history "
                f"blobs, this solver expects {len(order)} — it was written "
                f"by a different solver type")
        for n, (lname, i, s) in enumerate(order):
            ref = new_history[lname][i][s]
            arr = blob_to_array(blobs[n]).reshape(ref.shape)
            new_history[lname][i][s] = jnp.asarray(arr, ref.dtype)
        self.history = new_history

    def load_weights(self, caffemodel_path):
        """CopyTrainedLayersFrom equivalent — accepts stock .caffemodel
        (binaryproto) or .caffemodel.h5 (HDF5)."""
        if caffemodel_path.endswith(".h5"):
            from . import hdf5_io
            self.params = hdf5_io.load_net_hdf5(caffemodel_path, self.net,
                                                self.params)
            return
        net_proto = wire.load(caffemodel_path, "NetParameter")
        self.params, self.state = self.net.load_netproto(
            net_proto, self.params, self.state)
