"""Learning-rate policies (reference sgd_solver.cpp GetLearningRate :27-79).

Each returns a jnp scalar from a (possibly traced) iteration index so the
whole schedule lives inside the jitted train step — no per-iteration
recompiles, no host round trip.

  fixed:     base_lr
  step:      base_lr * gamma ^ floor(iter / stepsize)
  exp:       base_lr * gamma ^ iter
  inv:       base_lr * (1 + gamma * iter) ^ -power
  multistep: base_lr * gamma ^ (#stepvalues <= iter)
  poly:      base_lr * (1 - iter/max_iter) ^ power
  sigmoid:   base_lr * 1/(1 + exp(-gamma * (iter - stepsize)))
"""

import numpy as np
import jax.numpy as jnp


def make_lr_fn(sp):
    """SolverParameter -> fn(iter) -> lr (jnp scalar)."""
    policy = sp.lr_policy
    base_lr = float(sp.base_lr)
    if policy == "fixed":
        return lambda it: jnp.asarray(base_lr, jnp.float32)
    if policy == "step":
        stepsize = int(sp.stepsize)
        gamma = float(sp.gamma)
        return lambda it: base_lr * gamma ** jnp.floor(it / stepsize)
    if policy == "exp":
        gamma = float(sp.gamma)
        return lambda it: base_lr * gamma ** it.astype(jnp.float32) \
            if hasattr(it, "astype") else base_lr * gamma ** it
    if policy == "inv":
        gamma, power = float(sp.gamma), float(sp.power)
        return lambda it: base_lr * (1.0 + gamma * it) ** (-power)
    if policy == "multistep":
        steps = jnp.asarray(list(sp.stepvalue), jnp.int32)
        gamma = float(sp.gamma)
        return lambda it: base_lr * gamma ** jnp.sum(steps <= it)
    if policy == "poly":
        power = float(sp.power)
        max_iter = int(sp.max_iter)
        return lambda it: base_lr * (1.0 - it / max_iter) ** power
    if policy == "sigmoid":
        gamma, stepsize = float(sp.gamma), int(sp.stepsize)
        return lambda it: base_lr / (1.0 + jnp.exp(-gamma * (it - stepsize)))
    raise ValueError(f"unknown lr_policy {policy!r}")
