"""Solver update rules with exact Caffe semantics.

Re-derives, as pure pytree transforms, the math of the reference solver
hierarchy (solvers/sgd_solver.cpp and siblings):

  order per step (sgd_solver.cpp ApplyUpdate :102-117):
    rate = lr_policy(iter)
    ClipGradients            on RAW summed grads (:81-99)
    per param: Normalize (grad /= iter_size, :119-140)
               Regularize (grad += decay_mult*wd * {w | sign(w)}, :143-205)
               ComputeUpdateValue (per solver type)
    param -= update

  SGD       h = m*h + lr_local*g;            u = h           (:207+)
  Nesterov  h' = m*h + lr_local*g;           u = (1+m)h' - m*h
  AdaGrad   h += g^2;                        u = lr_local * g/(sqrt(h)+delta)
  RMSProp   h = r*h + (1-r)*g^2;             u = lr_local * g/(sqrt(h)+delta)
  AdaDelta  hg = m*hg + (1-m)g^2
            u  = g * sqrt((hu+delta)/(hg+delta))
            hu = m*hu + (1-m)u^2;            u *= lr_local
  Adam      m1 = b1*m1 + (1-b1)g; m2 = b2*m2 + (1-b2)g^2
            u  = lr_local * sqrt(1-b2^t)/(1-b1^t) * m1/(sqrt(m2)+delta)

All state is a per-param list of history arrays, mirroring the reference's
``history_`` blobs so .solverstate interchange is possible.
"""

import jax
import jax.numpy as jnp

SOLVER_TYPES = ("SGD", "Nesterov", "AdaGrad", "RMSProp", "AdaDelta", "Adam")

# number of history slots per param
N_HISTORY = {"SGD": 1, "Nesterov": 1, "AdaGrad": 1, "RMSProp": 1,
             "AdaDelta": 2, "Adam": 2}


def canonical_type(sp):
    """Resolve the solver type string, honoring the deprecated enum
    (reference solver_factory via SolverParameter.type / solver_type)."""
    t = sp.type
    if sp.has("solver_type") and not sp.has("type"):
        t = SOLVER_TYPES[int(sp.solver_type)]
    for s in SOLVER_TYPES:
        if t.lower() == s.lower():
            return s
    raise ValueError(f"unknown solver type {t!r}")


def init_history(solver_type, params):
    n = N_HISTORY[solver_type]
    return jax.tree_util.tree_map(
        lambda p: [jnp.zeros_like(p) for _ in range(n)], params,
        is_leaf=lambda x: hasattr(x, "shape"))


def apply_clip(grads, clip, sumsq):
    """Scale ``grads`` by clip/norm when the global L2 norm exceeds
    ``clip``. Split out of `clip_gradients` so a sharded caller (FSDP)
    can supply the DISTRIBUTED sumsq — shard leaves psum'd over the mesh
    axis — and still get reference clip semantics on the global norm."""
    norm = jnp.sqrt(sumsq)
    scale = jnp.where(norm > clip, clip / jnp.maximum(norm, 1e-30), 1.0)
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


def clip_gradients(grads, clip):
    """Global L2-norm clipping (sgd_solver.cpp:81-99); clip < 0 disables."""
    if clip is None or clip < 0:
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    sumsq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves)
    return apply_clip(grads, clip, sumsq)


def accum_init(params):
    """fp32 gradient accumulators for the iter_size micro-batch loop:
    the mixed-precision contract (Micikevicius et al., 2018) sums
    micro-grads in fp32 even when params or compute are bf16/fp16.
    fp32 params already accumulate in fp32, so this is bit-for-bit the
    old zeros_like path there."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(
            p.shape,
            jnp.float32 if jnp.issubdtype(p.dtype, jnp.floating)
            and jnp.finfo(p.dtype).bits < 32 else p.dtype),
        params)


def accum_add(acc, g):
    """acc + g in the accumulator's (>= fp32) dtype."""
    return jax.tree_util.tree_map(
        lambda a, x: a + x.astype(a.dtype), acc, g)


def regularize(grad, param, wd_local, reg_type):
    if wd_local == 0.0:
        return grad
    if reg_type == "L1":
        return grad + wd_local * jnp.sign(param)
    return grad + wd_local * param  # L2


def compute_update(solver_type, grad, history, local_rate, *, momentum,
                   delta, rms_decay, momentum2, t):
    """-> (update, new_history). ``t`` = iter+1 (Adam bias correction)."""
    g = grad
    if solver_type == "SGD":
        h = momentum * history[0] + local_rate * g
        return h, [h]
    if solver_type == "Nesterov":
        h_new = momentum * history[0] + local_rate * g
        u = (1.0 + momentum) * h_new - momentum * history[0]
        return u, [h_new]
    if solver_type == "AdaGrad":
        h = history[0] + g * g
        u = local_rate * g / (jnp.sqrt(h) + delta)
        return u, [h]
    if solver_type == "RMSProp":
        h = rms_decay * history[0] + (1.0 - rms_decay) * g * g
        u = local_rate * g / (jnp.sqrt(h) + delta)
        return u, [h]
    if solver_type == "AdaDelta":
        hg = momentum * history[0] + (1.0 - momentum) * g * g
        u = g * jnp.sqrt((history[1] + delta) / (hg + delta))
        hu = momentum * history[1] + (1.0 - momentum) * u * u
        return local_rate * u, [hg, hu]
    if solver_type == "Adam":
        m1 = momentum * history[0] + (1.0 - momentum) * g
        m2 = momentum2 * history[1] + (1.0 - momentum2) * g * g
        correction = jnp.sqrt(1.0 - momentum2 ** t) / (1.0 - momentum ** t)
        u = local_rate * correction * m1 / (jnp.sqrt(m2) + delta)
        return u, [m1, m2]
    raise ValueError(solver_type)


class Updater:
    """Bound update transform for one SolverParameter + param-multiplier map.

    mults: pytree congruent to params with (lr_mult, decay_mult) leaves.
    """

    def __init__(self, sp, mults):
        self.solver_type = canonical_type(sp)
        self.momentum = float(sp.momentum) if sp.has("momentum") else 0.0
        self.momentum2 = float(sp.momentum2)
        self.delta = float(sp.delta)
        self.rms_decay = float(sp.rms_decay) if sp.has("rms_decay") else 0.99
        self.weight_decay = float(sp.weight_decay) \
            if sp.has("weight_decay") else 0.0
        self.reg_type = sp.regularization_type
        self.clip = float(sp.clip_gradients)
        self.iter_size = int(sp.iter_size)
        self.mults = mults

    def init(self, params):
        return init_history(self.solver_type, params)

    def __call__(self, params, grads, history, rate, it, clip_fn=None):
        """One update: returns (new_params, new_history).

        ``rate`` is the policy lr for this iter; ``it`` the iter index
        (both may be traced). ``clip_fn`` replaces the default global
        L2 clip — a sharded solver passes one that computes the norm
        over the whole mesh (see parallel/fsdp.py); None keeps the
        reference `clip_gradients` path bit-for-bit.
        """
        grads = clip_fn(grads) if clip_fn is not None \
            else clip_gradients(grads, self.clip)
        t = it + 1
        new_params, new_history = {}, {}
        for lname, blobs in params.items():
            ups, hs = [], []
            for i, p in enumerate(blobs):
                g = grads[lname][i].astype(p.dtype)
                lr_mult, decay_mult = self.mults[lname][i]
                if self.iter_size > 1:
                    g = g / self.iter_size
                g = regularize(g, p, self.weight_decay * decay_mult,
                               self.reg_type)
                local_rate = rate * lr_mult
                u, h = compute_update(
                    self.solver_type, g, history[lname][i], local_rate,
                    momentum=self.momentum, delta=self.delta,
                    rms_decay=self.rms_decay, momentum2=self.momentum2, t=t)
                ups.append(p - u)
                hs.append(h)
            new_params[lname] = ups
            new_history[lname] = hs
        return new_params, new_history
