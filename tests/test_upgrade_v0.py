"""V0 -> V2 NetParameter upgrade (reference util/upgrade_proto.cpp:93-584)."""

import numpy as np
import jax
import pytest

from sparknet_tpu.proto import text_format, Message
from sparknet_tpu.graph import (CompiledNet, upgrade_net, upgrade_v0,
                                needs_v0_upgrade, TRAIN)

V0_NET = """
name: "v0_lenet"
input: "data"
input_dim: 4 input_dim: 2 input_dim: 24 input_dim: 24
input: "label"
input_dim: 4 input_dim: 1 input_dim: 1 input_dim: 1
layers {
  layer { name: "pad1" type: "padding" pad: 2 }
  bottom: "data" top: "pad1"
}
layers {
  layer {
    name: "conv1" type: "conv" num_output: 8 kernelsize: 5 stride: 1
    group: 2 biasterm: true
    weight_filler { type: "gaussian" std: 0.01 }
  }
  bottom: "pad1" top: "conv1"
}
layers {
  layer { name: "relu1" type: "relu" }
  bottom: "conv1" top: "conv1"
}
layers {
  layer { name: "pool1" type: "pool" pool: MAX kernelsize: 2 stride: 2 }
  bottom: "conv1" top: "pool1"
}
layers {
  layer { name: "norm1" type: "lrn" local_size: 3 alpha: 5e-05 beta: 0.75 }
  bottom: "pool1" top: "norm1"
}
layers {
  layer { name: "drop1" type: "dropout" dropout_ratio: 0.3 }
  bottom: "norm1" top: "norm1"
}
layers {
  layer {
    name: "ip1" type: "innerproduct" num_output: 10
    blobs_lr: 1.0 blobs_lr: 2.0 weight_decay: 1.0 weight_decay: 0.0
  }
  bottom: "norm1" top: "ip1"
}
layers {
  layer { name: "loss" type: "softmax_loss" }
  bottom: "ip1" bottom: "label" top: "loss"
}
"""


def test_needs_and_field_mapping():
    net = text_format.loads(V0_NET, "NetParameter")
    assert needs_v0_upgrade(net)
    v1 = upgrade_v0(net)
    assert not needs_v0_upgrade(v1)
    by_name = {l.name: l for l in v1.layers}
    conv = by_name["conv1"]
    assert conv.enum_name("type") == "CONVOLUTION"
    assert int(conv.convolution_param.num_output) == 8
    # pad/kernel_size/stride are repeated in the shared ConvolutionParameter
    # (the reference's UpgradeV0LayerParameter add_pad()s them)
    assert list(conv.convolution_param.kernel_size) == [5]
    assert int(conv.convolution_param.group) == 2
    # the padding layer was fused: pad=2 moved in, bottom rewired to data
    assert list(conv.convolution_param.pad) == [2]
    assert list(conv.bottom) == ["data"]
    assert "pad1" not in by_name
    pool = by_name["pool1"]
    assert pool.pooling_param.enum_name("pool") == "MAX"
    assert int(pool.pooling_param.kernel_size) == 2
    lrn = by_name["norm1"]
    assert int(lrn.lrn_param.local_size) == 3
    assert abs(float(lrn.lrn_param.alpha) - 5e-05) < 1e-9
    assert abs(float(by_name["drop1"].dropout_param.dropout_ratio) - 0.3) \
        < 1e-6
    ip = by_name["ip1"]
    assert int(ip.inner_product_param.num_output) == 10
    assert list(ip.blobs_lr) == [1.0, 2.0]
    assert by_name["loss"].enum_name("type") == "SOFTMAX_LOSS"


def test_v0_net_compiles_and_runs():
    """The whole chain: V0 text -> V2 -> jitted forward."""
    net = text_format.loads(V0_NET, "NetParameter")
    v2 = upgrade_net(net)
    assert len(v2.layer) == 7 and not v2.layers
    cn = CompiledNet(v2, TRAIN)
    params, state = cn.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    loss, _ = cn.loss_fn(params, state,
                         {"data": rs.randn(4, 2, 24, 24).astype(np.float32),
                          "label": rs.randint(0, 10, (4, 1, 1, 1))},
                         jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    # padded conv: 24 + 2*2 - 5 + 1 = 24 -> pool /2 -> 12
    assert cn.blob_shapes["pool1"] == (4, 8, 12, 12)


def test_v0_data_layer_transform_migration():
    """V0 data fields (scale/meanfile/cropsize/mirror) land in
    transform_param; source/batchsize in data_param; and deprecated
    V1-level DataParameter transform fields migrate too."""
    txt = """
    name: "d"
    layers {
      layer {
        name: "data" type: "data" source: "some_lmdb" batchsize: 32
        scale: 0.5 meanfile: "m.binaryproto" cropsize: 20 mirror: true
        rand_skip: 5
      }
      top: "data" top: "label"
    }
    """
    net = text_format.loads(txt, "NetParameter")
    v2 = upgrade_net(net)
    lp = v2.layer[0]
    assert lp.type == "Data"
    assert lp.data_param.source == "some_lmdb"
    assert int(lp.data_param.batch_size) == 32
    assert int(lp.data_param.rand_skip) == 5
    tp = lp.transform_param
    assert abs(float(tp.scale) - 0.5) < 1e-6
    assert tp.mean_file == "m.binaryproto"
    assert int(tp.crop_size) == 20 and bool(tp.mirror)
    # not duplicated on the data_param (reference clears them on upgrade)
    assert not lp.data_param.has("scale")
    assert not lp.data_param.has("mean_file")


def test_padding_fusion_rejects_bad_consumer():
    txt = """
    name: "bad"
    input: "data"
    layers {
      layer { name: "pad1" type: "padding" pad: 1 }
      bottom: "data" top: "p"
    }
    layers {
      layer { name: "r" type: "relu" }
      bottom: "p" top: "r"
    }
    """
    net = text_format.loads(txt, "NetParameter")
    with pytest.raises(ValueError, match="non-conv/pool"):
        upgrade_v0(net)
