"""Scan-over-layers (SPARKNET_SCAN / CompiledNet.scan): the lax.scan
over stacked per-block params must be numerically equivalent to the
unrolled stack — loss and gradients — and must compose with remat.

Also pins the solver-level knob contract (Solver.set_remat/set_scan):
toggling mid-process drops the jit and costs EXACTLY one fresh compile,
never a stale cache entry serving the old policy.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sparknet_tpu.proto import Message
from sparknet_tpu.models import zoo
from sparknet_tpu.graph.compiler import CompiledNet, TRAIN
from sparknet_tpu.solver.solver import Solver


def _lm_net(layers=3):
    return zoo.transformer_lm(vocab_size=64, seq_len=32, batch_size=2,
                              d_model=32, num_layers=layers, num_heads=4,
                              flash=False)


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, 64, (2, 33))
    return {"data": toks[:, :-1], "label": toks[:, 1:]}


def test_run_detection_on_lm_stack():
    net = CompiledNet(_lm_net(3), TRAIN)
    runs = net._scan_runs()
    assert len(runs) == 1
    r = runs[0]
    assert r["n"] == 3 and r["entry"] == "embed"
    assert r["out"].endswith("/res2")
    names = [net.layers[i][0].name for i in range(r["lo"], r["hi"])]
    assert all(n.startswith("block") for n in names)


def test_single_block_forms_no_run():
    assert CompiledNet(_lm_net(1), TRAIN)._scan_runs() == []


def test_scan_loss_and_grads_match_unrolled():
    net = CompiledNet(_lm_net(3), TRAIN)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = _batch()

    def run(mode):
        net.scan = mode
        return jax.value_and_grad(
            lambda p: net.loss_fn(p, state, batch)[0])(params)

    l_off, g_off = run("off")
    l_on, g_on = run("on")
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        g_on, g_off)


@pytest.mark.parametrize("pol", ["dots", "full"])
def test_scan_composes_with_remat(pol):
    net = CompiledNet(_lm_net(3), TRAIN)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = _batch()
    net.scan = "off"
    net.remat = "none"
    l_ref, g_ref = jax.value_and_grad(
        lambda p: net.loss_fn(p, state, batch)[0])(params)
    net.scan = "on"
    net.remat = pol
    l_sc, g_sc = jax.value_and_grad(
        lambda p: net.loss_fn(p, state, batch)[0])(params)
    np.testing.assert_allclose(float(l_sc), float(l_ref), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        g_sc, g_ref)


def test_scan_internal_blobs_absent_boundary_present():
    """Scanned blocks follow the remat-segment blob discipline: internal
    per-layer activations are ABSENT from the returned dict (only the
    run's boundary output exists — one stacked carry lives on device,
    which is the memory win), never stale."""
    net = CompiledNet(_lm_net(3), TRAIN)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = _batch()
    net.scan = "off"
    blobs_off, _ = net.apply(params, state, batch, train=True)
    net.scan = "on"
    blobs_on, _ = net.apply(params, state, batch, train=True)
    run = net._scan_runs()[0]
    assert run["out"] in blobs_on
    assert "block0/attn" in blobs_off
    assert not any(k.startswith("block0/") or k.startswith("block1/")
                   for k in blobs_on)


def test_auto_gate_is_off_on_cpu(monkeypatch):
    monkeypatch.delenv("SPARKNET_SCAN", raising=False)
    net = CompiledNet(_lm_net(3), TRAIN)
    if jax.default_backend() != "tpu":
        assert not net._scan_enabled()
    monkeypatch.setenv("SPARKNET_SCAN", "on")
    assert net._scan_enabled()


# -- solver knob contract (the --remat / --scan CLI flags ride on this) -----

def _solver():
    sp = Message("SolverParameter", base_lr=0.05, lr_policy="fixed",
                 momentum=0.9, display=0, random_seed=0)
    return Solver(sp, net_param=_lm_net(2))


def test_set_remat_one_fresh_compile_no_stale_entries(monkeypatch):
    monkeypatch.delenv("SPARKNET_REMAT", raising=False)
    s = _solver()
    s.train_step(_batch())
    jit_old = s._jit_train
    assert jit_old._cache_size() == 1
    # env flips AFTER tracing are inert: the policy is baked at trace
    # time, so no recompile and no second entry appears
    monkeypatch.setenv("SPARKNET_REMAT", "full")
    s.train_step(_batch(1))
    assert s._jit_train is jit_old and jit_old._cache_size() == 1
    monkeypatch.delenv("SPARKNET_REMAT", raising=False)
    # the real toggle goes through set_remat: the jit is DROPPED, the
    # new one traces once under the new policy — 1 entry, none stale
    s.set_remat("dots")
    assert s._jit_train is None
    s.train_step(_batch(2))
    assert s._jit_train is not jit_old
    assert s._jit_train._cache_size() == 1
    assert s.net.remat == "dots"


def test_set_remat_and_scan_validate():
    s = _solver()
    with pytest.raises(ValueError):
        s.set_remat("bogus")
    with pytest.raises(ValueError):
        s.set_scan("sometimes")


def test_set_scan_matches_unrolled_training():
    def run(mode):
        s = _solver()
        s.set_scan(mode)
        return [float(s.train_step(_batch(i))) for i in range(3)]

    np.testing.assert_allclose(run("on"), run("off"), rtol=1e-5)
