"""SeqParallelSolver (dp x sp): the sequence-sharded LM training
trajectory == single-device training on the global batch.

This is the trained-curve evidence for ring attention that the per-op
exactness tests (test_flash.py ring-vs-dense) don't give: position
embeddings offset per shard, causal ring attention across the seq axis,
per-token loss pmean'd over both axes, momentum updates from pmean'd
grads — all of it, stepped repeatedly, must reproduce the single-device
loss curve."""

import numpy as np
import pytest
import jax

from sparknet_tpu.proto import Message
from sparknet_tpu.models import zoo
from sparknet_tpu.solver.solver import Solver
from sparknet_tpu.parallel import make_mesh, SeqParallelSolver

B, S, V, D = 4, 32, 64, 32
STEPS = 12


def _sp():
    return Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                   momentum=0.9, display=0, random_seed=0)


def _net():
    return zoo.transformer_lm(vocab_size=V, seq_len=S, batch_size=B,
                              d_model=D, num_layers=2, num_heads=2,
                              flash=False, ring=True)


def _batches():
    rs = np.random.RandomState(0)
    toks = rs.randint(0, V, (STEPS, B, S + 1))
    return [{"data": t[:, :-1], "label": t[:, 1:]} for t in toks]


def _curve(solver):
    return [float(solver.train_step(b)) for b in _batches()]


@pytest.mark.parametrize("axes", [{"data": 2, "seq": 4},
                                  {"data": 1, "seq": 8}])
def test_sp_curve_matches_single_device(axes):
    ref = _curve(Solver(_sp(), net_param=zoo.transformer_lm(
        vocab_size=V, seq_len=S, batch_size=B, d_model=D, num_layers=2,
        num_heads=2, flash=False, ring=False)))
    got = _curve(SeqParallelSolver(_sp(), mesh=make_mesh(axes),
                                   net_param=_net()))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
    assert got[-1] < got[0] - 0.1          # and it is actually learning


def test_sp_rejects_indivisible_dims():
    with pytest.raises(ValueError, match="seq dim"):
        SeqParallelSolver(_sp(), mesh=make_mesh({"data": 1, "seq": 8}),
                          net_param=zoo.transformer_lm(
                              vocab_size=V, seq_len=S + 4, batch_size=B,
                              d_model=D, num_layers=1, num_heads=2,
                              flash=False, ring=True))


def test_sp_rejects_ignore_label_loss():
    """ignore_label losses normalize by the per-shard valid count, which
    breaks the equal-shard exactness contract — refuse at init."""
    np2 = _net()
    for lp in np2.layer:
        if lp.name == "loss":
            lp.loss_param = Message("LossParameter", ignore_label=0)
    with pytest.raises(ValueError, match="ignore_label"):
        SeqParallelSolver(_sp(), mesh=make_mesh({"data": 1, "seq": 8}),
                          net_param=np2)


def test_sp_allows_rank1_feed_blobs():
    """(B,)-shaped feed blobs need no sequence shard: they stay
    batch-sharded / seq-replicated instead of erroring at init."""
    from sparknet_tpu.models import dsl
    from sparknet_tpu.parallel.data_parallel import _rebatch
    from sparknet_tpu.graph.compiler import CompiledNet
    np3 = zoo.transformer_lm(vocab_size=V, seq_len=S, batch_size=B,
                             d_model=D, num_layers=1, num_heads=2,
                             flash=False, ring=True)
    np3.layer.insert(2, dsl.RDDLayer("wt", [B]))
    local = _rebatch(CompiledNet(np3), 2, seq=4)
    assert local.feed_shapes()["wt"] == (B // 2,)
    assert local.feed_shapes()["data"] == (B // 2, S // 4)
