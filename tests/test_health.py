"""Training-dynamics health layer tests (sparknet_tpu.obs, ISSUE 3).

Covers the acceptance surface: divergence measured at the sync round is
monotonically non-decreasing in tau on a deterministic toy model with
worker-disjoint data; a chaos-injected stall makes the straggler
detector name the slow worker; the HealthMonitor detectors (straggler,
loss skew, per-worker NaN, divergence trend/ceiling) fire with the right
attribution and respect cooldowns; the comms cost models are clean at
world_size=1 / zero bytes; `sparknet report` / `sparknet monitor` turn
missing/empty/garbage metrics files into one-line errors; and the
device-cache hit/miss gauge lands in the metrics stream.
"""

import io
import json

import numpy as np
import pytest
import jax

from sparknet_tpu.proto import Message
from sparknet_tpu.utils.metrics import MetricsLogger
from sparknet_tpu.obs import (HealthMonitor, DivergenceMeter, MemoryMonitor,
                              CommsMeter, ring_allreduce_bytes,
                              broadcast_collect_bytes, all_to_all_bytes)
from sparknet_tpu.obs import report as obs_report
from sparknet_tpu.obs.report import MetricsFileError
from sparknet_tpu.obs.monitor import MonitorState, _Tail, monitor_file


def events_of(buf):
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def sink():
    buf = io.StringIO()
    return MetricsLogger(stream=buf), buf


def mlp_net(batch=8, dim=16, classes=4):
    net = Message("NetParameter", name="mlp")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[batch, dim])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[batch])))
    net.add("layer", name="fc", type="InnerProduct", bottom=["data"],
            top=["fc"], inner_product_param=dict(
                num_output=classes, weight_filler=dict(type="xavier")))
    net.add("layer", name="loss", type="SoftmaxWithLoss",
            bottom=["fc", "label"], top=["loss"])
    return net


def lsgd_solver(tau, metrics=None):
    from sparknet_tpu.parallel import LocalSGDSolver, make_mesh
    sp = Message("SolverParameter", base_lr=0.05, lr_policy="fixed",
                 random_seed=0, display=0)
    return LocalSGDSolver(sp, net_param=mlp_net(), metrics=metrics,
                          mesh=make_mesh({"data": 2}), tau=tau, log_fn=None)


# ------------------------------------------------ divergence vs tau (e2e)

class TestDivergenceVsTau:
    MAXT = 8

    def _round_batches(self):
        """tau worker-disjoint steps: worker 0 (batch rows 0..7) only ever
        sees class 0 drawn around +2, worker 1 only class 1 around -2 —
        each local step pulls the replicas toward different classifiers,
        so drift at the averaging point grows with tau."""
        rs = np.random.RandomState(0)
        data = rs.randn(self.MAXT, 16, 16).astype(np.float32)
        data[:, :8, :] += 2.0
        data[:, 8:, :] -= 2.0
        labels = np.zeros((self.MAXT, 16), np.int32)
        labels[:, 8:] = 1
        return data, labels

    def test_divergence_monotone_in_tau(self):
        data, labels = self._round_batches()
        means = []
        for tau in (1, 2, 4, 8):
            ms, buf = sink()
            s = lsgd_solver(tau, metrics=ms)
            s.train_round({"data": data[:tau].copy(),
                           "label": labels[:tau].copy()})
            d = s.last_divergence
            s.close()
            assert d is not None, f"no divergence measured at tau={tau}"
            assert d["kind"] == "params" and d["tau"] == tau
            assert len(d["per_worker"]) == 2
            ev = next(e for e in events_of(buf)
                      if e["event"] == "divergence")
            assert ev["mean"] == d["mean"]      # event hit the JSONL
            assert len(ev["worker_loss"]) == 2
            means.append(d["mean"])
        assert means[0] > 0, "identical-start workers measured zero drift"
        assert all(b >= a for a, b in zip(means, means[1:])), \
            f"divergence not monotone in tau: {means}"

    def test_divergence_aux_costs_no_host_gather(self):
        """The per-round divergence event carries only scalars/short
        vectors — never weight-sized payloads."""
        ms, buf = sink()
        s = lsgd_solver(2, metrics=ms)
        data, labels = self._round_batches()
        s.train_round({"data": data[:2], "label": labels[:2]})
        s.close()
        ev = next(e for e in events_of(buf) if e["event"] == "divergence")
        assert len(json.dumps(ev)) < 2048


# ------------------------------------------ straggler via chaos stall (e2e)

class TestStragglerInjection:
    def test_chaos_stall_names_slow_worker(self):
        from sparknet_tpu.resilience.chaos import ChaosMonkey, install_chaos
        install_chaos(ChaosMonkey(stall_step=0, stall_s=0.3, stall_worker=1,
                                  stall_repeat=True,
                                  log_fn=lambda *a: None))
        try:
            ms, buf = sink()
            s = lsgd_solver(2, metrics=ms)
            assert s.chaos is not None
            s.arm_health(straggler_factor=1.3, straggler_min_s=0.05,
                         cooldown=1)
            rs = np.random.RandomState(1)
            batches = {"data": rs.randn(2, 16, 16).astype(np.float32),
                       "label": rs.randint(0, 4, (2, 16)).astype(np.int32)}
            for _ in range(3):
                s.train_round(dict(batches))
            s.close()
        finally:
            install_chaos(None)
        evs = events_of(buf)
        stragglers = [e for e in evs if e["event"] == "health"
                      and e["kind"] == "straggler"]
        assert stragglers, "straggler alarm never fired"
        assert all(e["worker"] == 1 for e in stragglers)
        assert stragglers[0]["ratio"] >= 1.3
        # and the report renders the named straggler in training health
        rep = obs_report.aggregate(evs)
        assert rep["health"]["worst_straggler"] == 1
        text = obs_report.render(rep)
        assert "training health" in text and "straggler: worker 1" in text


# -------------------------------------------------- HealthMonitor (unit)

class TestHealthMonitor:
    def test_straggler_detection_and_cooldown(self):
        ms, buf = sink()
        hm = HealthMonitor(ms, log_fn=None, straggler_factor=1.5,
                           straggler_min_s=0.01, cooldown=3)
        for r in range(4):
            hm.observe_round(r, round_idx=r,
                             latencies=[0.1, 0.1, 0.5, 0.1])
        evs = [e for e in events_of(buf) if e["event"] == "health"]
        assert len(evs) == 2            # obs 1 fires, 2-3 cooled, 4 fires
        assert all(e["kind"] == "straggler" and e["worker"] == 2
                   for e in evs)
        assert hm.straggler_counts[2] == 4   # counted even while cooled

    def test_straggler_needs_margin_and_factor(self):
        ms, buf = sink()
        hm = HealthMonitor(ms, log_fn=None, straggler_factor=1.5,
                           straggler_min_s=0.05, cooldown=1)
        hm.observe_round(0, latencies=[0.10, 0.11])      # under min_s
        hm.observe_round(1, latencies=[1.00, 1.30])      # under factor
        hm.observe_round(2, latencies=[0.5])             # one worker
        assert not events_of(buf)

    def test_loss_skew_jump_over_own_ema(self):
        ms, buf = sink()
        hm = HealthMonitor(ms, log_fn=None, loss_skew_factor=3.0,
                           loss_skew_min=0.01, cooldown=1)
        for r in range(5):
            hm.observe_round(r, worker_losses=[1.0, 1.01])
        hm.observe_round(5, worker_losses=[1.0, 2.0])
        evs = [e for e in events_of(buf) if e["event"] == "health"]
        assert len(evs) == 1 and evs[0]["kind"] == "loss_skew"
        assert evs[0]["worker"] == 1          # the off-trend replica

    def test_worker_nonfinite_is_critical_and_arms_recovery(self):
        class FakeSolver:
            recovery = None
            tau = 4
            armed = None

            def arm_recovery(self, **kw):
                self.armed = kw
        ms, buf = sink()
        fs = FakeSolver()
        hm = HealthMonitor(ms, log_fn=None, solver=fs, arm_recovery=True,
                           recovery_kw={"max_rollbacks": 2})
        hm.observe_round(3, worker_losses=[1.0, float("nan")])
        evs = [e for e in events_of(buf) if e["event"] == "health"]
        kinds = {e["kind"] for e in evs}
        assert "worker_nonfinite" in kinds and "recovery_armed" in kinds
        bad = next(e for e in evs if e["kind"] == "worker_nonfinite")
        assert bad["worker"] == 1 and bad["severity"] == "critical"
        assert fs.armed == {"max_rollbacks": 2}

    def test_divergence_trend_suggests_halved_tau(self):
        ms, buf = sink()
        hm = HealthMonitor(ms, log_fn=None, trend_rounds=3,
                           trend_factor=2.0)
        for r, m in enumerate([0.1, 0.25, 0.6]):
            hm.observe_round(r, divergence={"mean": m, "tau": 8})
        evs = [e for e in events_of(buf) if e["event"] == "health"]
        assert len(evs) == 1 and evs[0]["kind"] == "divergence_trend"
        assert evs[0]["suggest_tau"] == 4
        assert hm.summary()["tau_suggestion"] == 4

    def test_divergence_ceiling_is_critical(self):
        ms, buf = sink()
        hm = HealthMonitor(ms, log_fn=None, div_abs=0.5)
        hm.observe_round(0, divergence={"mean": 0.75, "tau": 4})
        ev = [e for e in events_of(buf) if e["event"] == "health"][0]
        assert ev["kind"] == "divergence_high"
        assert ev["severity"] == "critical" and ev["suggest_tau"] == 2

    def test_detectors_never_raise(self):
        hm = HealthMonitor(None, log_fn=None)
        hm.observe_round(0, latencies="not numbers",
                         worker_losses=object(),
                         divergence={"mean": "nan?"})
        assert hm.alarms == 0


# ------------------------------------------------- DivergenceMeter (unit)

class TestDivergenceMeter:
    def test_observe_builds_full_event(self):
        ms, buf = sink()
        dm = DivergenceMeter(ms, topk=2)
        aux = {"div_mean_sq": 0.04, "div_max_sq": 0.09,
               "div_worker_sq": [0.01, 0.09],
               "layer_div_sq": {"fc": 0.03, "conv": 0.01, "bn": 0.0},
               "ref_sq": 4.0, "worker_loss": [1.0, 2.0]}
        ev = dm.observe(10, aux, kind="params", tau=4, round_idx=2)
        assert ev["mean"] == pytest.approx(0.2)
        assert ev["max"] == pytest.approx(0.3)
        assert ev["per_worker"] == [pytest.approx(0.1), pytest.approx(0.3)]
        assert [k for k, _ in ev["top_layers"]] == ["fc", "conv"]
        assert ev["update_norm"] == pytest.approx(2.0)
        assert ev["rel"] == pytest.approx(0.1)            # sqrt(.04/4)
        assert ev["gns_proxy"] == pytest.approx(0.02)     # 2 * .04/4
        assert dm.last is ev and dm.samples == 1
        logged = events_of(buf)[0]
        assert logged["event"] == "divergence" and logged["tau"] == 4

    def test_observe_skips_without_divergence_fields(self):
        dm = DivergenceMeter(None)
        assert dm.observe(0, {"worker_loss": [1.0]}) is None
        assert dm.observe(0, None) is None and dm.samples == 0

    def test_tree_sq_dist_groups_by_layer(self):
        from sparknet_tpu.obs import tree_sq_dist
        a = {"fc": {"w": np.ones((2, 2), np.float32)},
             "bias": {"b": np.zeros(3, np.float32)}}
        b = {"fc": {"w": np.zeros((2, 2), np.float32)},
             "bias": {"b": np.zeros(3, np.float32)}}
        per, total = tree_sq_dist(a, b)
        assert float(per["fc"]) == pytest.approx(4.0)
        assert float(per["bias"]) == pytest.approx(0.0)
        assert float(total) == pytest.approx(4.0)


# ------------------------------------------------- comms edge cases (sat)

class TestCommsEdgeCases:
    def test_world_size_one_and_zero_bytes_are_zero(self):
        for fn in (ring_allreduce_bytes, broadcast_collect_bytes,
                   all_to_all_bytes):
            assert fn(1 << 20, 1) == 0
            assert fn(0, 8) == 0
            assert fn(0, 1) == 0
            assert fn(1 << 20, 4) > 0

    def test_register_zero_byte_collective_is_noop(self):
        ms, buf = sink()
        cm = CommsMeter(ms)
        assert cm.register("avg", 0) is None
        assert cm.register("avg", ring_allreduce_bytes(100, 1)) is None
        assert cm.collectives == []
        assert cm.collective_bytes_per_step() == 0
        # steps_per_round=0 must not divide by zero downstream
        c = cm.register("avg", 100, steps_per_round=0)
        assert c["steps_per_round"] == 1
        assert cm.collective_bytes_per_step() == 100


# ------------------------------------------------- MemoryMonitor (unit)

class TestMemoryMonitor:
    def test_sample_emits_memstats(self):
        ms, buf = sink()
        mm = MemoryMonitor(ms)
        f = jax.jit(lambda a: a + 1)
        x = f(jax.numpy.zeros((64, 64), jax.numpy.float32))
        x.block_until_ready()
        ev = mm.sample(5, jit_fns=(f, None))
        assert ev["iter"] == 5
        assert ev["live_arrays"] >= 1 and ev["live_bytes"] > 0
        assert ev["host_rss_bytes"] > 0
        assert mm.peak_live_bytes >= ev["live_bytes"] > 0
        logged = events_of(buf)
        assert logged and logged[-1]["event"] == "memstats"

    def test_sample_cadence_and_force(self):
        mm = MemoryMonitor(None, sample_every=3)
        assert mm.sample(0) is not None
        assert mm.sample(1) is None and mm.sample(2) is None
        assert mm.sample(3) is not None
        assert mm.sample(4, force=True) is not None


# ------------------------------------- report/monitor error paths (sat)

class TestReportErrors:
    def test_missing_file_raises_metrics_file_error(self, tmp_path):
        with pytest.raises(MetricsFileError, match="cannot read"):
            obs_report.load_events(str(tmp_path / "nope.jsonl"))

    def test_empty_file(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        with pytest.raises(MetricsFileError, match="no parseable events"):
            obs_report.report_file(str(p))

    def test_garbage_lines_skipped_with_count(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text('garbage{{{\n'
                     '{"event": "train", "iter": 1, "loss": 2.0}\n'
                     '{"event": "train", "it\n'
                     '[1, 2]\n')
        events, bad = obs_report.load_events(str(p))
        assert len(events) == 1 and bad == 3
        rep = obs_report.aggregate(events)
        rep["malformed_lines"] = bad
        assert "3 malformed" in obs_report.render(rep)

    def test_report_cli_one_line_error(self, tmp_path, capsys):
        from sparknet_tpu.cli import main
        rc = main(["report", str(tmp_path / "missing.jsonl")])
        assert rc == 2
        err = capsys.readouterr().err
        assert "sparknet report: error" in err
        assert "Traceback" not in err

    def test_monitor_cli_once(self, tmp_path, capsys):
        from sparknet_tpu.cli import main
        rc = main(["monitor", str(tmp_path / "missing.jsonl"), "--once"])
        assert rc == 2
        assert "sparknet monitor: error" in capsys.readouterr().err
        p = tmp_path / "m.jsonl"
        p.write_text(
            '{"event": "train", "iter": 3, "loss": 1.5}\n'
            'trunc{"a"\n'
            '{"event": "health", "kind": "straggler", "worker": 1,'
            ' "ratio": 2.0}\n')
        rc = main(["monitor", str(p), "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "iter 3" in out and "straggler" in out
        assert "1 bad lines" in out


# ------------------------------------------------------- monitor (unit)

class TestMonitorTail:
    def test_partial_trailing_line_buffered(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"event": "a"}\n{"ev')
        tail = _Tail(str(p))
        assert tail.poll() == ['{"event": "a"}']
        with open(p, "a") as f:
            f.write('ent": "b"}\n')
        assert tail.poll() == ['{"event": "b"}']
        assert tail.poll() == []

    def test_truncation_reopens_from_start(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"event": "a"}\n{"event": "b"}\n')
        tail = _Tail(str(p))
        tail.poll()
        p.write_text('{"event": "c"}\n')
        assert tail.poll() == ['{"event": "c"}']

    def test_state_folds_and_renders(self):
        st = MonitorState()
        st.update({"event": "round", "round": 3, "iter": 15, "loss": 2.1})
        st.update({"event": "divergence", "mean": 0.01, "max": 0.02,
                   "tau": 5, "worker_loss": [2.0, 2.2],
                   "top_layers": [["fc", 0.01]]})
        st.update({"event": "health", "kind": "straggler", "worker": 1,
                   "ratio": 3.0})
        st.update({"event": "health", "kind": "straggler", "worker": 1,
                   "ratio": 2.5})
        st.update({"event": "summary"})
        text = st.render("x.jsonl")
        assert "round 3" in text and "loss 2.1" in text
        assert "divergence: mean 0.01" in text and "tau=5" in text
        assert "worker 1 flagged 2x" in text
        assert "last alarm: [straggler]" in text
        assert "FINISHED" in text

    def test_monitor_file_missing_and_once(self, tmp_path):
        with pytest.raises(MetricsFileError):
            monitor_file(str(tmp_path / "none.jsonl"), once=True)
        p = tmp_path / "m.jsonl"
        p.write_text('{"event": "train", "iter": 1, "loss": 9.0}\n')
        got = []
        st = monitor_file(str(p), once=True, out=got.append)
        assert st.events == 1 and "iter 1" in got[0]

    def test_monitor_live_loop_ingests_on_tailer_thread(self, tmp_path):
        # the live view runs a background tailer (MonitorState is
        # lock-guarded — the discipline `sparknet lint` SPK201 checks);
        # events appended mid-run must land in the final state
        p = tmp_path / "m.jsonl"
        p.write_text('{"event": "train", "iter": 1, "loss": 9.0}\n')
        import threading

        def append_late():
            with open(p, "a") as f:
                f.write('{"event": "train", "iter": 2, "loss": 8.0}\n')
                f.write("garbage not json\n")
        t = threading.Timer(0.15, append_late)
        t.start()
        got = []
        st = monitor_file(str(p), interval=0.05, duration=0.6,
                          out=got.append, clear=False)
        t.join()
        assert st.events == 2 and st.bad_lines == 1
        assert st.iter == 2 and any("iter 2" in s for s in got)


# -------------------------------------------- device-cache gauge (sat)

class TestDeviceCacheGauge:
    def _make_db(self, path, n=24):
        from sparknet_tpu.data.lmdb import LMDBWriter
        from sparknet_tpu.data.datum import array_to_datum
        rs = np.random.RandomState(0)
        imgs = rs.randint(0, 256, (n, 3, 8, 8)).astype(np.uint8)
        with LMDBWriter(path) as w:
            for i in range(n):
                w.put(b"%08d" % i, array_to_datum(imgs[i], i % 4))

    def test_resident_cache_emits_hit_gauge(self, tmp_path):
        from sparknet_tpu.data.db_source import DatumBatchSource
        from sparknet_tpu.data.device_cache import (DeviceCachedSource,
                                                    maybe_device_cache)
        self._make_db(str(tmp_path / "db"))
        ms, buf = sink()
        src = DatumBatchSource(str(tmp_path / "db"), 8,
                               device_transform=True)
        cached = maybe_device_cache(src, metrics=ms)
        assert isinstance(cached, DeviceCachedSource)
        it = iter(cached)
        for _ in range(3):
            next(it)
        cached.close()
        evs = [e for e in events_of(buf) if e["event"] == "device_cache"]
        assert evs[0]["resident"] is True and evs[0]["records"] == 24
        assert evs[-1]["hits"] == 3 and evs[-1]["hit_rate"] == 1.0
        assert evs[-1]["misses"] == 0

    def test_refused_promotion_logs_all_miss_gauge(self, tmp_path):
        from sparknet_tpu.data.db_source import DatumBatchSource
        from sparknet_tpu.data.device_cache import maybe_device_cache
        self._make_db(str(tmp_path / "db"))
        ms, buf = sink()
        src = DatumBatchSource(str(tmp_path / "db"), 8,
                               device_transform=True)
        assert maybe_device_cache(src, budget_mb=1e-6, metrics=ms) is src
        ev = [e for e in events_of(buf) if e["event"] == "device_cache"][0]
        assert ev["resident"] is False and ev["reason"] == "over_budget"
        assert ev["hits"] == 0 and ev["hit_rate"] == 0.0
