"""Data subsystem tests + the CIFAR end-to-end smoke/training tests.

Mirrors reference CifarSpec.scala (random net scores near chance on CIFAR,
:92 asserts 70-130% of 10x chance) and MinibatchSamplerSpec.scala (window
sampling semantics), using synthetic CIFAR-format files — then goes further
than the reference: trains the full CIFAR10_full net to above-chance
accuracy in-process.
"""

import numpy as np
import pytest
import jax

from sparknet_tpu.data import (CifarDataset, read_batch_file,
                               write_batch_file, MinibatchSampler,
                               class_gaussian_images, batch_stream)
from sparknet_tpu.models import cifar10_full
from sparknet_tpu.proto import Message
from sparknet_tpu.solver import Solver


@pytest.fixture(scope="module")
def cifar_dir(tmp_path_factory):
    """Synthetic CIFAR-10-format directory: 5 train batches + test batch,
    with class-dependent image content so nets can learn."""
    d = tmp_path_factory.mktemp("cifar")
    rs = np.random.RandomState(0)
    protos = rs.randint(0, 255, size=(10, 3, 32, 32)).astype(np.float32)
    def gen(n, seed):
        r = np.random.RandomState(seed)
        labels = r.randint(0, 10, n)
        noise = r.randint(-40, 40, size=(n, 3, 32, 32))
        images = np.clip(0.7 * protos[labels] + noise, 0, 255).astype(np.uint8)
        return images, labels
    for i in range(1, 6):
        imgs, labs = gen(400, i)
        write_batch_file(str(d / f"data_batch_{i}.bin"), imgs, labs)
    imgs, labs = gen(400, 99)
    write_batch_file(str(d / "test_batch.bin"), imgs, labs)
    return str(d)


class TestCifarLoader:
    def test_batch_file_roundtrip(self, tmp_path):
        imgs = np.random.RandomState(0).randint(
            0, 256, size=(10, 3, 32, 32)).astype(np.uint8)
        labs = np.arange(10) % 10
        p = str(tmp_path / "b.bin")
        write_batch_file(p, imgs, labs)
        ri, rl = read_batch_file(p)
        np.testing.assert_array_equal(ri, imgs)
        np.testing.assert_array_equal(rl, labs)

    def test_dataset_load(self, cifar_dir):
        ds = CifarDataset(cifar_dir, seed=0)
        assert ds.train_images.shape == (2000, 3, 32, 32)
        assert ds.test_images.shape == (400, 3, 32, 32)
        assert ds.mean_image.shape == (3, 32, 32)
        np.testing.assert_allclose(
            ds.mean_image, ds.train_images.astype(np.float64).mean(0),
            atol=1e-3)

    def test_minibatches_drop_ragged(self, cifar_dir):
        ds = CifarDataset(cifar_dir, seed=0)
        batches = list(ds.minibatches(300, train=False))
        assert len(batches) == 1  # 400 // 300
        assert batches[0]["data"].shape == (300, 3, 32, 32)
        # mean-subtracted data is roughly centered
        assert abs(batches[0]["data"].mean()) < 20


class TestMinibatchSampler:
    def test_contiguous_window(self):
        batches = [{"i": i} for i in range(10)]
        rng = np.random.RandomState(3)
        s = MinibatchSampler(batches, 10, 4, rng=rng)
        got = [b["i"] for b in s]
        assert len(got) == 4
        assert got == list(range(got[0], got[0] + 4))
        assert 0 <= got[0] <= 6

    def test_full_window(self):
        batches = [{"i": i} for i in range(5)]
        s = MinibatchSampler(batches, 5, 5, rng=np.random.RandomState(0))
        assert [b["i"] for b in s] == [0, 1, 2, 3, 4]

    def test_short_stream_raises_clear_error(self):
        """A stream shorter than total_num_batches must not surface as
        a bare StopIteration (silently-short window / PEP 479
        RuntimeError in generators) — it names expected vs actual."""
        batches = [{"i": i} for i in range(3)]       # lies: claims 10
        s = MinibatchSampler(batches, 10, 4, rng=np.random.RandomState(3))
        assert s.start + 4 > 3                       # window needs more
        with pytest.raises(ValueError) as ei:
            list(s)
        msg = str(ei.value)
        assert "exhausted after 3 batches" in msg
        assert "total_num_batches=10" in msg

    def test_short_stream_error_inside_generator(self):
        """Inside a generator (the prefetch path), the old bare
        StopIteration would have become an opaque RuntimeError."""
        def feed():
            s = MinibatchSampler(iter([{"i": 0}]), 8, 3,
                                 rng=np.random.RandomState(0))
            for b in s:
                yield b
        with pytest.raises(ValueError, match="exhausted"):
            list(feed())


def make_cifar_solver(log_fn=None, **overrides):
    # cifar10_full_solver.prototxt schedule, shrunk for test runtime
    kw = dict(base_lr=0.001, lr_policy="fixed", momentum=0.9,
              weight_decay=0.004, random_seed=2, display=0)
    kw.update(overrides)
    sp = Message("SolverParameter", **kw)
    return Solver(sp, net_param=cifar10_full(batch_size=50), log_fn=log_fn)


class TestCifarEndToEnd:
    def test_chance_accuracy_random_net(self, cifar_dir):
        """Reference CifarSpec.scala:92: an untrained net must score within
        70-130% of chance x 10 on CIFAR."""
        ds = CifarDataset(cifar_dir, seed=0)
        s = make_cifar_solver()
        scores = s.test(iter(list(ds.minibatches(50, train=False))),
                        num_iters=8)
        acc = float(scores["accuracy"])
        assert 0.07 <= acc <= 0.13, acc

    def test_training_beats_chance(self, cifar_dir):
        """The round-1 'aha': DSL-built CIFAR net + real solver schedule
        learns synthetic CIFAR far past chance inside the test suite —
        a closed training loop the reference could only run on a cluster."""
        ds = CifarDataset(cifar_dir, seed=0)
        s = make_cifar_solver()
        stream = batch_stream(
            (ds.train_images.astype(np.float32) - ds.mean_image),
            ds.train_labels, 50, seed=1)
        for _ in range(120):
            s.train_step(next(stream))
        test_batches = list(ds.minibatches(50, train=False))
        acc = float(s.test(iter(test_batches), num_iters=8)["accuracy"])
        assert acc > 0.3, f"expected >0.3 accuracy (chance 0.1), got {acc}"


class TestSyntheticData:
    def test_class_gaussians_learnable_shapes(self):
        x, y = class_gaussian_images(100, seed=0)
        assert x.shape == (100, 3, 32, 32) and y.shape == (100,)

    def test_batch_stream_epochs(self):
        x, y = class_gaussian_images(10, seed=0)
        st = batch_stream(x, y, 4, loop=False)
        batches = list(st)
        assert len(batches) == 2  # ragged tail dropped
