"""Serving-tier tests (sparknet_tpu.serve, ISSUE 11).

The contract under test: `sparknet serve` answers over weights-only
checkpoint loads (the optimizer state is never needed and may be
gone), pads every batch to a power-of-two bucket whose logits match an
unpadded forward to fp32 roundoff, flushes partial batches at the
max-wait deadline, rejects with backpressure instead of queueing
unboundedly, hot-reloads newer snapshots without dropping in-flight
work, drains on SIGTERM with exit 0, and — because serving only ever
READS the checkpoint dir — leaves no partial state when killed.
"""

import json
import os
import re
import shutil
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.proto import Message
from sparknet_tpu.solver import Solver
from sparknet_tpu.resilience import load_manifest, manifest_path
from sparknet_tpu.resilience.checkpoint import load_model_only
from sparknet_tpu.serve import (Batcher, RejectedError, ServeEngine,
                                bucket_for, bucket_sizes)
from sparknet_tpu.serve.engine import deploy_net_param
from sparknet_tpu.serve.server import _parse_inputs

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _mlp_net():
    net = Message("NetParameter", name="mlp")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[16, 8])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[16])))
    net.add("layer", name="fc1", type="InnerProduct", bottom=["data"],
            top=["fc1"], inner_product_param=dict(
                num_output=16, weight_filler=dict(type="xavier")))
    net.add("layer", name="r1", type="ReLU", bottom=["fc1"], top=["fc1"])
    net.add("layer", name="fc2", type="InnerProduct", bottom=["fc1"],
            top=["fc2"], inner_product_param=dict(
                num_output=4, weight_filler=dict(type="xavier")))
    net.add("layer", name="loss", type="SoftmaxWithLoss",
            bottom=["fc2", "label"], top=["loss"])
    return net


def _train_and_snapshot(prefix, iters=3, seed=0):
    sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                 momentum=0.9, random_seed=7)
    s = Solver(sp, net_param=_mlp_net(), log_fn=None)
    rs = np.random.RandomState(seed)
    for _ in range(iters):
        s.train_step({"data": rs.randn(16, 8).astype(np.float32),
                      "label": rs.randint(0, 4, 16).astype(np.int32)})
    s.snapshot(prefix)
    return s


@pytest.fixture(scope="module")
def snap_dir(tmp_path_factory):
    """One trained snapshot shared read-only by the module; tests that
    mutate checkpoint state copy it first."""
    d = tmp_path_factory.mktemp("serve_snap")
    _train_and_snapshot(str(d / "snap"))
    return str(d)


def _copy_snap(snap_dir, tmp_path):
    d = tmp_path / "snap_copy"
    shutil.copytree(snap_dir, d)
    return str(d / "snap")


class _Sink:
    """Event recorder with the metrics .log signature."""

    def __init__(self):
        self.rows = []

    def log(self, event, **kw):
        self.rows.append(dict(kw, event=event))

    def events(self, name):
        return [r for r in self.rows if r["event"] == name]


# ------------------------------------------------------------- buckets ----

class TestBuckets:
    def test_bucket_sizes_powers_of_two(self):
        assert bucket_sizes(8) == [1, 2, 4, 8]
        assert bucket_sizes(1) == [1]
        # a non-power max is still included as the terminal bucket
        assert bucket_sizes(6) == [1, 2, 4, 6]

    def test_bucket_for(self):
        sizes = bucket_sizes(8)
        assert [bucket_for(n, sizes) for n in (1, 2, 3, 5, 8)] == \
            [1, 2, 4, 8, 8]
        assert bucket_for(9, sizes) is None

    def test_jit_cache_is_bounded_by_buckets(self, snap_dir):
        eng = ServeEngine(os.path.join(snap_dir, "snap"), max_batch=4)
        eng.load()
        for n in (1, 2, 3, 4, 1, 3, 2, 4):
            eng.forward({"data": np.zeros((n, 8), np.float32)})
        assert set(eng._fwd) <= set(eng.buckets)
        assert len(eng._fwd) == 3           # buckets 1, 2, 4 touched


class TestDeployNet:
    def test_loss_and_label_feed_dropped(self):
        dep = deploy_net_param(_mlp_net())
        names = [lp.name for lp in dep.layer]
        assert "loss" not in names
        assert "l" not in names             # orphaned label feed pruned
        assert "d" in names and "fc2" in [lp.name for lp in dep.layer]

    def test_deploy_shaped_net_passes_through(self):
        dep = deploy_net_param(_mlp_net())
        again = deploy_net_param(dep)
        assert [lp.name for lp in again.layer] == \
            [lp.name for lp in dep.layer]


# ---------------------------------------------------------- engine ----

def _reference_logits(model_path, xs):
    """Direct unpadded forward at exactly xs.shape[0] rows."""
    import jax
    from sparknet_tpu.proto import wire
    from sparknet_tpu.graph.compiler import CompiledNet, TEST
    blob = wire.load(model_path, "NetParameter")
    dep = deploy_net_param(blob.copy())
    net = CompiledNet(dep, TEST,
                      feed_shapes={"data": (xs.shape[0], 8)})
    params, state = net.init(jax.random.PRNGKey(0))
    params, state = net.load_netproto(blob, params, state)
    blobs, _ = net.apply(params, state, {"data": xs}, train=False)
    return np.asarray(blobs["fc2"])


class TestEngineParity:
    def test_padded_logits_match_direct_forward(self, snap_dir):
        """Acceptance: across every bucket, padded serving logits equal
        a direct unpadded forward to fp32 roundoff."""
        prefix = os.path.join(snap_dir, "snap")
        eng = ServeEngine(prefix, max_batch=8)
        entry = eng.load()
        model_path = os.path.join(snap_dir, entry["model"])
        rs = np.random.RandomState(3)
        for n in (1, 2, 3, 4, 5, 8):
            xs = rs.randn(n, 8).astype(np.float32)
            out, bucket = eng.forward({"data": xs})
            assert bucket == bucket_for(n, eng.buckets)
            assert out["fc2"].shape == (n, 4)
            np.testing.assert_allclose(
                out["fc2"], _reference_logits(model_path, xs),
                rtol=1e-5, atol=1e-6)

    def test_oversize_batch_rejected(self, snap_dir):
        eng = ServeEngine(os.path.join(snap_dir, "snap"), max_batch=2)
        eng.load()
        with pytest.raises(ValueError, match="max_batch"):
            eng.forward({"data": np.zeros((3, 8), np.float32)})

    def test_feed_shapes_are_per_sample(self, snap_dir):
        eng = ServeEngine(os.path.join(snap_dir, "snap"), max_batch=2)
        eng.load()
        assert eng.feed_shapes() == {"data": (8,)}   # label feed pruned


# ------------------------------------------------------- load_model_only ----

class TestLoadModelOnly:
    def test_loads_without_solverstate(self, snap_dir, tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        d = os.path.dirname(prefix)
        for f in os.listdir(d):
            if ".solverstate" in f:
                os.remove(os.path.join(d, f))
        path, entry = load_model_only(prefix)
        assert os.path.exists(path)
        assert entry["iter"] == 3
        eng = ServeEngine(prefix)
        eng.load()                       # weights-only: still servable
        eng.forward({"data": np.zeros((1, 8), np.float32)})

    def test_missing_manifest_names_it(self, tmp_path):
        prefix = str(tmp_path / "nosuch")
        with pytest.raises(ValueError) as ei:
            load_model_only(prefix)
        assert manifest_path(prefix) in str(ei.value)

    def test_torn_manifest_names_it(self, snap_dir, tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        with open(manifest_path(prefix), "w") as f:
            f.write('{"version": 1, "latest": {"it')   # torn mid-write
        with pytest.raises(ValueError) as ei:
            load_model_only(prefix)
        assert manifest_path(prefix) in str(ei.value)

    def test_corrupt_model_blob_rejected(self, snap_dir, tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        man = load_manifest(prefix)
        blob = os.path.join(os.path.dirname(prefix),
                            man["latest"]["model"])
        with open(blob, "r+b") as f:     # flip bytes: sha256 must fail
            f.seek(0)
            f.write(b"\xde\xad\xbe\xef")
        with pytest.raises(ValueError) as ei:
            load_model_only(prefix)
        assert manifest_path(prefix) in str(ei.value)
        assert "sha256" in str(ei.value)

    def test_falls_back_to_older_servable_snapshot(self, snap_dir,
                                                   tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        # grow the same manifest: restore and snapshot 2 more iters
        man = load_manifest(prefix)
        d = os.path.dirname(prefix)
        sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                     momentum=0.9, random_seed=7)
        sv = Solver(sp, net_param=_mlp_net(), log_fn=None)
        sv.restore(os.path.join(d, man["latest"]["state"]))
        rs = np.random.RandomState(9)
        for _ in range(2):
            sv.train_step({"data": rs.randn(16, 8).astype(np.float32),
                           "label": rs.randint(0, 4, 16).astype(np.int32)})
        sv.snapshot(prefix)
        man = load_manifest(prefix)
        assert man["latest"]["iter"] == 5
        # corrupt the newest blob: serving must fall back to iter 3
        with open(os.path.join(d, man["latest"]["model"]), "r+b") as f:
            f.seek(0)
            f.write(b"\xde\xad\xbe\xef")
        path, entry = load_model_only(prefix)
        assert entry["iter"] == 3
        assert os.path.exists(path)


# ---------------------------------------------------------- batcher ----

class TestBatcher:
    def test_deadline_flushes_partial_batch(self):
        b = Batcher(max_batch=8, max_wait_s=0.05, queue_limit=64)
        b.submit({"data": np.zeros((1, 8))}, n=1)
        t0 = time.perf_counter()
        reqs, _wait = b.next_batch(timeout=1.0)
        elapsed = time.perf_counter() - t0
        assert len(reqs) == 1            # flushed alone at the deadline
        assert elapsed < 0.8             # ... not at the full timeout

    def test_full_bucket_dispatches_before_deadline(self):
        b = Batcher(max_batch=4, max_wait_s=10.0, queue_limit=64)
        for _ in range(4):
            b.submit({"data": np.zeros((1, 8))}, n=1)
        t0 = time.perf_counter()
        reqs, _wait = b.next_batch(timeout=1.0)
        assert len(reqs) == 4
        assert time.perf_counter() - t0 < 1.0

    def test_backpressure_rejects_over_limit(self):
        sink = _Sink()
        b = Batcher(max_batch=4, max_wait_s=0.01, queue_limit=2,
                    metrics=sink)
        b.submit({"x": [0]}, n=1)
        b.submit({"x": [0]}, n=1)
        with pytest.raises(RejectedError) as ei:
            b.submit({"x": [0]}, n=1)
        assert ei.value.reason == "queue_full"
        assert ei.value.queue_depth == 2
        assert [r["reason"] for r in sink.events("serve_reject")] == \
            ["queue_full"]

    def test_draining_rejects_new_work(self):
        b = Batcher(max_batch=4, queue_limit=8)
        b.submit({"x": [0]}, n=1)
        b.close()
        assert b.draining()
        with pytest.raises(RejectedError) as ei:
            b.submit({"x": [0]}, n=1)
        assert ei.value.reason == "replica_draining"
        # queued work is still drainable after close
        reqs, _ = b.next_batch(timeout=0.2)
        assert len(reqs) == 1
        assert b.pending() == 0


class TestParseInputs:
    FEEDS = {"data": (8,)}

    def test_bare_list_is_first_feed(self):
        arrays, n = _parse_inputs([[0.0] * 8, [1.0] * 8], self.FEEDS)
        assert n == 2 and arrays["data"].shape == (2, 8)

    def test_single_sample_gets_batch_dim(self):
        arrays, n = _parse_inputs({"data": [0.0] * 8}, self.FEEDS)
        assert n == 1 and arrays["data"].shape == (1, 8)

    def test_shape_mismatch_rejected(self):
        with pytest.raises(ValueError, match="per-sample shape"):
            _parse_inputs({"data": [[0.0] * 7]}, self.FEEDS)

    def test_unknown_feed_rejected(self):
        with pytest.raises(ValueError, match="unknown feed"):
            _parse_inputs({"bogus": [[0.0] * 8]}, self.FEEDS)


# -------------------------------------------------------- hot reload ----

class TestHotReload:
    def test_reload_without_dropping_in_flight(self, snap_dir,
                                               tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        eng = ServeEngine(prefix, max_batch=2, log_fn=None)
        eng.load()
        assert eng.status()["iter"] == 3
        errors = []
        stop = threading.Event()
        xs = np.random.RandomState(0).randn(2, 8).astype(np.float32)

        def hammer():
            while not stop.is_set():
                try:
                    out, _ = eng.forward({"data": xs})
                    assert out["fc2"].shape == (2, 4)
                except Exception as e:      # surfaced on the main side
                    errors.append(e)
                    return

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            # training advances the SAME prefix to iter 5 while serving
            d = os.path.dirname(prefix)
            man = load_manifest(prefix)
            sp = Message("SolverParameter", base_lr=0.1,
                         lr_policy="fixed", momentum=0.9, random_seed=7)
            sv = Solver(sp, net_param=_mlp_net(), log_fn=None)
            sv.restore(os.path.join(d, man["latest"]["state"]))
            rs = np.random.RandomState(5)
            for _ in range(2):
                sv.train_step(
                    {"data": rs.randn(16, 8).astype(np.float32),
                     "label": rs.randint(0, 4, 16).astype(np.int32)})
            sv.snapshot(prefix)
            entry = eng.poll_reload()
            assert entry is not None and entry["iter"] == 5
            assert eng.poll_reload() is None     # idempotent
            out, _ = eng.forward({"data": xs})
            assert out["fc2"].shape == (2, 4)
        finally:
            stop.set()
            t.join(timeout=10)
        assert errors == []
        st = eng.status()
        assert st["iter"] == 5 and st["reloads"] == 1

    def test_torn_manifest_keeps_old_weights(self, snap_dir, tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        eng = ServeEngine(prefix, max_batch=2, log_fn=None)
        eng.load()
        before, _ = eng.forward(
            {"data": np.ones((1, 8), np.float32)})
        with open(manifest_path(prefix), "w") as f:
            f.write('{"version": 1, "latest"')       # torn mid-swap
        assert eng.poll_reload() is None
        after, _ = eng.forward({"data": np.ones((1, 8), np.float32)})
        np.testing.assert_array_equal(before["fc2"], after["fc2"])
        assert eng.status()["iter"] == 3             # old entry kept


# ----------------------------------------------------- process contract ----

def _serve_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _start_server(prefix, metrics_path, max_batch=2):
    p = subprocess.Popen(
        [sys.executable, "-m", "sparknet_tpu", "serve",
         "--prefix", prefix, "--port", "0", "--no_warmup",
         "--max_batch", str(max_batch), "--metrics", metrics_path],
        cwd=REPO, env=_serve_env(), text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    url, lines = None, []
    deadline = time.time() + 120
    while time.time() < deadline:
        line = p.stdout.readline()
        if not line:
            break
        lines.append(line)
        m = re.search(r"listening on (http://\S+)", line)
        if m:
            url = m.group(1)
            break
    if url is None:
        p.kill()
        raise AssertionError("server never announced: " + "".join(lines))
    # keep the pipe drained so the server never blocks on stdout
    drain = threading.Thread(
        target=lambda: lines.extend(iter(p.stdout.readline, "")),
        daemon=True)
    drain.start()
    return p, url, lines


def _predict(url, rows=1, timeout=30.0):
    from urllib.request import urlopen, Request
    body = json.dumps(
        np.zeros((rows, 8)).tolist()).encode("utf-8")
    req = Request(url + "/predict", data=body,
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestProcessContract:
    def test_sigterm_drains_and_exits_zero(self, snap_dir, tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        mfile = str(tmp_path / "serve.jsonl")
        p, url, lines = _start_server(prefix, mfile)
        try:
            code, body = _predict(url, rows=2)
            assert code == 200
            assert np.asarray(body["outputs"]["fc2"]).shape == (2, 4)
            assert body["iter"] == 3
            p.send_signal(signal.SIGTERM)
            rc = p.wait(timeout=60)
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        assert rc == 0
        out = "".join(lines)
        assert "drained cleanly" in out
        rows = [json.loads(ln) for ln in open(mfile) if ln.strip()]
        summaries = [r for r in rows if r.get("event") == "serve_summary"]
        assert len(summaries) == 1 and summaries[0]["drained"] is True
        assert summaries[0]["requests"] == 1

    def test_unservable_checkpoint_exits_3(self, tmp_path):
        p = subprocess.run(
            [sys.executable, "-m", "sparknet_tpu", "serve",
             "--prefix", str(tmp_path / "nothing"), "--port", "0"],
            cwd=REPO, env=_serve_env(), text=True, timeout=120,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        assert p.returncode == 3            # EXIT_RECOVERY_ABORT
        assert "manifest" in p.stdout

    def test_sigkill_under_load_leaves_no_partial_state(self, snap_dir,
                                                        tmp_path):
        prefix = _copy_snap(snap_dir, tmp_path)
        mfile = str(tmp_path / "serve.jsonl")
        p, url, _lines = _start_server(prefix, mfile)
        stop = threading.Event()

        def fire():
            while not stop.is_set():
                try:
                    _predict(url, rows=1, timeout=5.0)
                except Exception:
                    return                   # server died mid-request
        threads = [threading.Thread(target=fire, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.5)                  # requests in flight
            p.kill()                         # SIGKILL: no drain
            p.wait(timeout=30)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        # serving only reads the checkpoint dir: manifest and blobs
        # stay valid, no temp files, and a fresh engine serves
        d = os.path.dirname(prefix)
        assert not [f for f in os.listdir(d) if ".tmp." in f]
        assert load_manifest(prefix)["latest"]["iter"] == 3
        eng = ServeEngine(prefix, log_fn=None)
        eng.load()
        out, _ = eng.forward({"data": np.zeros((1, 8), np.float32)})
        assert out["fc2"].shape == (1, 4)
