"""Fleet-scale chaos simulation (sparknet_tpu/sim/): the SimClock /
MemDir halves of the Clock/Dir seam, monotonic lease freshness under
wall-clock jumps, the fail_rate/fail_corr chaos grammar, table-driven
lease boundary semantics against the REAL HeartbeatCoordinator and
ElasticPolicy, FleetSim end-to-end (scheduled deaths, repair, quorum
loss, consensus transports), replay validation against a real
multi-coordinator run, the sweep grid driver, and report/monitor
rendering of a simulated metrics stream."""

import time

import numpy as np
import pytest

from conftest import REFERENCE  # noqa: F401  (conftest sets the cpu env)

from sparknet_tpu.resilience.chaos import ChaosMonkey
from sparknet_tpu.resilience.elastic import ElasticPolicy
from sparknet_tpu.resilience.heartbeat import HeartbeatCoordinator
from sparknet_tpu.sim import FleetSim, MemDir, SimClock
from sparknet_tpu.sim.replay import (SequenceSink, record_real,
                                     replay_sim)
from sparknet_tpu.sim.sweep import (parse_grid, render_table, run_cell,
                                    run_sweep)


class _Sink:
    def __init__(self):
        self.events = []

    def log(self, event, **fields):
        self.events.append(dict(fields, event=event))

    def kinds(self):
        return [e["event"] for e in self.events]

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]


def _quiet(*a, **k):
    pass


def _sim_coord(clock, dirops, host, n, interval=0.2, lease=1.0, **kw):
    return HeartbeatCoordinator(dirops.root, host=host, n_hosts=n,
                                interval_s=interval, lease_s=lease,
                                log_fn=_quiet, clock=clock,
                                dirops=dirops, **kw)


# ------------------------------------------------------------ SimClock ----
class TestSimClock:
    def test_sleep_advances_monotonic_and_wall_together(self):
        c = SimClock()
        m0, w0 = c.monotonic(), c.time()
        c.sleep(2.5)
        assert c.monotonic() == pytest.approx(m0 + 2.5)
        assert c.time() == pytest.approx(w0 + 2.5)

    def test_events_fire_in_due_order_with_fifo_ties(self):
        c = SimClock()
        seen = []
        c.after(2.0, lambda: seen.append("b"))
        c.after(1.0, lambda: seen.append("a"))
        c.after(2.0, lambda: seen.append("c"))   # same due as "b"
        c.sleep(3.0)
        assert seen == ["a", "b", "c"]

    def test_events_scheduled_while_firing_still_fire(self):
        c = SimClock()
        seen = []

        def recurse():
            seen.append(c.monotonic())
            if len(seen) < 3:
                c.after(1.0, recurse)
        c.after(1.0, recurse)
        c.sleep(10.0)
        assert seen == pytest.approx([1.0, 2.0, 3.0])
        assert c.monotonic() == pytest.approx(10.0)

    def test_past_due_clamps_to_now(self):
        c = SimClock()
        c.sleep(5.0)
        seen = []
        c.at(1.0, lambda: seen.append(True))     # already in the past
        c.sleep(0.0)
        assert seen == [True]

    def test_jump_wall_moves_wall_but_never_monotonic(self):
        c = SimClock()
        c.sleep(1.0)
        m, w = c.monotonic(), c.time()
        c.jump_wall(-3600.0)
        assert c.monotonic() == m                # monotonic is immune
        assert c.time() == pytest.approx(w - 3600.0)
        c.jump_wall(+7200.0)
        assert c.time() == pytest.approx(w + 3600.0)

    def test_pending_counts_unfired_events(self):
        c = SimClock()
        c.after(1.0, lambda: None)
        c.after(2.0, lambda: None)
        assert c.pending() == 2
        c.sleep(1.5)
        assert c.pending() == 1


# -------------------------------------------------------------- MemDir ----
class TestMemDir:
    def test_json_roundtrip_and_mtime(self):
        clock = SimClock()
        d = MemDir(clock)
        d.write_json("hb-0.json", {"host": 0, "seq": 1})
        assert d.read_json("hb-0.json") == {"host": 0, "seq": 1}
        assert d.mtime("hb-0.json") == clock.time()
        clock.sleep(2.0)
        d.write_json("hb-0.json", {"host": 0, "seq": 2})
        assert d.mtime("hb-0.json") == clock.time()

    def test_glob_is_sorted_and_pattern_scoped(self):
        d = MemDir(SimClock())
        for name in ("hb-2.json", "hb-0.json", "round-0.part", "hb-1.json"):
            d.write_json(name, {})
        assert d.glob("hb-*.json") == ["hb-0.json", "hb-1.json",
                                       "hb-2.json"]
        assert d.glob("*.part") == ["round-0.part"]

    def test_npz_roundtrip_returns_copy(self):
        d = MemDir(SimClock())
        d.write_npz("x.npz", {"a": np.arange(4)})
        out = d.load_npz("x.npz")
        assert list(out["a"]) == [0, 1, 2, 3]
        out["b"] = 1                              # caller's copy only
        assert "b" not in d.load_npz("x.npz")

    def test_missing_and_remove(self):
        d = MemDir(SimClock())
        assert d.read_json("nope.json") is None
        assert d.load_npz("nope.npz") is None
        assert d.mtime("nope.json") is None
        assert not d.exists("nope.json")
        d.write_json("x.json", {})
        assert d.remove("x.json") and not d.exists("x.json")
        assert not d.remove("x.json")


# ------------------------------------- wall jumps never evict (bugfix) ----
class TestWallClockJumps:
    """Satellite regression: lease freshness and gate deadlines live on
    the monotonic clock, so NTP steps / suspend-resume wall jumps in
    EITHER direction must not expire (or resurrect) anyone."""

    @pytest.mark.parametrize("jump_s", [-3600.0, +3600.0],
                             ids=["backwards", "forwards"])
    def test_wall_jump_mid_run_evicts_nobody(self, jump_s):
        clock = SimClock()
        d = MemDir(clock)
        a = _sim_coord(clock, d, 0, 2)
        b = _sim_coord(clock, d, 1, 2)
        b.beat()
        a.view()                      # register the lease receipt
        clock.jump_wall(jump_s)
        clock.sleep(0.1)              # well inside the 1.0s lease
        alive, age = a.view()
        assert alive[1], f"wall jump {jump_s:+g}s expired a live lease"
        assert age[1] == pytest.approx(0.1)
        # the gate deadline is monotonic too: a bounded gate neither
        # hangs nor reports the leasing-but-unarrived peer dead
        res = a.gate(0, expect={1}, timeout=0.3)
        assert not res.dead
        assert res.wait_s == pytest.approx(0.3, abs=0.06)

    def test_ghost_lease_reads_old_on_first_sight(self):
        # first-ever sight seeds the age from the wall stamp: a record
        # that predates this process must NOT be granted a fresh lease
        clock = SimClock()
        d = MemDir(clock)
        b = _sim_coord(clock, d, 1, 2)
        b.beat()
        clock.sleep(10.0)             # 10x the lease, no re-lease
        a = _sim_coord(clock, d, 0, 2)
        alive, age = a.view()
        assert not alive[1]
        assert age[1] == pytest.approx(10.0)


# ----------------------------------------------- chaos failure grammar ----
class TestFailRateGrammar:
    def test_parse_round_trips_the_new_tokens(self):
        c = ChaosMonkey.parse("fail_rate=0.01,fail_seed=9,fail_corr=4",
                              log_fn=_quiet)
        assert (c.fail_rate, c.fail_seed, c.fail_corr) == (0.01, 9, 4)

    @pytest.mark.parametrize("spec", ["fail_rate=nope", "fail_seed=1.5x",
                                      "fail_rate=2.0", "fail_rat=0.1"])
    def test_bad_tokens_error_naming_the_token(self, spec):
        with pytest.raises(ValueError) as err:
            ChaosMonkey.parse(spec, log_fn=_quiet)
        assert spec.split(",")[0].split("=")[0].rstrip("e") \
            .rstrip("t")[:8] in str(err.value) or spec in str(err.value)

    def test_victim_timeline_is_deterministic_per_seed(self):
        a = ChaosMonkey.parse("fail_rate=0.2,fail_seed=7", log_fn=_quiet)
        b = ChaosMonkey.parse("fail_rate=0.2,fail_seed=7", log_fn=_quiet)
        seq_a = [a.fail_rate_victims(r, 64) for r in range(10)]
        seq_b = [b.fail_rate_victims(r, 64) for r in range(10)]
        assert seq_a == seq_b
        assert any(seq_a), "p=0.2 over 10 rounds x 64 hosts drew nothing"
        c = ChaosMonkey.parse("fail_rate=0.2,fail_seed=8", log_fn=_quiet)
        assert seq_a != [c.fail_rate_victims(r, 64) for r in range(10)]

    def test_victims_are_newly_dead_only_until_revived(self):
        # the process reports deltas: an already-down host cannot die
        # twice, and only a revive re-arms it
        c = ChaosMonkey.parse("fail_rate=1.0", log_fn=_quiet)
        assert c.fail_rate_victims(0, 4) == [0, 1, 2, 3]
        assert c.fail_rate_victims(1, 4) == []
        c.revive_host(2)
        assert c.fail_rate_victims(2, 4) == [2]

    def test_fail_rate_one_kills_everyone(self):
        c = ChaosMonkey.parse("fail_rate=1.0", log_fn=_quiet)
        assert c.fail_rate_victims(0, 5) == [0, 1, 2, 3, 4]

    def test_fail_corr_kills_whole_domains(self):
        c = ChaosMonkey.parse("fail_rate=0.5,fail_seed=3,fail_corr=4",
                              log_fn=_quiet)
        hit = False
        for r in range(20):
            victims = set(c.fail_rate_victims(r, 16))
            hit = hit or bool(victims)
            for v in victims:
                dom = v // 4
                assert set(range(dom * 4, dom * 4 + 4)) <= victims, \
                    f"round {r}: domain {dom} died partially: {victims}"
        assert hit, "p=0.5 over 20 rounds x 4 domains drew no failures"

    def test_dead_hosts_carries_victims_and_emits_the_event(self):
        sink = _Sink()
        c = ChaosMonkey.parse("fail_rate=1.0", metrics=sink,
                              log_fn=_quiet)
        assert set(c.dead_hosts(0, 3)) == {0, 1, 2}
        assert any(e.get("kind") == "fail_rate" for e in sink.of("chaos"))
        c.revive_host(1)
        assert set(c.dead_hosts(1, 3)) == {1}    # p=1 re-kills it


# ------------------------------------------------- lease boundaries -------
#: (advance after the lease receipt, alive expected) — the lease is
#: inclusive at exactly lease_s (age <= lease_s), dead just beyond
LEASE_EDGE = [(0.5, True), (0.999, True), (1.0, True), (1.001, False),
              (3.0, False)]


class TestLeaseBoundaries:
    @pytest.mark.parametrize("advance,alive_expected", LEASE_EDGE)
    def test_beat_exactly_at_lease_expiry(self, advance, alive_expected):
        clock = SimClock()
        d = MemDir(clock)
        a = _sim_coord(clock, d, 0, 2, lease=1.0)
        b = _sim_coord(clock, d, 1, 2, lease=1.0)
        b.beat()
        a.view()                      # receipt at age 0
        clock.sleep(advance)
        alive, age = a.view()
        assert bool(alive[1]) is alive_expected
        assert age[1] == pytest.approx(advance)

    @pytest.mark.parametrize("arrive_at,arrives", [
        (0.1, True),                  # early
        (0.48, True),                 # the final poll before deadline
        (0.60, False),                # after the deadline: straggler
    ])
    def test_gate_peer_arriving_on_final_poll(self, arrive_at, arrives):
        clock = SimClock()
        d = MemDir(clock)
        a = _sim_coord(clock, d, 0, 2, interval=0.2, lease=5.0)
        b = _sim_coord(clock, d, 1, 2, interval=0.2, lease=5.0)
        b.beat()
        a.view()
        clock.after(arrive_at, lambda: b.announce_round(3))
        res = a.gate(3, expect={1}, timeout=0.5)
        assert (1 in res.arrived) is arrives
        # a leasing-but-late peer is NEITHER arrived nor dead — the
        # caller's straggler alarm decides, not an eviction
        assert not res.dead

    @pytest.mark.parametrize("readmit_after", [1, 2, 4])
    def test_readmit_cooldown_with_evict_after_one(self, readmit_after):
        sink = _Sink()
        pol = ElasticPolicy(n_workers=4, quorum=1, evict_after=1,
                            readmit_after=readmit_after, metrics=sink,
                            log_fn=_quiet, unit="host")
        pol.evict(2, 3, "lease_expired")
        for r in range(3, 3 + readmit_after + 1):
            pol.observe_round(r)
        back = [e["round"] for e in sink.of("readmission")
                if e.get("worker") == 2]
        assert back == [3 + readmit_after]


# ------------------------------------------------------------ FleetSim ----
class TestFleetSim:
    def test_same_seed_same_timeline(self):
        kw = dict(hosts=6, rounds=8, interval_s=0.25, lease_s=1.0,
                  round_s=0.3, quorum=1, consensus="none",
                  chaos="fail_rate=0.05,fail_seed=11", recover_after=2,
                  seed=4)
        assert FleetSim(**kw).run() == FleetSim(**kw).run()

    def test_scheduled_death_evicts_via_lease_expiry(self):
        sink = _Sink()
        s = FleetSim(hosts=4, rounds=8, interval_s=0.25, lease_s=1.0,
                     round_s=0.3, consensus="none", deaths={2: 3},
                     metrics=sink)
        out = s.run()
        ev = [(e["host"], e["round"]) for e in sink.of("host_evicted")]
        assert ev and ev[0][0] == 2
        assert all(e["reason"] == "lease_expired"
                   for e in sink.of("host_evicted"))
        assert out["live_final"] == 3 and not out["quorum_lost"]

    def test_recover_after_readmits_the_dead(self):
        s = FleetSim(hosts=4, rounds=12, interval_s=0.25, lease_s=1.0,
                     round_s=0.3, consensus="none", deaths={2: 3},
                     recover_after=3)
        out = s.run()
        assert out["admissions"] >= 1
        assert out["live_final"] == 4

    def test_churn_signature_evict_readmit_reevict(self):
        # the cooldown-readmission churn loop: a host that stays dead
        # is readmitted by the cooldown and re-evicted by its still-
        # lapsed lease — the hard sequencing case
        sink = SequenceSink()
        FleetSim(hosts=4, rounds=12, interval_s=0.25, lease_s=1.0,
                 round_s=0.3, consensus="none", deaths={2: 4},
                 readmit_after=3, jitter=0.0, metrics=sink).run()
        kinds = [e[0] for e in sink.sequence if e[1] == 2]
        assert kinds[:3] == ["host_evicted", "readmission",
                             "host_evicted"]

    def test_quorum_loss_halts_the_fleet(self):
        s = FleetSim(hosts=3, rounds=10, interval_s=0.25, lease_s=1.0,
                     round_s=0.3, quorum=3, consensus="none",
                     deaths={1: 2})
        out = s.run()
        assert out["quorum_lost"]
        assert out["rounds"] < 10

    def test_sync_consensus_converges_surrogate_leaves(self):
        s = FleetSim(hosts=4, rounds=5, interval_s=0.25, lease_s=1.5,
                     round_s=0.3, consensus="sync", jitter=0.0)
        out = s.run()
        assert out["consensus"] == "sync" and not out["quorum_lost"]
        for leaf in s.leaves[1:]:
            np.testing.assert_allclose(leaf, s.leaves[0])

    def test_async_consensus_with_staleness_runs(self):
        out = FleetSim(hosts=4, rounds=8, interval_s=0.25, lease_s=1.5,
                       round_s=0.3, consensus="async",
                       staleness=2).run()
        assert out["consensus"] == "async"
        assert out["staleness"] == 2 and not out["quorum_lost"]

    def test_auto_consensus_drops_transport_at_scale(self):
        assert FleetSim(hosts=4).consensus == "sync"
        assert FleetSim(hosts=4, staleness=2).consensus == "async"
        assert FleetSim(hosts=64).consensus == "none"

    def test_sim_event_matches_the_closed_schema(self):
        from sparknet_tpu.obs.event_schema import EVENTS
        sink = _Sink()
        FleetSim(hosts=4, rounds=4, interval_s=0.25, lease_s=1.0,
                 round_s=0.3, consensus="none", metrics=sink).run()
        evs = sink.of("sim")
        assert len(evs) == 4
        spec = EVENTS["sim"]
        assert not spec["open"]
        for e in evs:
            assert sorted(k for k in e if k != "event") == \
                sorted(spec["fields"])

    def test_midsize_fleet_stays_cheap_on_cpu(self):
        # the scaled-down cousin of the 1000x200 acceptance cell (kept
        # tier-1-fast); the full cell runs under @slow and in smoke
        t0 = time.time()
        out = FleetSim(hosts=300, rounds=40, interval_s=0.2,
                       lease_s=0.6, round_s=0.15, quorum=200,
                       consensus="none", recover_after=5,
                       chaos="fail_rate=0.0005,fail_seed=7").run()
        assert time.time() - t0 < 20.0
        assert not out["quorum_lost"]
        assert out["rounds"] == 40

    @pytest.mark.slow
    def test_thousand_host_cell_under_budget(self):
        t0 = time.time()
        out = FleetSim(hosts=1000, rounds=200, interval_s=0.2,
                       lease_s=0.6, round_s=0.15, quorum=800,
                       consensus="none", recover_after=5,
                       chaos="fail_rate=0.0002,fail_seed=7").run()
        assert time.time() - t0 < 60.0
        assert out["rounds"] == 200 and not out["quorum_lost"]


# ------------------------------------------------------ replay gate -------
class TestReplayValidation:
    def test_sim_reproduces_a_real_run_exactly(self, tmp_path):
        rec = record_real(str(tmp_path), hosts=3, rounds=7,
                          kill_round=2, interval_s=0.1, lease_s=0.5,
                          round_s=0.12, readmit_after=3)
        assert rec["sequence"], "the real run recorded no membership"
        match, real_seq, sim_seq = replay_sim(rec)
        assert match, f"replay diverged:\n real {real_seq}\n sim {sim_seq}"


# ------------------------------------------------------------- sweeps -----
class TestSweep:
    def test_grid_is_the_cartesian_product_in_spec_order(self):
        cells = parse_grid("hosts=2:4,lease_s=1.0:2.0,quorum=1")
        assert cells == [
            {"hosts": 2, "lease_s": 1.0, "quorum": 1},
            {"hosts": 2, "lease_s": 2.0, "quorum": 1},
            {"hosts": 4, "lease_s": 1.0, "quorum": 1},
            {"hosts": 4, "lease_s": 2.0, "quorum": 1},
        ]

    @pytest.mark.parametrize("spec,needle", [
        ("hosst=2", "hosst"),                 # unknown axis
        ("hosts=two", "two"),                 # unconvertible value
        ("hosts", "hosts"),                   # no '='
    ])
    def test_bad_specs_error_naming_the_token(self, spec, needle):
        with pytest.raises(ValueError) as err:
            parse_grid(spec)
        assert needle in str(err.value)
        assert "valid axes" in str(err.value)

    def test_run_cell_routes_chaos_axes_and_echoes_the_cell(self):
        cell = {"hosts": 4, "rounds": 3, "interval_s": 0.25,
                "lease_s": 1.0, "round_s": 0.3, "fail_rate": 0.0,
                "fail_seed": 1}
        out = run_cell(cell)
        assert out["cell"] == cell
        assert out["hosts"] == 4 and out["rounds"] == 3
        assert "real_s" in out

    def test_budget_stops_early_and_says_so(self):
        lines = []
        cells = parse_grid("hosts=2,rounds=2,round_s=0.2,"
                           "lease_s=1.0") * 3
        out = run_sweep(cells, log_fn=lambda m: lines.append(m),
                        budget_s=0.0)
        assert out == []
        assert any("NOT run" in l for l in lines)

    def test_render_table_has_the_tuning_columns(self):
        cells = parse_grid("hosts=2,rounds=2,round_s=0.2,lease_s=1.0,"
                           "fail_rate=0.0")
        txt = render_table(run_sweep(cells))
        for col in ("hosts", "lease", "wait_p95", "wait_max", "qlost",
                    "chaos/tau/s"):
            assert col in txt.splitlines()[0]
        assert len(txt.splitlines()) == 2


# ---------------------------------------------- report / monitor ----------
class TestSimObservability:
    def _events(self):
        sink = _Sink()
        FleetSim(hosts=4, rounds=6, interval_s=0.25, lease_s=1.0,
                 round_s=0.3, consensus="none", deaths={2: 2},
                 recover_after=2, metrics=sink).run()
        return sink.events

    def test_report_aggregates_and_renders_the_sim_section(self):
        from sparknet_tpu.obs import report as obs_report
        rep = obs_report.aggregate(self._events())
        sim = rep["simulation"]
        assert sim["hosts"] == 4 and sim["rounds"] == 6
        assert sim["evictions"] >= 1 and sim["admissions"] >= 1
        txt = obs_report.render(rep)
        assert "fleet simulation" in txt
        assert "4 virtual hosts x 6 rounds" in txt

    def test_monitor_renders_the_live_sim_line(self):
        from sparknet_tpu.obs.monitor import MonitorState
        st = MonitorState()
        for e in self._events():
            ev = dict(e)
            st.update(dict(ev, event=ev.pop("event")))
        txt = st.render("mem:fleet")
        assert "sim: 4 hosts" in txt
        assert "round 5" in txt
