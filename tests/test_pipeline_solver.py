"""PipelineLMSolver: the GPipe trunk as a solver strategy.

The VERDICT round-2 gap: pipeline_apply was tested but unreachable from
any solver. These tests assert the integrated path — a 4-stage pipelined
transformer LM step produces the SAME loss and updated params as the
unpipelined zoo.transformer_lm on a single device with identical param
values and batch (gradient equivalence through scan + ppermute), plus
snapshot/restore and the divisibility guards.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sparknet_tpu.models import zoo
from sparknet_tpu.proto import Message
from sparknet_tpu.parallel import PipelineLMSolver, make_mesh
from sparknet_tpu.solver.solver import Solver

LM = dict(vocab_size=64, seq_len=32, batch_size=8, d_model=32, num_heads=4,
          flash=False)
L = 4


def _mk_pipeline(stages=4, tau_seed=3, **kw):
    sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                 momentum=0.9, display=0, random_seed=tau_seed)
    mesh = make_mesh({"pipe": stages})
    return PipelineLMSolver(sp, mesh=mesh, num_layers=L,
                            num_microbatches=4, **LM, **kw)


def _mk_reference(psolver, tau_seed=3):
    """zoo.transformer_lm Solver with params COPIED from the pipeline
    solver (prefix/blocks/suffix layout -> per-block layer names)."""
    sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                 momentum=0.9, display=0, random_seed=tau_seed)
    s = Solver(sp, net_param=zoo.transformer_lm(num_layers=L, **LM))

    def cp(x):
        return jnp.asarray(np.asarray(x))   # break donation aliasing

    params = {ln: list(blobs) for ln, blobs in s.params.items()}
    params["tok_embed"] = [cp(x) for x in psolver.params["prefix/tok_embed"]]
    params["pos_embed"] = [cp(x) for x in psolver.params["prefix/pos_embed"]]
    for zname, pname in (("ln_f", "suffix/ln_f"),
                         ("lm_head", "suffix/lm_head")):
        params[zname] = [cp(x) for x in psolver.params[pname]]
    for i in range(L):
        for ln in ("ln1", "attn", "ln2", "ffn1", "ffn2"):
            key = f"blocks/{ln}"
            if key in psolver.params:
                params[f"block{i}/{ln}"] = [cp(leaf[i])
                                            for leaf in psolver.params[key]]
    s.params = params
    return s


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {"data": rs.randint(0, 64, (8, 32)).astype(np.int32),
            "label": rs.randint(0, 64, (8, 32)).astype(np.int32)}


def test_gradient_equivalence_vs_single_device():
    ps = _mk_pipeline(stages=4)
    ref = _mk_reference(ps)
    batch = _batch()
    l_ref = float(ref.train_step(batch))
    l_pipe = float(ps.train_step(batch))
    assert l_ref == pytest.approx(l_pipe, rel=2e-4)
    # updated params agree: same grads flowed through the pipeline
    for i in range(L):
        for ln in ("ln1", "attn", "ln2", "ffn1", "ffn2"):
            key = f"blocks/{ln}"
            if key not in ps.params:
                continue
            for slot, leaf in enumerate(ps.params[key]):
                np.testing.assert_allclose(
                    np.asarray(leaf[i]),
                    np.asarray(ref.params[f"block{i}/{ln}"][slot]),
                    rtol=2e-3, atol=2e-5,
                    err_msg=f"block{i}/{ln}[{slot}]")
    for pname, zname in (("prefix/tok_embed", "tok_embed"),
                         ("suffix/lm_head", "lm_head")):
        for slot, leaf in enumerate(ps.params[pname]):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(ref.params[zname][slot]),
                rtol=2e-3, atol=2e-5, err_msg=f"{pname}[{slot}]")


def test_loss_decreases_over_steps():
    ps = _mk_pipeline(stages=2)
    batch = _batch(1)
    first = float(ps.train_step(batch))
    for _ in range(20):
        last = ps.train_step(batch)
    assert float(last) < first        # memorizes the fixed batch


def test_snapshot_restore_round_trip(tmp_path):
    ps = _mk_pipeline(stages=2)
    batch = _batch(2)
    ps.train_step(batch)
    path = ps.snapshot(str(tmp_path / "lm"))
    l_next = float(ps.train_step(batch))

    ps2 = _mk_pipeline(stages=2, tau_seed=99)   # different init
    ps2.restore(path)
    assert ps2.iter == 1
    l_resumed = float(ps2.train_step(batch))
    assert l_resumed == pytest.approx(l_next, rel=1e-5)


def test_stage_divisibility_guard():
    with pytest.raises(ValueError, match="divisible"):
        _mk_pipeline(stages=8)        # L=4 blocks across 8 stages
