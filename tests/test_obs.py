"""Observability subsystem tests (sparknet_tpu.obs + utils.metrics).

Covers the ISSUE-1 acceptance surface: span nesting/export round-trip,
step-accounting percentiles + recompile detection, comms byte counters
under a 2-device CPU mesh, the hardened MetricsLogger encoder, the
`report` CLI on a canned JSONL fixture, and the full `train --metrics
--profile` -> `report` loop on CPU.
"""

import io
import json
import pathlib
import threading

import numpy as np
import pytest
import jax

from sparknet_tpu.proto import Message
from sparknet_tpu.utils.metrics import MetricsLogger
from sparknet_tpu.obs import (Tracer, StepAccounting, CommsMeter,
                              percentiles, tree_bytes,
                              ring_allreduce_bytes,
                              broadcast_collect_bytes, all_to_all_bytes)
from sparknet_tpu.obs import report as obs_report
from sparknet_tpu.obs.trace import chrome_from_spans


def events_of(buf):
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def mlp_net(batch=8, dim=16, classes=4):
    net = Message("NetParameter", name="mlp")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[batch, dim])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[batch])))
    net.add("layer", name="fc", type="InnerProduct", bottom=["data"],
            top=["fc"], inner_product_param=dict(
                num_output=classes, weight_filler=dict(type="xavier")))
    net.add("layer", name="loss", type="SoftmaxWithLoss",
            bottom=["fc", "label"], top=["loss"])
    return net


def toy_batches(batch=8, dim=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    while True:
        yield {"data": rs.randn(batch, dim).astype(np.float32),
               "label": rs.randint(0, classes, batch).astype(np.int32)}


# ---------------------------------------------------------------- metrics

class TestMetricsLogger:
    def test_context_manager_and_basic_event(self, tmp_path):
        p = tmp_path / "m.jsonl"
        with MetricsLogger(str(p)) as ml:
            ml.log("hello", x=1)
        ev = json.loads(p.read_text())
        assert ev["event"] == "hello" and ev["x"] == 1
        ml.log("after_close")          # silently dropped, no crash
        assert len(p.read_text().splitlines()) == 1

    def test_non_json_fields_do_not_crash(self):
        buf = io.StringIO()
        ml = MetricsLogger(stream=buf)
        ml.log("mixed",
               arr=np.arange(4),
               big=np.zeros((100, 100)),
               scalar=np.float32(1.5),
               dt=np.dtype("float32"),
               path=pathlib.Path("/tmp/x"),
               s={"b", "a"},
               raw=b"bytes")
        ev = events_of(buf)[0]
        assert ev["arr"] == [0, 1, 2, 3]
        assert ev["big"]["shape"] == [100, 100]     # large arrays elided
        assert ev["scalar"] == 1.5
        assert ev["dt"] == "float32"
        assert ev["path"] == "/tmp/x"
        assert ev["s"] == ["a", "b"]

    def test_thread_safety_line_integrity(self):
        buf = io.StringIO()
        ml = MetricsLogger(stream=buf)

        def work(i):
            for j in range(50):
                ml.log("w", i=i, j=j)
        ts = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        evs = events_of(buf)               # every line parses
        assert len(evs) == 200


# ----------------------------------------------------------------- tracer

class TestTracer:
    def test_nesting_depth_and_parent(self):
        buf = io.StringIO()
        tr = Tracer(MetricsLogger(stream=buf))
        with tr.span("outer"):
            with tr.span("inner", k=3) as attrs:
                attrs["extra"] = "late"
        evs = events_of(buf)
        inner, outer = evs[0], evs[1]      # inner closes first
        assert inner["name"] == "inner" and inner["parent"] == "outer"
        assert inner["depth"] == 1 and outer["depth"] == 0
        assert inner["k"] == 3 and inner["extra"] == "late"
        assert outer["parent"] is None
        assert outer["dur_ms"] >= inner["dur_ms"]

    def test_chrome_export_round_trip(self, tmp_path):
        tr = Tracer(None)                  # sink-less: buffer still works
        with tr.span("a"):
            with tr.span("b"):
                pass
        tr.instant("mark", note="x")
        path = tr.export_chrome(str(tmp_path / "t" / "trace.json"))
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        assert {e["name"] for e in evs} == {"a", "b", "mark"}
        b = next(e for e in evs if e["name"] == "b")
        a = next(e for e in evs if e["name"] == "a")
        assert b["ph"] == "X" and a["ts"] <= b["ts"]
        assert b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 0.11
        assert b["args"]["parent"] == "a"

    def test_threads_nest_independently(self):
        tr = Tracer(None)
        seen = {}

        def worker():
            with tr.span("t2"):
                seen["depth"] = len(tr._stack())
        with tr.span("main"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["depth"] == 1          # not nested under "main"
        spans = tr.spans()
        t2 = next(s for s in spans if s["name"] == "t2")
        assert t2["parent"] is None and t2["depth"] == 0
        assert chrome_from_spans(spans)    # exportable


# ---------------------------------------------------------- step stats

class TestStepAccounting:
    def test_percentiles(self):
        vals = list(range(1, 101))         # 1..100
        p = percentiles(vals)
        assert p["p50"] == pytest.approx(50.5)
        assert p["p95"] == pytest.approx(95.05)
        assert p["p99"] == pytest.approx(99.01)
        assert percentiles([]) == {}
        assert percentiles([7.0])["p99"] == 7.0

    def test_recompile_detection_via_cache_size(self):
        buf = io.StringIO()
        sa = StepAccounting(MetricsLogger(stream=buf), sample_every=1000)
        f = jax.jit(lambda x: x * 2)
        b1 = {"x": np.ones(3, np.float32)}
        f(b1["x"])
        sa.observe(0, 0.001, jit_fn=f, batch=b1, sample=False)
        b2 = {"x": np.ones(4, np.float32)}
        f(b2["x"])                          # shape change -> retrace
        sa.observe(1, 0.001, jit_fn=f, batch=b2, sample=False)
        evs = events_of(buf)
        rec = [e for e in evs if e["event"] == "recompile"]
        assert len(rec) == 2
        assert rec[0]["first"] is True and rec[0]["reason"] == "first_compile"
        assert rec[1]["first"] is False
        assert rec[1]["reason"] == "shape_change"
        assert sa.recompiles == 1           # beyond the expected first

    def test_sampling_and_summary(self):
        buf = io.StringIO()
        sa = StepAccounting(MetricsLogger(stream=buf), sample_every=4)
        x = jax.numpy.ones(2)
        for it in range(12):
            sa.observe(it, 0.002, result=x)
        sa.flush(12)
        evs = events_of(buf)
        steps = [e for e in evs if e["event"] == "step"]
        # first two observes sampled, then every 4th iter
        assert [e["iter"] for e in steps] == [0, 1, 5, 9]
        assert all("device_ms" in e and "host_ms" in e for e in steps)
        summ = [e for e in evs if e["event"] == "step_summary"][-1]
        assert summ["steps"] == 12
        assert summ["host_ms_p50"] == pytest.approx(2.0, rel=0.5)
        assert summ["device_samples"] == len(steps)


# -------------------------------------------------------------- comms

class TestComms:
    def test_byte_models(self):
        assert ring_allreduce_bytes(1000, 1) == 0
        assert ring_allreduce_bytes(1000, 2) == 1000
        assert ring_allreduce_bytes(1000, 4) == 1500
        assert broadcast_collect_bytes(1000, 4) == 8000
        assert all_to_all_bytes(1000, 4) == 750
        assert tree_bytes({"a": [np.zeros((2, 3), np.float32)],
                           "b": np.zeros(5, np.int32)}) == 24 + 20

    def test_meter_emission_and_flush(self):
        buf = io.StringIO()
        cm = CommsMeter(MetricsLogger(stream=buf), emit_every=10)
        cm.set_topology(strategy="X", n_devices=2)
        cm.register("allreduce", 1000, steps_per_round=1)
        cm.register("param_avg", 500, steps_per_round=10)
        for it in range(15):
            cm.add_h2d(100)
            cm.tick(it)
        cm.flush(14)
        evs = events_of(buf)
        assert all(e["event"] == "comms" for e in evs)
        assert evs[0]["iter"] == 0 and evs[0]["h2d_bytes"] == 100
        assert evs[0]["collective_bytes_per_step"] == 1050
        # h2d deltas across all emits sum to the total
        assert sum(e["h2d_bytes"] for e in evs) == 1500
        assert evs[-1]["h2d_bytes_total"] == 1500


# ------------------------------------------------- solver integration

class TestSolverObs:
    def _solver(self, cls=None, **kw):
        from sparknet_tpu.solver.solver import Solver
        sp = Message("SolverParameter", base_lr=0.1, lr_policy="fixed",
                     random_seed=0, display=0)
        buf = io.StringIO()
        s = (cls or Solver)(sp, net_param=mlp_net(),
                            metrics=MetricsLogger(stream=buf),
                            log_fn=None, **kw)
        return s, buf

    def test_single_device_stream(self):
        s, buf = self._solver()
        data = toy_batches()
        for _ in range(3):
            s.train_step(next(data))
        s.close()
        evs = events_of(buf)
        kinds = {e["event"] for e in evs}
        assert {"step", "comms", "recompile", "step_summary"} <= kinds
        step = next(e for e in evs if e["event"] == "step")
        assert step["host_ms"] >= 0 and step["device_ms"] > 0
        comms = next(e for e in evs if e["event"] == "comms")
        b = next(toy_batches())
        assert comms["h2d_bytes"] == sum(np.asarray(v).nbytes
                                         for v in b.values())
        assert comms["strategy"] == "Solver"

    def test_dp_comms_byte_counters_two_device_mesh(self):
        from sparknet_tpu.parallel import DataParallelSolver, make_mesh
        s, buf = self._solver(cls=DataParallelSolver,
                              mesh=make_mesh({"data": 2}))
        data = toy_batches()
        for _ in range(2):
            s.train_step(next(data))
        gb, sb = tree_bytes(s.params), tree_bytes(s.state)
        expected = ring_allreduce_bytes(gb + sb, 2)
        s.close()
        evs = events_of(buf)
        comms = [e for e in evs if e["event"] == "comms"]
        assert comms, "no comms events from DP solver"
        # bucketed overlap is the default: grads register per bucket in
        # issue order, state separately — total bytes unchanged (the
        # ring model is exactly linear at n=2)
        cols = comms[0]["collectives"]
        grads = [c for c in cols if c["kind"] == "allreduce_grads_bucket"]
        state = [c for c in cols if c["kind"] == "allreduce_state"]
        # the stateless toy MLP registers no zero-byte state collective
        assert grads and len(state) == (1 if sb else 0)
        assert sum(c["bytes_per_round"] for c in grads) == \
            ring_allreduce_bytes(gb, 2)
        assert [c["bucket"] for c in grads] == list(range(len(grads)))
        assert not grads[-1]["overlappable"]
        # the paper comparison rides the (always-registered) grad volume
        assert grads[-1]["paper_broadcast_collect_bytes"] == \
            broadcast_collect_bytes(gb, 2)
        if state:
            assert state[0]["bytes_per_round"] == ring_allreduce_bytes(sb, 2)
        assert comms[0]["axes"] == {"data": 2}
        assert comms[0]["collective_bytes_per_step"] == expected

    def test_local_sgd_round_accounting(self):
        from sparknet_tpu.parallel import LocalSGDSolver, make_mesh
        s, buf = self._solver(cls=LocalSGDSolver,
                              mesh=make_mesh({"data": 2}), tau=3)
        rs = np.random.RandomState(0)
        batches = {"data": rs.randn(3, 16, 16).astype(np.float32),
                   "label": rs.randint(0, 4, (3, 16)).astype(np.int32)}
        s.train_round(dict(batches))
        s.close()
        evs = events_of(buf)
        comms = [e for e in evs if e["event"] == "comms"]
        col = comms[0]["collectives"][0]
        assert col["kind"] == "param_average"
        assert col["steps_per_round"] == 3
        assert comms[0]["tau"] == 3
        assert any(e["event"] == "step" for e in evs)

    def test_close_is_idempotent_and_stops_watchdog(self):
        s, buf = self._solver()
        wd = s.arm_watchdog(stall_seconds=30, poll_seconds=0.01)
        assert wd.metrics is s.metrics     # barks land in the JSONL
        assert wd._thread.is_alive()
        s.close()
        assert s.watchdog is None
        assert not wd._thread.is_alive()
        s.close()                          # second close: no-op


# ------------------------------------------------------------- report

CANNED = [
    {"event": "config", "t": 0.0, "d_model": 64},
    {"event": "span", "t": 0.1, "name": "setup", "start_ms": 0.0,
     "dur_ms": 100.0, "depth": 0, "parent": None, "tid": 1},
    {"event": "span", "t": 0.2, "name": "test", "start_ms": 150.0,
     "dur_ms": 30.0, "depth": 1, "parent": "train_block", "tid": 1},
    {"event": "span", "t": 0.3, "name": "train_block", "start_ms": 100.0,
     "dur_ms": 400.0, "depth": 0, "parent": None, "tid": 1},
    {"event": "step", "t": 0.2, "iter": 0, "host_ms": 5.0,
     "device_ms": 50.0, "sync_ms": 1.0, "steps_since_sync": 1},
    {"event": "step", "t": 0.3, "iter": 5, "host_ms": 1.0,
     "device_ms": 10.0, "sync_ms": 0.5, "steps_since_sync": 5},
    {"event": "recompile", "t": 0.1, "iter": 0, "cache_size": 1,
     "first": True, "reason": "first_compile"},
    {"event": "recompile", "t": 0.25, "iter": 3, "cache_size": 2,
     "first": False, "reason": "shape_change"},
    {"event": "comms", "t": 0.3, "iter": 5, "steps": 6,
     "h2d_bytes": 600, "h2d_bytes_total": 600,
     "collective_bytes_per_step": 1500, "strategy": "DataParallelSolver",
     "n_devices": 2, "axes": {"data": 2},
     "collectives": [{"kind": "allreduce_grads", "bytes_per_round": 1500,
                      "steps_per_round": 1}]},
    {"event": "train", "t": 0.25, "iter": 0, "loss": 2.0, "lr": 0.1,
     "images_per_sec": 100.0},
    {"event": "train", "t": 0.3, "iter": 5, "loss": 1.0, "lr": 0.1,
     "images_per_sec": 120.0},
    {"event": "test", "t": 0.31, "iter": 5, "accuracy": 0.5},
    {"event": "step_summary", "t": 0.35, "iter": 6, "name": "train",
     "steps": 6, "recompiles": 1, "device_samples": 2,
     "host_ms_p50": 1.2, "host_ms_p95": 4.5, "host_ms_p99": 5.0,
     "device_ms_p50": 30.0, "device_ms_p95": 48.0, "device_ms_p99": 50.0},
    {"event": "watchdog", "t": 0.2, "kind": "nan", "loss": float("nan")},
    {"event": "prefetch", "t": 0.3, "name": "train_feed", "gets": 6,
     "depth_cap": 3, "depth_mean": 2.5, "empty_frac": 0.0},
]


class TestReport:
    @pytest.fixture
    def canned(self, tmp_path):
        p = tmp_path / "run.jsonl"
        with open(p, "w") as f:
            for e in CANNED:
                f.write(json.dumps(e) + "\n")
            f.write("not json\n")          # malformed line is tolerated
        return p

    def test_aggregate(self, canned):
        events, bad = obs_report.load_events(str(canned))
        assert bad == 1
        rep = obs_report.aggregate(events)
        assert rep["num_events"] == len(CANNED)
        phases = {p["phase"]: p for p in rep["phases"]}
        assert set(phases) == {"setup", "train_block"}   # top-level only
        assert phases["train_block"]["pct"] == 80.0
        assert rep["steps"]["recompiles"] == 1
        assert rep["steps"]["host_ms_p95"] == 4.5
        assert rep["recompiles"]["count"] == 1
        assert rep["recompiles"]["unexpected"][0]["iter"] == 3
        assert rep["comms"]["collective_bytes_per_step"] == 1500
        assert rep["train"]["first_loss"] == 2.0
        assert rep["train"]["final_loss"] == 1.0
        assert rep["train"]["images_per_sec"]["mean"] == 110.0
        assert rep["test"]["accuracy"] == 0.5
        assert rep["watchdog"] == {"nan": 1}
        assert rep["prefetch"]["depth_mean"] == 2.5

    def test_render_and_cli(self, canned, tmp_path, capsys):
        from sparknet_tpu import cli
        out_json = tmp_path / "rep.json"
        chrome = tmp_path / "trace.json"
        rc = cli.main(["report", str(canned), "--json", str(out_json),
                       "--chrome", str(chrome)])
        assert rc == 0
        out = capsys.readouterr().out
        for needle in ("per-phase time breakdown", "train_block",
                       "step times", "recompiles", "communication",
                       "loss curve", "watchdog", "malformed"):
            assert needle in out, f"missing {needle!r} in report"
        rep = json.load(open(out_json))
        assert rep["malformed_lines"] == 1
        doc = json.load(open(chrome))
        assert len(doc["traceEvents"]) == 3


# ----------------------------------------------- CLI end-to-end (CPU)

NET_PROTOTXT = """
name: "obs_mlp"
layer { name: "data" type: "JavaData" top: "data"
        java_data_param { shape { dim: 8 dim: 16 } } }
layer { name: "label" type: "JavaData" top: "label"
        java_data_param { shape { dim: 8 } } }
layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
        inner_product_param { num_output: 10
                              weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc" bottom: "label"
        top: "loss" }
"""

SOLVER_PROTOTXT = """
net: "net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
display: 2
max_iter: 5
random_seed: 0
"""


def test_train_cli_metrics_profile_report(tmp_path, capsys):
    """ISSUE-1 acceptance: 5-step synthetic run with --metrics/--profile
    produces step/span/comms/recompile events with a host/device split,
    a valid Chrome span trace, and a `report` that renders + exports."""
    from sparknet_tpu import cli
    (tmp_path / "net.prototxt").write_text(NET_PROTOTXT)
    solver = tmp_path / "solver.prototxt"
    solver.write_text(SOLVER_PROTOTXT)
    mj = tmp_path / "run.jsonl"
    tr = tmp_path / "trace"
    rc = cli.main(["train", "--solver", str(solver), "--iterations", "5",
                   "--metrics", str(mj), "--profile", str(tr)])
    assert rc == 0
    events = [json.loads(line) for line in open(mj)]
    kinds = {e["event"] for e in events}
    assert {"step", "span", "comms", "recompile"} <= kinds
    step = next(e for e in events if e["event"] == "step")
    assert "host_ms" in step and "device_ms" in step
    spans = {e["name"] for e in events if e["event"] == "span"}
    assert {"setup", "train_block"} <= spans
    doc = json.load(open(tr / "spans.trace.json"))
    assert any(e["name"] == "train_block" for e in doc["traceEvents"])
    capsys.readouterr()
    rj = tmp_path / "rep.json"
    rc = cli.main(["report", str(mj), "--json", str(rj)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "per-phase time breakdown" in out
    assert "loss curve" in out
    rep = json.load(open(rj))
    assert rep["steps"]["steps"] == 5
    assert rep["comms"]["h2d_bytes_total"] > 0
