"""Space-to-depth stem-conv rewrite == the plain strided conv, exactly.

The rewrite (ops/convolution.py Convolution._s2d_conv) must be a pure
trace-time transformation: same weight blob, same outputs, same gradients
as the stock strided conv (reference conv1 geometries:
bvlc_reference_caffenet/train_val.prototxt 11x11/4 pad 0,
bvlc_googlenet/train_val.prototxt 7x7/2 pad 3).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from tests.test_layers import make_layer, init_params

RNG = np.random.RandomState(3)

GEOMETRIES = [
    # (in_shape, num_output, kernel, stride, pad)  — name for ids
    pytest.param((2, 3, 227, 227), 8, 11, 4, 0, id="caffenet-conv1"),
    pytest.param((2, 3, 224, 224), 8, 7, 2, 3, id="googlenet-conv1"),
    pytest.param((1, 3, 33, 33), 4, 5, 3, 2, id="odd-k5s3p2"),
    pytest.param((1, 4, 16, 16), 4, 4, 4, 0, id="k-divisible-by-s"),
    pytest.param((1, 2, 15, 17), 3, 3, 2, 1, id="rect-input"),
]


def _pair(monkeypatch, in_shape, num_output, k, s, p):
    layer, _ = make_layer(
        "Convolution", [in_shape],
        convolution_param=dict(num_output=num_output, kernel_size=[k],
                               stride=[s], pad=[p]))
    params = init_params(layer)
    x = jnp.asarray(RNG.randn(*in_shape), jnp.float32)
    monkeypatch.setenv("SPARKNET_CONV_S2D", "off")
    (ref,) = layer.apply(params, [x], False, None)
    monkeypatch.setenv("SPARKNET_CONV_S2D", "on")
    assert layer._s2d_eligible()
    (got,) = layer.apply(params, [x], False, None)
    return layer, params, x, ref, got


@pytest.mark.parametrize("in_shape,num_output,k,s,p", GEOMETRIES)
def test_forward_exact(monkeypatch, in_shape, num_output, k, s, p):
    layer, params, x, ref, got = _pair(monkeypatch, in_shape, num_output,
                                       k, s, p)
    assert got.shape == tuple(layer.out_shapes()[0])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("in_shape,num_output,k,s,p", GEOMETRIES[:3])
def test_gradients_match(monkeypatch, in_shape, num_output, k, s, p):
    layer, _ = make_layer(
        "Convolution", [in_shape],
        convolution_param=dict(num_output=num_output, kernel_size=[k],
                               stride=[s], pad=[p]))
    params = init_params(layer)
    x = jnp.asarray(RNG.randn(*in_shape), jnp.float32)

    def loss(w, xv):
        (y,) = layer.apply([w, params[1]], [xv], False, None)
        return (y * jnp.cos(jnp.arange(y.size, dtype=jnp.float32)
                            .reshape(y.shape))).sum()

    monkeypatch.setenv("SPARKNET_CONV_S2D", "off")
    gw_ref, gx_ref = jax.grad(loss, argnums=(0, 1))(params[0], x)
    monkeypatch.setenv("SPARKNET_CONV_S2D", "on")
    gw, gx = jax.grad(loss, argnums=(0, 1))(params[0], x)
    # weight grads must land on the stock (O, C, kh, kw) blob unchanged
    assert gw.shape == params[0].shape
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=2e-4, atol=2e-4)


def test_auto_policy_targets_stem_convs(monkeypatch):
    monkeypatch.setenv("SPARKNET_CONV_S2D", "auto")
    stem, _ = make_layer(
        "Convolution", [(1, 3, 32, 32)],
        convolution_param=dict(num_output=8, kernel_size=[7], stride=[2]))
    assert stem._s2d_eligible()
    deep, _ = make_layer(    # 64 channels: lanes already well fed
        "Convolution", [(1, 64, 16, 16)],
        convolution_param=dict(num_output=8, kernel_size=[3], stride=[2]))
    assert not deep._s2d_eligible()
    grouped, _ = make_layer(
        "Convolution", [(1, 4, 16, 16)],
        convolution_param=dict(num_output=8, kernel_size=[3], stride=[2],
                               group=2))
    assert not grouped._s2d_eligible()
    unstrided, _ = make_layer(
        "Convolution", [(1, 3, 16, 16)],
        convolution_param=dict(num_output=8, kernel_size=[3]))
    assert not unstrided._s2d_eligible()
