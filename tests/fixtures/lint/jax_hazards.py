"""SPK101/102/105 fixture corpus — positives, negatives, suppressed.

Never imported at runtime; `sparknet lint` only parses it. Expected
findings are asserted line-exactly in tests/test_lint.py, so EDITS
HERE MUST UPDATE THAT TEST.
"""

import jax
import jax.numpy as jnp
import numpy as np

_MUTABLE_TABLE = {"scale": 2.0}


def build_update(updater, lr_fn):
    def step(params, state, history, batch, it, rng):
        loss = float(jnp.sum(batch["x"]))            # SPK101 float
        host = np.asarray(params["w"])               # SPK101 asarray
        snap = jax.device_get(state)                 # SPK101 device_get
        probe = loss if loss > 0 else 0.0            # noqa: F841
        _ = host, snap
        if it > 0:                                   # SPK102 if-on-traced
            loss = loss + 1
        for _ in range(it):                          # SPK102 for-on-traced
            loss = loss + _MUTABLE_TABLE["scale"]    # SPK102 mutable global
        params = updater(params, lr_fn(it))
        return params, state, history, loss
    return jax.jit(step)                             # SPK105 no donation


def build_update_ok(updater, lr_fn):
    tau = 4                                          # static closure

    def step(params, state, history, batch, it, rng):
        if tau > 1:                                  # static: no finding
            batch = {k: v * 1.0 for k, v in batch.items()}
        loss = jnp.sum(batch["x"])
        params = updater(params, lr_fn(it))
        return params, state, history, loss
    return jax.jit(step, donate_argnums=(0, 1, 2))   # donated: no SPK105


def build_eval(net):
    # eval-style jit: params in, scores out — donation would be WRONG,
    # and the rule must stay quiet here
    def ev(params, state, batch):
        blobs = net.apply(params, state, batch)
        return {k: jnp.mean(v) for k, v in blobs.items()}
    return jax.jit(ev)


def build_update_suppressed(updater):
    def step(params, state, batch, it):
        dbg = float(jnp.sum(batch["x"]))  # spk: disable=SPK101
        return updater(params, it), state, dbg
    return jax.jit(step, donate_argnums=(0,))


def static_arg_hazard(f):
    jf = jax.jit(f, static_argnums=(1,))
    return jf(jnp.ones(3), [1, 2])                   # SPK102 unhashable


def host_driver(solver, loss):
    # host-side float() is the DISPLAY discipline, not a finding
    return float(loss)
