"""SPK104 fixture corpus — collective axis-name mismatches. Parsed,
never imported. Line numbers asserted in tests/test_lint.py."""

import jax
from jax.sharding import Mesh
from jax.experimental.shard_map import shard_map

DATA = "data"


def make_mesh(axes, devices=None):
    return Mesh(devices, tuple(axes))


def masked_mean(tree, valid, axis):
    # axis-forwarding helper: callers are checked at their call site
    return jax.tree_util.tree_map(
        lambda x: jax.lax.pmean(x, axis), tree)


def wrong_literal(devices):
    mesh = Mesh(devices, ("data",))

    def f(x):
        return jax.lax.pmean(x, "batch")             # SPK104 mismatch

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def wrong_constant(devices):
    mesh = Mesh(devices, ("model",))

    def f(x):
        return jax.lax.psum(x, DATA)                 # SPK104 via constant

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def wrong_helper(devices):
    mesh = make_mesh({"data": 8})

    def f(tree, valid):
        return masked_mean(tree, valid, "expert")    # SPK104 via helper

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def right_axes(devices):
    mesh = Mesh(devices, ("data", "seq"))

    def f(x):
        x = jax.lax.pmean(x, "seq")
        i = jax.lax.axis_index("data")
        return masked_mean(x, None, "data") + i

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def unresolvable_is_silent(mesh, axis):
    # neither the mesh nor the axis resolves statically: no guessing
    def f(x):
        return jax.lax.pmean(x, axis)

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def wrong_suppressed(devices):
    mesh = Mesh(devices, ("data",))

    def f(x):
        return jax.lax.pmean(x, "seq")  # spk: disable=SPK104
    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)
