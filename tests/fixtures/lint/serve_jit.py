"""SPK105 serving-tier corpus — pins the eval-style exemption for the
shapes serve/engine.py actually jits.

Never imported at runtime; `sparknet lint` only parses it. The serving
forward takes (params, state, batch) and returns ONLY output blobs —
params flow in on every call and are reused across requests, so
donating them would free buffers the next batch still needs. SPK105
must stay quiet on every serve-shaped function here; the one
update-shaped contrast at the bottom pins that the rule still fires
when params are carried through. Expected findings are asserted
line-exactly in tests/test_lint.py, so EDITS HERE MUST UPDATE THAT
TEST.
"""

import jax


def serve_bucket_forward(net):
    # the per-bucket jit `sparknet serve` builds: blobs out, nothing
    # state-named returned -> exempt by construction, no annotation
    def run(params, state, batch):
        blobs, _ = net.apply(params, state, batch, train=False)
        return {k: blobs[k] for k in net.output_blobs if k in blobs}
    return jax.jit(run)


def serve_single_logits(net, out_name):
    # single-output variant (subscript return, still not a carried Name)
    def run(params, state, batch):
        blobs, _ = net.apply(params, state, batch, train=False)
        return blobs[out_name]
    return jax.jit(run)


def serve_with_new_state(net):
    # a stateful serving net (e.g. BN running stats in TEST phase)
    # returns DERIVED state, not the `state` argument itself — reusing
    # the input params/state next call is still correct, so no finding
    def run(params, state, batch):
        blobs, new_state = net.apply(params, state, batch, train=False)
        return blobs, new_state
    return jax.jit(run)


def train_step_contrast(updater):
    # the update shape the rule exists for: params in AND out, no
    # donation -> one finding, proving the serve exemption is an
    # exemption and not a dead rule
    def step(params, state, batch):
        params = updater(params, batch)
        return params, state
    return jax.jit(step)                    # SPK105 no donation
