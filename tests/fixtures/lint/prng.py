"""SPK103 fixture corpus — PRNG key reuse. Parsed, never imported.
Line numbers are asserted in tests/test_lint.py."""

import jax


def reuse_param_key(rng):
    a = jax.random.normal(rng, (3,))
    b = jax.random.uniform(rng, (3,))                # SPK103 reuse
    return a + b


def reuse_local_key():
    k = jax.random.PRNGKey(0)
    a = jax.random.normal(k, (3,))
    b = jax.random.normal(k, (3,))                   # SPK103 reuse
    return a + b


def loop_reuse():
    k = jax.random.PRNGKey(0)
    out = []
    for i in range(8):
        out.append(jax.random.normal(k, (2,)))       # SPK103 loop reuse
    return out


def split_ok(rng):
    k1, k2 = jax.random.split(rng)
    a = jax.random.normal(k1, (3,))
    b = jax.random.uniform(k2, (3,))
    return a + b


def fold_in_loop_ok(rng):
    out = []
    for i in range(8):
        out.append(jax.random.normal(jax.random.fold_in(rng, i), (2,)))
    return out


def branch_ok(rng, gaussian):
    # exclusive branches may each consume the key once
    if gaussian:
        return jax.random.normal(rng, (3,))
    return jax.random.uniform(rng, (3,))


def rebind_ok():
    k = jax.random.PRNGKey(0)
    a = jax.random.normal(k, (3,))
    k = jax.random.PRNGKey(1)
    b = jax.random.normal(k, (3,))
    return a + b


def reuse_suppressed(rng):
    a = jax.random.normal(rng, (3,))
    b = jax.random.normal(rng, (3,))  # spk: disable=SPK103
    return a + b
