"""SPK104 fixture corpus — tensor-parallel axis helpers over the
("data", "model") mesh (the parallel/fsdp.py + gspmd.py shapes).
Parsed, never imported. Line numbers asserted in tests/test_lint.py."""

import jax
from jax.sharding import Mesh
from jax.experimental.shard_map import shard_map


def gather_full(tree, axis):
    # axis-forwarding helper (fsdp.gather_full shape): the all-gather of
    # dim0-sharded weights — callers are checked at their call site
    return jax.tree_util.tree_map(
        lambda x: jax.lax.all_gather(x, axis, axis=0, tiled=True), tree)


def take_shard(tree, axis, n):
    w = jax.lax.axis_index(axis)
    return jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_slice_in_dim(
            x, w * (x.shape[0] // n), x.shape[0] // n), tree)


def row_psum(y, axis):
    # the Megatron row-split completion psum (gspmd row-parallel blobs)
    return jax.lax.psum(y, axis)


def wrong_model_on_data_mesh(devices):
    mesh = Mesh(devices, ("data",))

    def f(p):
        return gather_full(p, "model")           # SPK104: no "model" axis

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def wrong_axis_through_psum_helper(devices):
    mesh = Mesh(devices, ("data", "model"))

    def f(y):
        return row_psum(y, "expert")             # SPK104 via helper

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def wrong_axis_into_shard_index(devices):
    mesh = Mesh(devices, ("data", "model"))

    def f(p):
        return take_shard(p, "pipe", 8)          # SPK104 via axis_index

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def right_tp_axes(devices):
    mesh = Mesh(devices, ("data", "model"))

    def f(p, y):
        full = gather_full(p, "data")
        part = row_psum(y, "model")
        return take_shard(full, "data", 8), part

    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)


def wrong_tp_suppressed(devices):
    mesh = Mesh(devices, ("data", "model"))

    def f(y):
        return row_psum(y, "seq")  # spk: disable=SPK104
    return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)
