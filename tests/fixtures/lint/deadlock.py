"""SPK205-207 fixture corpus — the deadlock family. Parsed, never
imported. Line numbers asserted in tests/test_lint.py."""

import threading
import time


class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:                        # SPK205 cycle leg 1
                pass

    def backward(self):
        with self._b:
            with self._a:                        # cycle leg 2 (one report)
                pass


class Caller:
    def __init__(self):
        self._a = threading.Lock()
        self.peer = Callee()

    def poke_peer(self):
        with self._a:
            self.peer.work()                     # SPK205 cross-class cycle

    def lock_a(self):
        with self._a:
            pass


class Callee:
    def __init__(self):
        self._b = threading.Lock()
        self.owner = Caller()

    def work(self):
        with self._b:
            pass

    def poke_owner(self):
        with self._b:
            self.owner.lock_a()                  # closes the cycle


class Reentry:
    def __init__(self):
        self._m = threading.Lock()

    def outer(self):
        with self._m:
            self.inner()                         # SPK205 self-deadlock

    def inner(self):
        with self._m:
            pass


class ReentrantOk:
    def __init__(self):
        self._m = threading.RLock()

    def outer(self):
        with self._m:
            self.inner()                         # RLock: no finding

    def inner(self):
        with self._m:
            pass


class Ordered:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:                        # same order everywhere:
                pass

    def two(self):
        with self._a:
            with self._b:                        # no cycle, no finding
                pass


class SlowUnderLock:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.v = 0

    def direct(self):
        with self._lock:
            time.sleep(0.1)                      # SPK206 direct

    def via_helper(self):
        with self._lock:
            self._flush()                        # SPK206 transitive

    def _flush(self):
        with open("state.json", "w") as f:
            f.write("{}")

    def waits(self):
        with self._lock:
            self._stop.wait(1.0)                 # SPK206 event wait

    def snapshot_then_block(self):
        with self._lock:
            v = self.v
        time.sleep(v)                            # outside: no finding

    def tolerated(self):
        with self._lock:
            time.sleep(0.1)                      # spk: disable=SPK206


class CondIdiom:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False

    def waiter(self):
        with self._cv:
            while not self.ready:
                self._cv.wait()                  # releases _cv: no finding


class Emitter:
    def __init__(self, on_tick):
        self._lock = threading.Lock()
        self.on_tick = on_tick
        self.n = 0

    def fire_bad(self):
        with self._lock:
            self.n += 1
            self.on_tick(self.n)                 # SPK207

    def fire_good(self):
        with self._lock:
            n = self.n
        self.on_tick(n)                          # after release: no finding
