"""SPK201-204 fixture corpus — lock discipline. Parsed, never
imported. Line numbers asserted in tests/test_lint.py."""

import threading


class Racy:
    def __init__(self):
        self._lock = threading.Lock()
        self._beat = 0.0          # spk: guarded-by=_lock
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def beat(self):
        self._beat = 1.0                             # SPK202 main side
        self.count += 1

    def _run(self):
        while True:
            dt = self._beat                          # SPK201 thread side
            self.count = 0                           # SPK204 unannotated
            self._locked_ok(dt)

    def _locked_ok(self, dt):
        with self._lock:
            self._beat = dt                          # held: no finding


class Clean:
    def __init__(self):
        self._lock = threading.Lock()
        self._x = 0               # spk: guarded-by=_lock
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while not self._stop.is_set():
            with self._lock:
                self._x += 1

    def snapshot(self):
        with self._lock:
            return self._x


class HoldsContract:
    # spk: guarded-by-default=_lock
    def __init__(self):
        self._lock = threading.Lock()
        self.a = 0
        self.b = 0

    def update(self):             # spk: thread-entry
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):       # spk: holds=_lock
        self.a += 1                                  # held by contract
        self.b += 1

    def broken(self):
        self._bump_locked()                          # SPK202 holds-breach


class StaleGuard:
    def __init__(self):
        self._y = 0               # spk: guarded-by=_gone  -> SPK203

    def poke(self):
        self._y = 1               # spk: disable=SPK202 (suppressed)


class OptedOut:
    def __init__(self):
        self.hits = 0             # spk: unguarded (single-writer gauge)

    def start(self):
        threading.Thread(target=self._run).start()

    def _run(self):
        self.hits += 1

    def reset(self):
        self.hits = 0
