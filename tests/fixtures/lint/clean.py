"""A fully clean fixture: the linter must report NOTHING here. Parsed,
never imported."""

import jax
import jax.numpy as jnp


def build_step(updater, lr_fn):
    def step(params, state, history, batch, it, rng):
        k1, k2 = jax.random.split(rng)
        noise = jax.random.normal(k1, (3,))
        more = jax.random.uniform(k2, (3,))
        loss = jnp.sum(batch["x"]) + jnp.sum(noise) + jnp.sum(more)
        params = updater(params, lr_fn(it))
        return params, state, history, loss
    return jax.jit(step, donate_argnums=(0, 1, 2))


def host_loop(solver, stream):
    for batch in stream:
        loss = solver.train_step(batch)
    return float(loss)
