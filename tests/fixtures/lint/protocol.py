"""SPK301-304 fixture corpus — distributed file-protocol discipline.
Parsed, never imported. Line numbers asserted in tests/test_lint.py."""

import json
import os
import sys

import numpy as np

EXIT_RECOVERY_ABORT = 3
MANIFEST_SUFFIX = ".latest.json"


def bad_heartbeat(host, rec):
    with open(f"hb-{host}.json", "w") as f:      # SPK301 (hb-)
        json.dump(rec, f)


def bad_part(h, r, arr):
    np.savez(f"part-{h}-{r}.npz", arr=arr)       # SPK301 (part-)


def bad_manifest(prefix, man):
    path = prefix + MANIFEST_SUFFIX
    with open(path, "w") as f:                   # SPK301 (constant)
        json.dump(man, f)


def _mask_path(round_idx):
    return f"mask-{round_idx}.json"


def bad_via_helper(round_idx, mask):
    p = _mask_path(round_idx)
    with open(p, "w") as f:                      # SPK301 (helper path)
        json.dump(mask, f)


def good_atomic(host, rec):
    path = f"hb-{host}.json"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:                    # tmp-tagged: no finding
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)                        # local src: no finding


def good_reader(host):
    with open(f"hb-{host}.json") as f:           # read mode: no finding
        return json.load(f)


def tolerated_write(host):
    with open(f"hb-{host}.json", "w") as f:      # spk: disable=SPK301
        f.write("{}")


def split_commit(tmp_path, host):
    os.replace(tmp_path, f"hb-{host}.json")      # SPK302 (src is a param)


def bad_gate(hb, round_idx):
    hb.gate(round_idx)                           # SPK303 (no timeout, dropped)


def good_gate(hb, round_idx):
    res = hb.gate(round_idx, timeout=30.0)       # consumed + bounded: ok
    return res


def bounded_barrier(hb, epoch):
    hb.restart_barrier(epoch, timeout=60.0)      # timeout: no finding


def tolerated_gate(hb, round_idx):
    hb.gate(round_idx)                           # spk: disable=SPK303


def bail_known():
    sys.exit(3)                                  # SPK304 (EXIT_RECOVERY_ABORT)


def bail_unknown():
    os._exit(7)                                  # SPK304 (not in the table)


def bail_named():
    sys.exit(EXIT_RECOVERY_ABORT)                # named constant: no finding
