"""SPK401-402 fixture corpus — metrics-schema agreement. Parsed, never
imported. Line numbers asserted in tests/test_lint.py.

Emit sites for fixture-only events are SPK402-suppressed (they are
intentionally absent from the committed repo schema); the consumers
below are then checked against the live registry these emits create.
"""


def emit(metrics, step, loss):
    metrics.log("fixture_tick", step=step, loss=loss)   # spk: disable=SPK402
    metrics.log("fixture_round", kind="fixture_sync")   # spk: disable=SPK402


def emit_unregistered(metrics):
    metrics.log("fixture_orphan", a=1)                  # SPK402 unregistered


def emit_drifted(metrics):
    metrics.log("bench_config", bogus_field=1)          # SPK402 field drift


def consume(e):
    if e.get("event") == "fixture_tick":                # emitted: no finding
        return 1
    if e.get("event") == "fixture_tikc":                # SPK401 typo
        return 2
    kind = e.get("event", "?")
    if kind == "fixture_round":                         # via local: no finding
        return 3
    if kind in ("fixture_rnd", "summary"):              # SPK401 (fixture_rnd)
        return 4
    return 0


def tolerated(e):
    if e.get("event") == "fixture_ghost":               # spk: disable=SPK401
        return 1
    return 0
