"""Wire codec (data/wire.py): compressed H2D feed, bit-exact by proof.

The acceptance bar from ISSUE 13: every wire mode must reproduce the raw
device-transform path bit for bit (the codec moves WHERE the crop slice
and the unpack happen, never the float32 op order), the pack must be
lossless-or-error, and the composed precrop+pack mode must cut the
shipped bytes by >= 3x for a low-entropy source at CaffeNet geometry.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.data.device_transform import (DeviceTransformer,
                                                build_device_transformer,
                                                aux_keys)
from sparknet_tpu.data.wire import (WIRE_MODES, PACK_WIDTHS, WireCodec,
                                    infer_pack_bits, wire_mode_from_env,
                                    wire_bits_from_env)
from sparknet_tpu.proto import Message


def _devt(crop=12, mirror=True, mean_values=(10.0, 20.0, 30.0),
          scale=0.5):
    tp = Message("TransformationParameter", mirror=mirror, scale=scale)
    if crop:
        tp.crop_size = crop
    if mean_values:
        tp.mean_value.extend(list(mean_values))
    return build_device_transformer(tp, phase=0)


def _feed(devt, images):
    """Device-mode feed dict: raw records + host-side aux draws."""
    n = len(images)
    out = {"data": images, "label": np.zeros(n, np.int32)}
    out.update(devt.aux(n, images.shape[1:]))
    return out


def _run(fn, batch):
    out = jax.jit(fn)({k: jnp.asarray(v) for k, v in batch.items()})
    return np.asarray(out["data"])


def _uniform(n=6, c=3, h=16, w=16, hi=256, seed=0):
    return np.random.RandomState(seed).randint(
        0, hi, (n, c, h, w)).astype(np.uint8)


@pytest.mark.parametrize("mode", ["precrop", "pack", "precrop+pack"])
def test_wire_modes_bit_exact_vs_raw(mode):
    # low-entropy pixels so every mode (incl. the inferred 2-bit pack)
    # is exercised; the raw path is the reference, equality is exact
    devt = _devt()
    images = _uniform(hi=4, seed=1)
    batch = _feed(devt, images)
    ref = _run(devt.device_fn(), batch)

    codec = WireCodec(devt, images.shape[1:], mode=mode, sample=images)
    shipped = codec.encode(batch)
    got = _run(codec.device_fn(), shipped)
    np.testing.assert_array_equal(got, ref)


def test_precrop_bit_exact_full_mean_and_mirror():
    # the hard case: the full-size mean window is sliced at the ORIGINAL
    # y/x (pre-mirror) — the precropped device path must still see those
    # coords even though the crop itself happened on the host
    devt = _devt(mean_values=None)
    mean = np.random.RandomState(2).rand(3, 16, 16).astype(np.float32) * 90
    devt.h.mean, devt.h.full_mean = mean, True    # bypass mean_file I/O
    images = _uniform(seed=3)
    batch = _feed(devt, images)
    ref = _run(devt.device_fn(), batch)

    codec = WireCodec(devt, images.shape[1:], mode="precrop")
    got = _run(codec.device_fn(), codec.encode(batch))
    np.testing.assert_array_equal(got, ref)


def test_encode_keeps_aux_and_ships_wire_shape():
    devt = _devt()
    images = _uniform(hi=4, seed=4)
    batch = _feed(devt, images)
    codec = WireCodec(devt, images.shape[1:], mode="precrop+pack",
                      sample=images)
    shipped = codec.encode(batch)
    ky, kx, kf = aux_keys("data")
    for k in (ky, kx, kf, "label"):
        assert shipped[k] is batch[k]     # aux rides along untouched
    assert shipped["data"].shape == (len(images),) + codec.wire_shape
    assert batch["data"].shape == images.shape    # caller's dict intact


@pytest.mark.parametrize("bits", [1, 2, 4])
def test_pack_roundtrip_lossless(bits):
    devt = _devt(crop=0, mirror=False, mean_values=None, scale=1.0)
    images = _uniform(hi=1 << bits, seed=5)
    codec = WireCodec(devt, images.shape[1:], mode="pack", bits=bits)
    batch = codec.encode(_feed(devt, images))
    # identity inner isolates the unpack stage
    out = jax.jit(codec.device_fn(inner=lambda b: b))(
        {k: jnp.asarray(v) for k, v in batch.items()})
    np.testing.assert_array_equal(np.asarray(out["data"]), images)


def test_pack_overflow_raises_not_clips():
    devt = _devt(crop=0)
    images = _uniform(hi=4, seed=6)
    codec = WireCodec(devt, images.shape[1:], mode="pack", bits=2)
    hot = images.copy()
    hot[0, 0, 0, 0] = 200                 # exceeds the fixed 2-bit width
    with pytest.raises(ValueError, match="lossless"):
        codec.encode(_feed(devt, hot))


def test_bits_are_fixed_once_static_shapes():
    # width 8 inferred from a full-range sample = passthrough; the wire
    # shape never depends on later batch contents (no recompiles)
    devt = _devt(crop=0)
    images = _uniform(hi=256, seed=7)
    codec = WireCodec(devt, images.shape[1:], mode="pack", sample=images)
    assert not codec.packing and codec.bits == 8
    assert codec.wire_shape == images.shape[1:]
    assert infer_pack_bits(np.array([0])) == 1
    assert infer_pack_bits(np.array([3])) == 2
    assert infer_pack_bits(np.array([15])) == 4
    assert infer_pack_bits(np.array([16])) == 8


def test_reduction_meets_3x_target_at_caffenet_geometry():
    # the acceptance geometry: 3x256x256 records cropped to 227, 2-bit
    # low-entropy source -> 1.27x (precrop) * 4x (pack) = 5.1x >= 3x
    tp = Message("TransformationParameter", crop_size=227, mirror=True)
    tp.mean_value.extend([104.0, 117.0, 123.0])
    devt = build_device_transformer(tp, phase=0)
    codec = WireCodec(devt, (3, 256, 256), mode="precrop+pack", bits=2)
    d = codec.describe()
    assert d["wire"] == "precrop+pack" and d["wire_bits"] == 2
    assert d["wire_reduction"] >= 3.0
    assert d["h2d_kb_per_image"] * 3 <= codec.raw_kb_per_image


def test_raw_overrides_reflect_shipped_shapes():
    devt = _devt()
    codec = WireCodec(devt, (3, 16, 16), mode="precrop+pack", bits=2)
    over = codec.raw_overrides(batch_size=4)
    assert over["data"] == (4,) + codec.wire_shape
    ky, kx, kf = aux_keys("data")
    for k in (ky, kx, kf):
        assert over[k] == (4,)


def test_precrop_without_crop_degenerates_to_raw():
    devt = _devt(crop=0)
    codec = WireCodec(devt, (3, 16, 16), mode="precrop")
    assert not codec.precrop and codec.wire_shape == (3, 16, 16)
    images = _uniform(seed=8)
    batch = _feed(devt, images)
    assert codec.encode(batch)["data"] is batch["data"]


def test_env_validation(monkeypatch):
    monkeypatch.setenv("SPARKNET_WIRE", "precrop+pack")
    assert wire_mode_from_env() == "precrop+pack"
    monkeypatch.setenv("SPARKNET_WIRE", "precorp")      # the typo trap
    with pytest.raises(ValueError, match="SPARKNET_WIRE"):
        wire_mode_from_env()
    monkeypatch.delenv("SPARKNET_WIRE")
    assert wire_mode_from_env() == "raw"
    monkeypatch.setenv("SPARKNET_WIRE_BITS", "3")
    with pytest.raises(ValueError, match="SPARKNET_WIRE_BITS"):
        wire_bits_from_env()
    monkeypatch.setenv("SPARKNET_WIRE_BITS", "4")
    assert wire_bits_from_env() == 4
    assert set(WIRE_MODES) >= {"raw", "precrop", "pack", "precrop+pack"}
    assert PACK_WIDTHS == (1, 2, 4, 8)


def test_pack_needs_bits_or_sample():
    devt = _devt(crop=0)
    with pytest.raises(ValueError, match="sample"):
        WireCodec(devt, (3, 16, 16), mode="pack")
