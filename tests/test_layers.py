"""Per-layer forward-correctness + numerical gradient checks.

The TPU-native analog of the reference's GradientChecker harness
(test_gradient_check_util.hpp:19): every differentiable layer's jax.grad is
compared against central finite differences, and forwards are checked against
straightforward numpy re-computations of the Caffe formulas (pooling's
ceil-mode/pad-divisor corner cases hand-derived from pooling_layer.cpp).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sparknet_tpu.proto import Message
from sparknet_tpu.graph.registry import get as get_layer

RNG = np.random.RandomState(0)


def make_layer(type_name, bottom_shapes, phase=0, **layer_fields):
    lp = Message("LayerParameter", name="t", type=type_name, **layer_fields)
    cls = get_layer(type_name)
    return cls(lp, bottom_shapes, phase), lp


def init_params(layer, seed=0):
    rng = jax.random.PRNGKey(seed)
    out = []
    for i, (shape, filler, lr, dc) in enumerate(layer.param_shapes()):
        k = jax.random.fold_in(rng, i)
        out.append(0.1 * jax.random.normal(k, shape))
    return out


def numeric_grad(f, x, step=1e-2):
    """Central-difference gradient of scalar f at x (mirrors the reference
    checker's two-sided estimate, test_gradient_check_util.hpp:160-171)."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    flat = x.ravel()
    gflat = g.ravel()
    for i in range(flat.size):
        old = flat[i]
        flat[i] = old + step
        fp = float(f(jnp.asarray(x, jnp.float32)))
        flat[i] = old - step
        fm = float(f(jnp.asarray(x, jnp.float32)))
        flat[i] = old
        gflat[i] = (fp - fm) / (2 * step)
    return g


def check_grad(f, x, step=1e-2, tol=2e-2):
    analytic = np.asarray(jax.grad(lambda v: f(v))(jnp.asarray(x, jnp.float32)))
    numeric = numeric_grad(f, x, step)
    scale = max(1.0, np.abs(numeric).max())
    np.testing.assert_allclose(analytic, numeric, atol=tol * scale,
                               err_msg="analytic vs numeric gradient")


class TestConvolution:
    def test_forward_matches_direct(self):
        layer, _ = make_layer(
            "Convolution", [(2, 3, 5, 5)],
            convolution_param=dict(num_output=4, kernel_size=[3], stride=[1],
                                   pad=[1]))
        params = init_params(layer)
        x = jnp.asarray(RNG.randn(2, 3, 5, 5), jnp.float32)
        (y,) = layer.apply(params, [x], False, None)
        assert y.shape == (2, 4, 5, 5)
        # direct computation at one output position
        w, b = np.asarray(params[0]), np.asarray(params[1])
        xp = np.pad(np.asarray(x), ((0, 0), (0, 0), (1, 1), (1, 1)))
        want = (xp[1, :, 2:5, 1:4] * w[3]).sum() + b[3]
        np.testing.assert_allclose(y[1, 3, 2, 1], want, rtol=2e-5)

    def test_grouped(self):
        layer, _ = make_layer(
            "Convolution", [(1, 4, 4, 4)],
            convolution_param=dict(num_output=6, kernel_size=[3], group=2))
        params = init_params(layer)
        assert params[0].shape == (6, 2, 3, 3)
        x = jnp.asarray(RNG.randn(1, 4, 4, 4), jnp.float32)
        (y,) = layer.apply(params, [x], False, None)
        assert y.shape == (1, 6, 2, 2)
        # group 0 outputs depend only on channels 0-1
        x2 = x.at[:, 2:].set(0.0)
        (y2,) = layer.apply(params, [x2], False, None)
        np.testing.assert_allclose(y[:, :3], y2[:, :3], rtol=1e-5)

    def test_rect_kernel_stride(self):
        layer, _ = make_layer(
            "Convolution", [(1, 2, 8, 9)],
            convolution_param=dict(num_output=3, kernel_h=3, kernel_w=2,
                                   stride_h=2, stride_w=3, pad_h=1, pad_w=0))
        assert layer.out_shapes() == [(1, 3, 4, 3)]

    def test_gradcheck(self):
        layer, _ = make_layer(
            "Convolution", [(1, 2, 4, 4)],
            convolution_param=dict(num_output=2, kernel_size=[3], pad=[1]))
        params = init_params(layer)
        x = np.asarray(0.5 * RNG.randn(1, 2, 4, 4), np.float32)
        check_grad(lambda v: layer.apply(params, [v], False, None)[0].sum(), x)
        check_grad(lambda w: layer.apply([w, params[1]],
                                         [jnp.asarray(x)], False, None)[0].sum(),
                   np.asarray(params[0]))


class TestDeconvolution:
    def test_shape_and_inverse_of_conv(self):
        layer, _ = make_layer(
            "Deconvolution", [(1, 3, 4, 4)],
            convolution_param=dict(num_output=2, kernel_size=[4], stride=[2],
                                   pad=[1]))
        assert layer.out_shapes() == [(1, 2, 8, 8)]
        params = init_params(layer)
        x = jnp.asarray(RNG.randn(1, 3, 4, 4), jnp.float32)
        (y,) = layer.apply(params, [x], False, None)
        assert y.shape == (1, 2, 8, 8)

    def test_gradcheck(self):
        layer, _ = make_layer(
            "Deconvolution", [(1, 2, 3, 3)],
            convolution_param=dict(num_output=2, kernel_size=[2], stride=[2]))
        params = init_params(layer)
        x = np.asarray(0.5 * RNG.randn(1, 2, 3, 3), np.float32)
        check_grad(lambda v: layer.apply(params, [v], False, None)[0].sum(), x)


class TestPooling:
    def test_ceil_mode_sizing(self):
        # CIFAR pool1: 32x32, k3 s2 -> ceil((32-3)/2)+1 = 16
        layer, _ = make_layer("Pooling", [(1, 1, 32, 32)],
                              pooling_param=dict(pool="MAX", kernel_size=3,
                                                 stride=2))
        assert layer.out_shapes() == [(1, 1, 16, 16)]
        # AlexNet pool5: 13x13 k3 s2 -> ceil(10/2)+1 = 6
        layer, _ = make_layer("Pooling", [(1, 1, 13, 13)],
                              pooling_param=dict(pool="MAX", kernel_size=3,
                                                 stride=2))
        assert layer.out_shapes() == [(1, 1, 6, 6)]

    def test_pad_clip_rule(self):
        # in=4, k=3, s=2, p=1: ceil((4+2-3)/2)+1 = 3; (3-1)*2=4 < 4+1 -> keep 3
        layer, _ = make_layer("Pooling", [(1, 1, 4, 4)],
                              pooling_param=dict(pool="AVE", kernel_size=3,
                                                 stride=2, pad=1))
        assert layer.out_shapes() == [(1, 1, 3, 3)]
        # in=2, k=2, s=2, p=1: ceil((2+2-2)/2)+1 = 2; (2-1)*2=2 >= 2+1? no -> 2
        layer, _ = make_layer("Pooling", [(1, 1, 2, 2)],
                              pooling_param=dict(pool="AVE", kernel_size=2,
                                                 stride=2, pad=1))
        assert layer.out_shapes() == [(1, 1, 2, 2)]

    def test_max_ignores_padding(self):
        layer, _ = make_layer("Pooling", [(1, 1, 2, 2)],
                              pooling_param=dict(pool="MAX", kernel_size=2,
                                                 stride=2, pad=1))
        x = -jnp.ones((1, 1, 2, 2))  # all negative; pad must not win
        (y,) = layer.apply([], [x], False, None)
        assert float(y.max()) == -1.0

    def test_ave_divisor_includes_pad(self):
        # caffe AVE: divisor = raw window clipped to in+pad
        layer, _ = make_layer("Pooling", [(1, 1, 3, 3)],
                              pooling_param=dict(pool="AVE", kernel_size=3,
                                                 stride=2, pad=1))
        x = jnp.ones((1, 1, 3, 3))
        (y,) = layer.apply([], [x], False, None)
        # out position (0,0): window rows/cols [-1,2): 2 real rows of 3-col
        # window... divisor = (min(-1+3, 3+1) - (-1))^2 = 3^2 = 9, sum = 4
        np.testing.assert_allclose(y[0, 0, 0, 0], 4.0 / 9.0, rtol=1e-6)
        # center (1,1): window [1,4) clip->[1,3) real sum 4; divisor:
        # (min(1+3,4)-1)=3 per axis -> 9
        np.testing.assert_allclose(y[0, 0, 1, 1], 4.0 / 9.0, rtol=1e-6)

    def test_ave_matches_numpy_nopad(self):
        layer, _ = make_layer("Pooling", [(2, 3, 6, 6)],
                              pooling_param=dict(pool="AVE", kernel_size=2,
                                                 stride=2))
        x = RNG.randn(2, 3, 6, 6).astype(np.float32)
        (y,) = layer.apply([], [jnp.asarray(x)], False, None)
        want = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
        np.testing.assert_allclose(y, want, rtol=1e-5)

    def test_global_pooling(self):
        layer, _ = make_layer("Pooling", [(2, 5, 7, 7)],
                              pooling_param=dict(pool="AVE",
                                                 global_pooling=True))
        assert layer.out_shapes() == [(2, 5, 1, 1)]
        x = RNG.randn(2, 5, 7, 7).astype(np.float32)
        (y,) = layer.apply([], [jnp.asarray(x)], False, None)
        np.testing.assert_allclose(y[:, :, 0, 0], x.mean(axis=(2, 3)),
                                   rtol=1e-5)

    def test_stochastic_train_and_test(self):
        layer, _ = make_layer("Pooling", [(1, 1, 4, 4)],
                              pooling_param=dict(pool="STOCHASTIC",
                                                 kernel_size=2, stride=2))
        x = jnp.abs(jnp.asarray(RNG.randn(1, 1, 4, 4), jnp.float32)) + 0.1
        (y,) = layer.apply([], [x], True, jax.random.PRNGKey(0))
        # every sampled value must be one of the window members
        xa = np.asarray(x).reshape(2, 2, 2, 2)
        for i in range(2):
            for j in range(2):
                win = np.asarray(x)[0, 0, 2*i:2*i+2, 2*j:2*j+2].ravel()
                assert float(y[0, 0, i, j]) in [float(v) for v in win]
        (yt,) = layer.apply([], [x], False, None)
        xs = np.asarray(x)
        for i in range(2):
            for j in range(2):
                win = xs[0, 0, 2*i:2*i+2, 2*j:2*j+2].ravel()
                np.testing.assert_allclose(
                    yt[0, 0, i, j], (win ** 2).sum() / win.sum(), rtol=1e-5)

    @pytest.mark.parametrize("method", ["MAX", "AVE"])
    def test_gradcheck(self, method):
        layer, _ = make_layer("Pooling", [(1, 2, 4, 4)],
                              pooling_param=dict(pool=method, kernel_size=3,
                                                 stride=2, pad=1))
        # distinct values keep max-pool away from ties
        x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4) / 7.0
        x += 0.01 * RNG.randn(*x.shape).astype(np.float32)
        check_grad(lambda v: (layer.apply([], [v], False, None)[0]
                              * jnp.arange(18.0).reshape(1, 2, 3, 3)).sum(),
                   x, step=1e-3)


class TestLRN:
    def test_across_channels_formula(self):
        layer, _ = make_layer("LRN", [(1, 5, 2, 2)],
                              lrn_param=dict(local_size=3, alpha=0.1,
                                             beta=0.75))
        x = RNG.rand(1, 5, 2, 2).astype(np.float32)
        (y,) = layer.apply([], [jnp.asarray(x)], False, None)
        # channel 2 at (0,0): window channels 1..3
        s = 1.0 + (0.1 / 3) * (x[0, 1:4, 0, 0] ** 2).sum()
        np.testing.assert_allclose(y[0, 2, 0, 0], x[0, 2, 0, 0] * s ** -0.75,
                                   rtol=1e-5)
        # edge channel 0: window channels 0..1 (zero padded below)
        s0 = 1.0 + (0.1 / 3) * (x[0, 0:2, 0, 0] ** 2).sum()
        np.testing.assert_allclose(y[0, 0, 0, 0], x[0, 0, 0, 0] * s0 ** -0.75,
                                   rtol=1e-5)

    def test_within_channel_formula(self):
        # CIFAR-full config: local_size 3, WITHIN_CHANNEL
        layer, _ = make_layer("LRN", [(1, 1, 3, 3)],
                              lrn_param=dict(local_size=3, alpha=5e-5,
                                             beta=0.75,
                                             norm_region="WITHIN_CHANNEL"))
        x = RNG.rand(1, 1, 3, 3).astype(np.float32)
        (y,) = layer.apply([], [jnp.asarray(x)], False, None)
        # center: full 3x3 window, AVE divisor 9
        s = 1.0 + 5e-5 * ((x[0, 0] ** 2).sum() / 9.0)
        np.testing.assert_allclose(y[0, 0, 1, 1], x[0, 0, 1, 1] * s ** -0.75,
                                   rtol=1e-5)
        # corner (0,0): window [-1,2)x[-1,2) -> 4 real values, divisor 9
        sc = 1.0 + 5e-5 * ((x[0, 0, :2, :2] ** 2).sum() / 9.0)
        np.testing.assert_allclose(y[0, 0, 0, 0], x[0, 0, 0, 0] * sc ** -0.75,
                                   rtol=1e-5)

    @pytest.mark.parametrize("region", ["ACROSS_CHANNELS", "WITHIN_CHANNEL"])
    def test_gradcheck(self, region):
        layer, _ = make_layer("LRN", [(1, 4, 3, 3)],
                              lrn_param=dict(local_size=3, alpha=0.05,
                                             beta=0.75, norm_region=region))
        x = np.asarray(RNG.randn(1, 4, 3, 3), np.float32)
        wts = jnp.asarray(RNG.rand(1, 4, 3, 3), jnp.float32)
        check_grad(lambda v: (layer.apply([], [v], False, None)[0]
                              * wts).sum(), x, step=1e-2)


class TestInnerProduct:
    def test_forward_and_axis(self):
        layer, _ = make_layer("InnerProduct", [(2, 3, 4, 4)],
                              inner_product_param=dict(num_output=7))
        params = init_params(layer)
        assert params[0].shape == (7, 48)
        x = RNG.randn(2, 3, 4, 4).astype(np.float32)
        (y,) = layer.apply(params, [jnp.asarray(x)], False, None)
        want = x.reshape(2, 48) @ np.asarray(params[0]).T + np.asarray(params[1])
        np.testing.assert_allclose(y, want, rtol=1e-4)

    def test_gradcheck(self):
        layer, _ = make_layer("InnerProduct", [(2, 5)],
                              inner_product_param=dict(num_output=3))
        params = init_params(layer)
        x = np.asarray(RNG.randn(2, 5), np.float32)
        check_grad(lambda v: layer.apply(params, [v], False, None)[0].sum(), x)
        check_grad(lambda w: layer.apply([w, params[1]], [jnp.asarray(x)],
                                         False, None)[0].sum(),
                   np.asarray(params[0]))


class TestActivations:
    def test_relu_and_leaky(self):
        layer, _ = make_layer("ReLU", [(2, 3)])
        x = jnp.asarray([[-1.0, 0.0, 2.0], [3.0, -4.0, 5.0]])
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, [[0, 0, 2], [3, 0, 5]])
        layer, _ = make_layer("ReLU", [(2, 3)],
                              relu_param=dict(negative_slope=0.1))
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, [[-0.1, 0, 2], [3, -0.4, 5]], rtol=1e-6)

    def test_prelu(self):
        layer, _ = make_layer("PReLU", [(2, 3, 2, 2)])
        params = [jnp.asarray([0.1, 0.2, 0.3])]
        x = -jnp.ones((2, 3, 2, 2))
        (y,) = layer.apply(params, [x], False, None)
        np.testing.assert_allclose(y[0, :, 0, 0], [-0.1, -0.2, -0.3],
                                   rtol=1e-6)

    def test_dropout_train_test(self):
        layer, _ = make_layer("Dropout", [(1000,)],
                              dropout_param=dict(dropout_ratio=0.3))
        x = jnp.ones((1000,))
        (y,) = layer.apply([], [x], True, jax.random.PRNGKey(0))
        kept = float((y > 0).mean())
        assert abs(kept - 0.7) < 0.05
        np.testing.assert_allclose(np.asarray(y)[np.asarray(y) > 0],
                                   1.0 / 0.7, rtol=1e-5)
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, x)

    def test_power_exp_log_bnll_threshold_absval(self):
        x = jnp.asarray([[0.5, 1.0, 2.0]])
        layer, _ = make_layer("Power", [(1, 3)],
                              power_param=dict(power=2.0, scale=3.0,
                                               shift=1.0))
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, (1 + 3 * np.asarray(x)) ** 2, rtol=1e-5)
        layer, _ = make_layer("Exp", [(1, 3)],
                              exp_param=dict(base=2.0))
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, 2.0 ** np.asarray(x), rtol=1e-5)
        layer, _ = make_layer("Log", [(1, 3)])
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, np.log(np.asarray(x)), rtol=1e-5)
        layer, _ = make_layer("BNLL", [(1, 3)])
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, np.log1p(np.exp(np.asarray(x))),
                                   rtol=1e-5)
        layer, _ = make_layer("Threshold", [(1, 3)],
                              threshold_param=dict(threshold=0.75))
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(y, [[0.0, 1.0, 1.0]])
        layer, _ = make_layer("AbsVal", [(1, 3)])
        (y,) = layer.apply([], [-x], False, None)
        np.testing.assert_allclose(y, x)

    @pytest.mark.parametrize("ltype", ["Sigmoid", "TanH", "BNLL", "PReLU"])
    def test_gradcheck(self, ltype):
        layer, _ = make_layer(ltype, [(2, 3)])
        params = init_params(layer)
        x = np.asarray(RNG.randn(2, 3), np.float32) + 0.2
        check_grad(lambda v: (layer.apply(params, [v], False, None)[0]
                              * jnp.asarray([[1., 2, 3], [4, 5, 6]])).sum(), x)


class TestBatchNorm:
    def test_train_normalizes_and_updates_state(self):
        layer, _ = make_layer("BatchNorm", [(4, 3, 2, 2)])
        state = [jnp.zeros(3), jnp.zeros(3), jnp.zeros(1)]
        x = jnp.asarray(RNG.randn(4, 3, 2, 2) * 2 + 1, jnp.float32)
        (y,), st = layer.apply_stateful([], state, [x], True,
                                        jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(y).mean(axis=(0, 2, 3)), 0,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(y).var(axis=(0, 2, 3)), 1,
                                   atol=1e-3)
        np.testing.assert_allclose(st[2], [1.0])
        m = 16
        np.testing.assert_allclose(
            st[1], np.asarray(x).var(axis=(0, 2, 3)) * m / (m - 1), rtol=1e-4)

    def test_global_stats(self):
        layer, _ = make_layer("BatchNorm", [(4, 2, 1, 1)], phase=1)
        assert layer.use_global
        mean = jnp.asarray([1.0, 2.0])
        var = jnp.asarray([4.0, 9.0])
        state = [mean * 2, var * 2, jnp.asarray([2.0])]  # scale factor 2
        x = jnp.zeros((4, 2, 1, 1))
        (y,), st = layer.apply_stateful([], state, [x], False, None)
        want = (0 - np.asarray(mean)) / np.sqrt(np.asarray(var) + 1e-5)
        np.testing.assert_allclose(y[0, :, 0, 0], want, rtol=1e-4)


class TestStructural:
    def test_softmax(self):
        layer, _ = make_layer("Softmax", [(2, 5)])
        x = RNG.randn(2, 5).astype(np.float32)
        (y,) = layer.apply([], [jnp.asarray(x)], False, None)
        e = np.exp(x - x.max(1, keepdims=True))
        np.testing.assert_allclose(y, e / e.sum(1, keepdims=True), rtol=1e-5)

    def test_concat_slice_roundtrip(self):
        a = jnp.asarray(RNG.randn(2, 3, 2, 2), jnp.float32)
        b = jnp.asarray(RNG.randn(2, 5, 2, 2), jnp.float32)
        layer, _ = make_layer("Concat", [(2, 3, 2, 2), (2, 5, 2, 2)])
        (y,) = layer.apply([], [a, b], False, None)
        assert y.shape == (2, 8, 2, 2)
        lp = Message("LayerParameter", name="s", type="Slice",
                     top=["t1", "t2"], slice_param=dict(slice_point=[3]))
        sl = get_layer("Slice")(lp, [(2, 8, 2, 2)], 0)
        t1, t2 = sl.apply([], [y], False, None)
        np.testing.assert_allclose(t1, a)
        np.testing.assert_allclose(t2, b)

    def test_flatten_reshape(self):
        layer, _ = make_layer("Flatten", [(2, 3, 4, 5)])
        assert layer.out_shapes() == [(2, 60)]
        layer, _ = make_layer(
            "Reshape", [(2, 8)],
            reshape_param=dict(shape=dict(dim=[0, 2, -1])))
        assert layer.out_shapes() == [(2, 2, 4)]
        layer, _ = make_layer(
            "Reshape", [(2, 8)],
            reshape_param=dict(shape=dict(dim=[2, 4]), axis=1))
        assert layer.out_shapes() == [(2, 2, 4)]

    def test_eltwise(self):
        a = jnp.asarray([[1.0, 2]])
        b = jnp.asarray([[3.0, 4]])
        for op, want in [("PROD", [[3, 8]]), ("SUM", [[4, 6]]),
                         ("MAX", [[3, 4]])]:
            layer, _ = make_layer("Eltwise", [(1, 2), (1, 2)],
                                  eltwise_param=dict(operation=op))
            (y,) = layer.apply([], [a, b], False, None)
            np.testing.assert_allclose(y, want)
        layer, _ = make_layer("Eltwise", [(1, 2), (1, 2)],
                              eltwise_param=dict(operation="SUM",
                                                 coeff=[2.0, -1.0]))
        (y,) = layer.apply([], [a, b], False, None)
        np.testing.assert_allclose(y, [[-1, 0]])

    def test_tile_argmax_reduction(self):
        layer, _ = make_layer("Tile", [(2, 3)], tile_param=dict(tiles=2))
        (y,) = layer.apply([], [jnp.asarray([[1., 2, 3], [4, 5, 6]])],
                           False, None)
        assert y.shape == (2, 6)
        layer, _ = make_layer("ArgMax", [(2, 4)])
        (y,) = layer.apply([], [jnp.asarray([[1., 9, 2, 3], [7, 1, 8, 2]])],
                           False, None)
        np.testing.assert_allclose(y[:, 0, 0], [1, 2])
        layer, _ = make_layer("Reduction", [(2, 3)],
                              reduction_param=dict(operation="MEAN", axis=1,
                                                   coeff=2.0))
        (y,) = layer.apply([], [jnp.asarray([[1., 2, 3], [4, 5, 6]])],
                           False, None)
        np.testing.assert_allclose(y, [4.0, 10.0])

    def test_embed_batchreindex(self):
        layer, _ = make_layer("Embed", [(4,)],
                              embed_param=dict(num_output=3, input_dim=5))
        params = init_params(layer)
        idx = jnp.asarray([0, 2, 4, 2])
        (y,) = layer.apply(params, [idx], False, None)
        np.testing.assert_allclose(
            y, np.asarray(params[0])[np.asarray(idx)] + np.asarray(params[1]),
            rtol=1e-5)
        layer, _ = make_layer("BatchReindex", [(3, 2), (4,)])
        (y,) = layer.apply([], [jnp.asarray([[1., 1], [2, 2], [3, 3]]),
                                jnp.asarray([2, 0, 1, 1])], False, None)
        np.testing.assert_allclose(y[:, 0], [3, 1, 2, 2])

    def test_mvn(self):
        layer, _ = make_layer("MVN", [(2, 3, 4, 4)])
        x = jnp.asarray(RNG.randn(2, 3, 4, 4) * 3 + 2, jnp.float32)
        (y,) = layer.apply([], [x], False, None)
        np.testing.assert_allclose(np.asarray(y).mean(axis=(2, 3)), 0,
                                   atol=1e-5)
        std = np.asarray(y).std(axis=(2, 3))
        np.testing.assert_allclose(std, 1.0, atol=1e-2)


class TestLosses:
    def test_softmax_loss_uniform(self):
        layer, _ = make_layer("SoftmaxWithLoss", [(4, 10), (4,)])
        x = jnp.zeros((4, 10))
        lab = jnp.asarray([1, 2, 3, 4])
        (loss,) = layer.apply([], [x, lab], True, None)
        np.testing.assert_allclose(loss, np.log(10), rtol=1e-5)

    def test_softmax_loss_spatial_and_ignore(self):
        lp = Message("LayerParameter", type="SoftmaxWithLoss",
                     loss_param=dict(ignore_label=255))
        layer = get_layer("SoftmaxWithLoss")(lp, [(2, 3, 2, 2), (2, 2, 2)], 0)
        x = jnp.asarray(RNG.randn(2, 3, 2, 2), jnp.float32)
        lab = np.zeros((2, 2, 2), np.int32)
        lab[1, 1, 1] = 255
        (loss,) = layer.apply([], [x, jnp.asarray(lab)], True, None)
        # manual
        xs = np.asarray(x)
        e = np.exp(xs - xs.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        total, cnt = 0.0, 0
        for i in range(2):
            for h in range(2):
                for w in range(2):
                    if lab[i, h, w] == 255:
                        continue
                    total -= np.log(p[i, lab[i, h, w], h, w])
                    cnt += 1
        np.testing.assert_allclose(loss, total / cnt, rtol=1e-5)

    def test_softmax_loss_gradcheck(self):
        layer, _ = make_layer("SoftmaxWithLoss", [(3, 5), (3,)])
        lab = jnp.asarray([0, 2, 4])
        x = np.asarray(RNG.randn(3, 5), np.float32)
        check_grad(lambda v: layer.apply([], [v, lab], True, None)[0], x)

    def test_euclidean(self):
        layer, _ = make_layer("EuclideanLoss", [(4, 3), (4, 3)])
        a = jnp.asarray(RNG.randn(4, 3), jnp.float32)
        b = jnp.asarray(RNG.randn(4, 3), jnp.float32)
        (loss,) = layer.apply([], [a, b], True, None)
        np.testing.assert_allclose(
            loss, ((np.asarray(a) - np.asarray(b)) ** 2).sum() / 8, rtol=1e-5)
        x = np.asarray(a)
        check_grad(lambda v: layer.apply([], [v, b], True, None)[0], x)

    def test_hinge_l1(self):
        layer, _ = make_layer("HingeLoss", [(2, 3), (2,)])
        x = jnp.asarray([[2.0, -1.0, 0.5], [0.0, 3.0, -2.0]])
        lab = jnp.asarray([0, 1])
        (loss,) = layer.apply([], [x, lab], True, None)
        # i=0: margins max(0, 1 + [-2, -1... wait sign: correct class
        # negated: [1-2, 1-1+... manual:
        m0 = [max(0, 1 - 2.0), max(0, 1 + -1.0), max(0, 1 + 0.5)]
        m1 = [max(0, 1 + 0.0), max(0, 1 - 3.0), max(0, 1 + -2.0)]
        np.testing.assert_allclose(loss, (sum(m0) + sum(m1)) / 2, rtol=1e-5)

    def test_sigmoid_ce(self):
        layer, _ = make_layer("SigmoidCrossEntropyLoss", [(3, 4), (3, 4)])
        x = jnp.asarray(RNG.randn(3, 4), jnp.float32)
        t = jnp.asarray(RNG.rand(3, 4) > 0.5, jnp.float32)
        (loss,) = layer.apply([], [x, t], True, None)
        p = 1 / (1 + np.exp(-np.asarray(x)))
        want = -(np.asarray(t) * np.log(p) +
                 (1 - np.asarray(t)) * np.log(1 - p)).sum() / 3
        np.testing.assert_allclose(loss, want, rtol=1e-4)
        check_grad(lambda v: layer.apply([], [v, t], True, None)[0],
                   np.asarray(x))

    def test_multinomial_and_infogain_identity(self):
        probs = jnp.asarray(RNG.dirichlet(np.ones(4), size=3), jnp.float32)
        lab = jnp.asarray([0, 1, 2])
        layer, _ = make_layer("MultinomialLogisticLoss", [(3, 4), (3,)])
        (loss,) = layer.apply([], [probs, lab], True, None)
        want = -np.log(np.asarray(probs)[np.arange(3), [0, 1, 2]]).sum() / 3
        np.testing.assert_allclose(loss, want, rtol=1e-5)
        # Infogain with identity H == multinomial logistic
        lp = Message("LayerParameter", type="InfogainLoss")
        ig = get_layer("InfogainLoss")(lp, [(3, 4), (3,), (4, 4)], 0)
        (loss2,) = ig.apply([], [probs, lab, jnp.eye(4)], True, None)
        np.testing.assert_allclose(loss2, want, rtol=1e-5)

    def test_contrastive(self):
        a = jnp.asarray(RNG.randn(4, 3), jnp.float32)
        b = jnp.asarray(RNG.randn(4, 3), jnp.float32)
        y = jnp.asarray([1, 0, 1, 0], jnp.float32)
        layer, _ = make_layer("ContrastiveLoss", [(4, 3), (4, 3), (4,)],
                              contrastive_loss_param=dict(margin=2.0))
        (loss,) = layer.apply([], [a, b, y], True, None)
        d = np.asarray(a) - np.asarray(b)
        dsq = (d ** 2).sum(1)
        ya = np.asarray(y)
        want = (ya * dsq + (1 - ya) *
                np.maximum(2.0 - np.sqrt(dsq), 0) ** 2).sum() / 8
        np.testing.assert_allclose(loss, want, rtol=1e-5)

    def test_accuracy_topk(self):
        x = jnp.asarray([[0.1, 0.9, 0.0, 0.0],
                         [0.5, 0.1, 0.4, 0.0],
                         [0.0, 0.2, 0.3, 0.5]])
        lab = jnp.asarray([1, 2, 0])
        layer, _ = make_layer("Accuracy", [(3, 4), (3,)])
        (acc,) = layer.apply([], [x, lab], False, None)
        np.testing.assert_allclose(acc, 1.0 / 3.0, rtol=1e-6)
        layer, _ = make_layer("Accuracy", [(3, 4), (3,)],
                              accuracy_param=dict(top_k=2))
        (acc,) = layer.apply([], [x, lab], False, None)
        np.testing.assert_allclose(acc, 2.0 / 3.0, rtol=1e-6)


class TestAttention:
    """The long-context extension layer (ops/attention.py): shape, causal
    masking, and gradient correctness vs central differences."""

    def _layer(self, b=2, s=8, e=12, heads=3, causal=True):
        return make_layer("Attention", [(b, s, e)],
                          attention_param=dict(num_heads=heads,
                                               causal=causal))

    def test_forward_shape_and_causality(self):
        layer, _ = self._layer()
        params = init_params(layer)
        x = jnp.asarray(RNG.randn(2, 8, 12), jnp.float32)
        (y,) = layer.apply(params, [x], False, None)
        assert y.shape == (2, 8, 12)
        # causality: perturbing a LATER position must not change earlier rows
        x2 = np.asarray(x).copy()
        x2[:, 5] += 10.0
        (y2,) = layer.apply(params, [jnp.asarray(x2)], False, None)
        np.testing.assert_allclose(np.asarray(y)[:, :5],
                                   np.asarray(y2)[:, :5], atol=1e-5)
        assert not np.allclose(np.asarray(y)[:, 5:], np.asarray(y2)[:, 5:])

    def test_gradient_wrt_input(self):
        layer, _ = self._layer(b=1, s=4, e=6, heads=2)
        params = init_params(layer)
        x = 0.5 * RNG.randn(1, 4, 6)

        def f(v):
            (y,) = layer.apply(params, [v], True, None)
            return jnp.sum(y * jnp.asarray(WEIGHTS_A[: y.size]
                                           .reshape(y.shape)))
        check_grad(f, x, step=1e-3, tol=2e-2)

    def test_gradient_wrt_qkv_weight(self):
        layer, _ = self._layer(b=1, s=4, e=6, heads=2)
        params = init_params(layer)
        x = jnp.asarray(0.5 * RNG.randn(1, 4, 6), jnp.float32)

        def f(w):
            (y,) = layer.apply([w] + params[1:], [x], True, None)
            return jnp.sum(y * jnp.asarray(WEIGHTS_A[: y.size]
                                           .reshape(y.shape)))
        check_grad(f, np.asarray(params[0]), step=1e-3, tol=2e-2)


WEIGHTS_A = np.linspace(-1.0, 1.0, 4096).astype(np.float32)


# -- Filter (capacity-padded semantics; see ops/structural.py) -------------

def _filter_layer(bottom_shapes, ntops, name="filt"):
    lp = Message("LayerParameter", name=name, type="Filter")
    lp.bottom.extend([f"b{i}" for i in range(len(bottom_shapes))])
    lp.top.extend([f"t{i}" for i in range(ntops)])
    return get_layer("Filter")(lp, bottom_shapes, 0)


def test_filter_compacts_selected_rows_and_zero_pads():
    layer = _filter_layer([(5, 3), (5,), (5, 1)], 2)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(5, 3), jnp.float32)
    z = jnp.asarray(rs.randn(5), jnp.float32)
    sel = jnp.asarray([1.0, 0.0, 1.0, 0.0, 1.0]).reshape(5, 1)
    tx, tz = layer.apply([], [x, z, sel], True, None)
    # selected rows 0,2,4 compacted to the front in order; tail zeros
    np.testing.assert_allclose(np.asarray(tx[:3]),
                               np.asarray(x)[[0, 2, 4]])
    np.testing.assert_allclose(np.asarray(tx[3:]), 0.0)
    np.testing.assert_allclose(np.asarray(tz[:3]),
                               np.asarray(z)[[0, 2, 4]])
    np.testing.assert_allclose(np.asarray(tz[3:]), 0.0)
    # full-batch (padded) static shapes
    assert tx.shape == (5, 3) and tz.shape == (5,)


def test_filter_valid_count_top():
    layer = _filter_layer([(4, 2), (4,)], 2)   # data top + count top
    assert layer.out_shapes() == [(4, 2), ()]
    sel = jnp.asarray([0.0, 1.0, 1.0, 0.0])
    _, cnt = layer.apply([], [jnp.zeros((4, 2)), sel], True, None)
    assert int(cnt) == 2


def test_filter_gradients_scatter_to_selected_rows():
    """Autodiff through the compaction == filter_layer.cpp Backward_cpu:
    cotangents land on selected rows, zero elsewhere."""
    layer = _filter_layer([(4, 3), (4,)], 2)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 3), jnp.float32)
    sel = jnp.asarray([1.0, 0.0, 0.0, 1.0])
    w = jnp.asarray(rs.randn(4, 3), jnp.float32)

    def f(x):
        y, _ = layer.apply([], [x, sel], True, None)
        return jnp.sum(y * w)

    g = np.asarray(jax.grad(f)(x))
    want = np.zeros((4, 3), np.float32)
    want[0] = np.asarray(w)[0]        # row 0 -> slot 0
    want[3] = np.asarray(w)[1]        # row 3 -> slot 1
    np.testing.assert_allclose(g, want, atol=1e-6)


def test_filter_shape_validation():
    with pytest.raises(ValueError, match="singletons"):
        _filter_layer([(4, 3), (4, 2)], 1)
    with pytest.raises(ValueError, match="batch"):
        _filter_layer([(3, 3), (4,)], 1)
    with pytest.raises(ValueError, match="tops"):
        _filter_layer([(4, 3), (4,)], 3 + 1)


def test_filter_compiles_in_a_net():
    """Filter inside a CompiledNet: static shapes end to end."""
    from sparknet_tpu.models import dsl
    from sparknet_tpu.graph.compiler import CompiledNet, TRAIN
    lp = Message("LayerParameter", name="filt", type="Filter")
    lp.bottom.extend(["x", "sel"])
    lp.top.extend(["xf", "nvalid"])
    npm = dsl.NetParam("t", dsl.RDDLayer("x", [4, 3]),
                       dsl.RDDLayer("sel", [4]), lp)
    net = CompiledNet(npm, TRAIN)
    params, state = net.init(jax.random.PRNGKey(0))
    blobs, _ = net.apply(params, state,
                         {"x": np.ones((4, 3), np.float32),
                          "sel": np.asarray([1, 0, 1, 0], np.float32)},
                         train=True)
    assert blobs["xf"].shape == (4, 3)
    assert int(blobs["nvalid"]) == 2
