"""Model DSL + zoo tests.

Mirrors reference LayerSpec.scala (DSL builds a loadable LeNet; AlexNet
prototxt loads into a solver) and extends it: the programmatic zoo builders
must agree with the stock reference prototxts on parameter shapes/counts
and blob geometry.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sparknet_tpu import proto
from sparknet_tpu.graph import CompiledNet, TRAIN, TEST
from sparknet_tpu.models import dsl, lenet, cifar10_full, caffenet, googlenet

REF = "/root/reference/caffe"


def param_shapes_of(net):
    return {k: v[0] for k, v in
            {k: (tuple(s),) for k, (s, f, lr, dc) in
             sorted(net.param_meta.items())}.items()}


class TestDSL:
    def test_rdd_layer_matches_scala_shape(self):
        lp = dsl.RDDLayer("data", [100, 3, 32, 32], include=dsl.TRAIN)
        assert lp.type == "JavaData"
        assert list(lp.java_data_param.shape.dim) == [100, 3, 32, 32]
        assert lp.include[0].enum_name("phase") == "TRAIN"
        assert list(lp.top) == ["data"]

    def test_lenet_via_dsl_builds_and_trains(self):
        net = CompiledNet(lenet(batch_size=8), TRAIN)
        params, state = net.init(jax.random.PRNGKey(0))
        assert params["conv1"][0].shape == (20, 1, 5, 5)
        assert params["ip1"][0].shape == (500, 800)
        batch = {"data": jnp.asarray(
            np.random.RandomState(0).rand(8, 1, 28, 28), jnp.float32),
            "label": jnp.arange(8) % 10}
        loss, _ = net.loss_fn(params, state, batch,
                              rng=jax.random.PRNGKey(1))
        assert abs(float(loss) - np.log(10)) < 0.3

    def test_lenet_matches_reference_prototxt_shapes(self):
        ref = proto.load_prototxt(f"{REF}/examples/mnist/lenet_train_test.prototxt",
                                  "NetParameter")
        refnet = CompiledNet(ref, TRAIN,
                             feed_shapes={"data": (64, 1, 28, 28),
                                          "label": (64,)})
        ours = CompiledNet(lenet(batch_size=64), TRAIN)
        for key in refnet.param_meta:
            assert refnet.param_meta[key][0] == ours.param_meta[key][0], key

    def test_prototxt_emission_roundtrip(self):
        net = lenet(batch_size=4)
        text = proto.format_prototxt(net)
        again = proto.parse_prototxt(text, "NetParameter")
        assert again == net
        CompiledNet(again, TRAIN)  # still compiles


class TestZooParity:
    def test_cifar10_full_matches_reference(self):
        ref = proto.load_prototxt(
            f"{REF}/examples/cifar10/cifar10_full_train_test.prototxt",
            "NetParameter")
        refnet = CompiledNet(ref, TRAIN, feed_shapes={"data": (100, 3, 32, 32),
                                                      "label": (100,)})
        ours = CompiledNet(cifar10_full(batch_size=100), TRAIN)
        assert set(refnet.param_meta) == set(ours.param_meta)
        for key in refnet.param_meta:
            rs, rf, rlr, rdc = refnet.param_meta[key]
            os_, of, olr, odc = ours.param_meta[key]
            assert rs == os_, key
            assert (rlr, rdc) == (olr, odc), key
        # blob geometry identical
        for blob, shape in refnet.blob_shapes.items():
            assert ours.blob_shapes[blob] == shape, blob

    def test_caffenet_matches_reference(self):
        ref = proto.load_prototxt(
            f"{REF}/models/bvlc_reference_caffenet/train_val.prototxt",
            "NetParameter")
        refnet = CompiledNet(ref, TRAIN,
                             feed_shapes={"data": (8, 3, 227, 227),
                                          "label": (8,)})
        ours = CompiledNet(caffenet(batch_size=8), TRAIN)
        assert set(refnet.param_meta) == set(ours.param_meta)
        for key in refnet.param_meta:
            assert refnet.param_meta[key][0] == ours.param_meta[key][0], key
        ref_total = sum(int(np.prod(s)) for s, *_ in refnet.param_meta.values())
        our_total = sum(int(np.prod(s)) for s, *_ in ours.param_meta.values())
        assert ref_total == our_total == 60965224

    def test_googlenet_matches_reference_param_count(self):
        ref = proto.load_prototxt(
            f"{REF}/models/bvlc_googlenet/train_val.prototxt", "NetParameter")
        refnet = CompiledNet(ref, TRAIN,
                             feed_shapes={"data": (2, 3, 224, 224),
                                          "label": (2,)})
        ours = CompiledNet(googlenet(batch_size=2), TRAIN)
        ref_shapes = {k: v[0] for k, v in refnet.param_meta.items()}
        our_shapes = {k: v[0] for k, v in ours.param_meta.items()}
        assert ref_shapes == our_shapes
        assert sorted(ours.output_blobs) == sorted(refnet.output_blobs)

    def test_googlenet_forward(self):
        net = CompiledNet(googlenet(batch_size=2, with_aux=False), TRAIN)
        params, state = net.init(jax.random.PRNGKey(0))
        batch = {"data": jnp.asarray(
            np.random.RandomState(0).randn(2, 3, 224, 224) * 0.1,
            jnp.float32), "label": jnp.asarray([1, 2])}
        loss, (blobs, _) = net.loss_fn(params, state, batch,
                                       rng=jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        assert blobs["pool5/7x7_s1"].shape == (2, 1024, 1, 1)
