"""Graph-compiler tests: phase filtering, in-place SSA, param sharing,
weight IO, and whole-net builds from stock reference prototxts (the
capability checks mirroring reference net.cpp behaviors and LayerSpec.scala).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sparknet_tpu import proto
from sparknet_tpu.proto import Message
from sparknet_tpu.graph import CompiledNet, filter_net, upgrade_v1, TRAIN, TEST

REF = "/root/reference/caffe"
CIFAR_SHAPES = {"data": (4, 3, 32, 32), "label": (4,)}


def load_cifar_net():
    return proto.load_prototxt(
        f"{REF}/examples/cifar10/cifar10_full_train_test.prototxt",
        "NetParameter")


def tiny_mlp(loss_weight=None):
    net = Message("NetParameter", name="tiny")
    net.add("layer", name="d", type="JavaData", top=["data"],
            java_data_param=dict(shape=dict(dim=[4, 6])))
    net.add("layer", name="l", type="JavaData", top=["label"],
            java_data_param=dict(shape=dict(dim=[4])))
    net.add("layer", name="fc1", type="InnerProduct", bottom=["data"],
            top=["fc1"], inner_product_param=dict(
                num_output=5, weight_filler=dict(type="xavier")))
    net.add("layer", name="relu1", type="ReLU", bottom=["fc1"], top=["fc1"])
    net.add("layer", name="fc2", type="InnerProduct", bottom=["fc1"],
            top=["fc2"], inner_product_param=dict(
                num_output=3, weight_filler=dict(type="xavier")))
    loss = net.add("layer", name="loss", type="SoftmaxWithLoss",
                   bottom=["fc2", "label"], top=["loss"])
    if loss_weight is not None:
        loss.loss_weight.append(loss_weight)
    return net


class TestPhaseFiltering:
    def test_cifar_phases(self):
        net = load_cifar_net()
        tr = filter_net(net, TRAIN)
        te = filter_net(net, TEST)
        tr_names = [l.name for l in tr.layer]
        te_names = [l.name for l in te.layer]
        assert tr_names.count("cifar") == 1  # one data layer per phase
        assert te_names.count("cifar") == 1
        assert "accuracy" not in tr_names
        assert "accuracy" in te_names

    def test_exclude_rule(self):
        net = tiny_mlp()
        net.layer[2].add("exclude", phase="TEST")
        te = filter_net(net, TEST)
        assert "fc1" not in [l.name for l in te.layer]

    def test_stage_rules(self):
        net = tiny_mlp()
        net.layer[2].add("include", stage=["deploy"])
        assert "fc1" not in [l.name for l in filter_net(net, TRAIN).layer]
        assert "fc1" in [l.name for l in
                         filter_net(net, TRAIN, stages=("deploy",)).layer]


class TestBuild:
    def test_inplace_ssa(self):
        net = CompiledNet(tiny_mlp(), TRAIN)
        params, state = net.init(jax.random.PRNGKey(0))
        batch = {"data": jnp.ones((4, 6)), "label": jnp.zeros((4,), jnp.int32)}
        blobs, _ = net.apply(params, state, batch)
        # relu applied in place onto fc1's blob name
        assert float(blobs["fc1"].min()) >= 0.0
        assert net.output_blobs == ["loss"]

    def test_undefined_bottom_raises(self):
        net = tiny_mlp()
        net.layer[2].bottom[0] = "nonexistent"
        with pytest.raises(ValueError, match="undefined"):
            CompiledNet(net, TRAIN)

    def test_feed_shapes_required_for_db_layers(self):
        net = load_cifar_net()
        with pytest.raises(ValueError, match="feed_shapes"):
            CompiledNet(net, TRAIN)

    def test_cifar_full_shapes(self):
        net = CompiledNet(load_cifar_net(), TRAIN, feed_shapes=CIFAR_SHAPES)
        # caffe's published blob progression for cifar10_full
        assert net.blob_shapes["conv1"] == (4, 32, 32, 32)
        assert net.blob_shapes["pool1"] == (4, 32, 16, 16)
        assert net.blob_shapes["norm1"] == (4, 32, 16, 16)
        assert net.blob_shapes["conv2"] == (4, 32, 16, 16)
        assert net.blob_shapes["pool2"] == (4, 32, 8, 8)
        assert net.blob_shapes["conv3"] == (4, 64, 8, 8)
        assert net.blob_shapes["pool3"] == (4, 64, 4, 4)
        assert net.blob_shapes["ip1"] == (4, 10)

    def test_caffenet_param_count(self):
        npm = proto.load_prototxt(
            f"{REF}/models/bvlc_reference_caffenet/train_val.prototxt",
            "NetParameter")
        net = CompiledNet(npm, TRAIN,
                          feed_shapes={"data": (2, 3, 227, 227),
                                       "label": (2,)})
        total = sum(int(v.size) for _, (s, f, lr, dc) in
                    sorted(net.param_meta.items())
                    for v in [np.zeros(s)])
        assert total == 60965224  # canonical AlexNet/CaffeNet 61M

    def test_googlenet_builds_with_three_losses(self):
        npm = proto.load_prototxt(
            f"{REF}/models/bvlc_googlenet/train_val.prototxt", "NetParameter")
        net = CompiledNet(npm, TRAIN,
                          feed_shapes={"data": (2, 3, 224, 224),
                                       "label": (2,)})
        assert sorted(net.output_blobs) == [
            "loss1/loss1", "loss2/loss1", "loss3/loss3"]
        # aux losses weighted 0.3 (train_val.prototxt)
        w = {l.name: ws for (l, i, b, t), ws in
             zip(net.layers, [net.loss_weights[l.name]
                              for l, _, _, _ in net.layers])}
        assert w["loss1/loss"] == [pytest.approx(0.3)]
        assert w["loss3/loss3"] == [1.0]

    def test_deploy_net_inputs(self):
        npm = proto.load_prototxt(
            f"{REF}/models/bvlc_googlenet/deploy.prototxt", "NetParameter")
        net = CompiledNet(npm, TEST)
        assert net.net_inputs == ["data"]
        assert net.blob_shapes["data"] == (10, 3, 224, 224)
        assert net.output_blobs == ["prob"]


class TestForward:
    def test_uniform_logits_loss(self):
        net = CompiledNet(load_cifar_net(), TRAIN, feed_shapes=CIFAR_SHAPES)
        params, state = net.init(jax.random.PRNGKey(0))
        batch = {"data": jnp.zeros((4, 3, 32, 32)),
                 "label": jnp.zeros((4,), jnp.int32)}
        loss, (blobs, _) = net.loss_fn(params, state, batch,
                                       rng=jax.random.PRNGKey(1))
        # gaussian-initialized tiny weights -> near-uniform logits
        assert abs(float(loss) - np.log(10)) < 0.1

    def test_loss_weight_scaling(self):
        net1 = CompiledNet(tiny_mlp(), TRAIN)
        net2 = CompiledNet(tiny_mlp(loss_weight=2.5), TRAIN)
        params, state = net1.init(jax.random.PRNGKey(0))
        batch = {"data": jnp.ones((4, 6)),
                 "label": jnp.zeros((4,), jnp.int32)}
        l1, _ = net1.loss_fn(params, state, batch)
        l2, _ = net2.loss_fn(params, state, batch)
        np.testing.assert_allclose(float(l2), 2.5 * float(l1), rtol=1e-6)

    def test_grad_flows_to_all_params(self):
        net = CompiledNet(load_cifar_net(), TRAIN, feed_shapes=CIFAR_SHAPES)
        params, state = net.init(jax.random.PRNGKey(0))
        batch = {"data": jnp.asarray(
            np.random.RandomState(0).randn(4, 3, 32, 32), jnp.float32),
            "label": jnp.asarray([0, 1, 2, 3])}
        g = jax.grad(lambda p: net.loss_fn(p, state, batch,
                                           rng=jax.random.PRNGKey(1))[0])(params)
        for lname, blobs in g.items():
            for i, b in enumerate(blobs):
                assert float(jnp.abs(b).max()) > 0, f"{lname}[{i}] zero grad"

    def test_train_vs_test_determinism(self):
        net = CompiledNet(load_cifar_net(), TEST, feed_shapes=CIFAR_SHAPES)
        params, state = net.init(jax.random.PRNGKey(0))
        batch = {"data": jnp.ones((4, 3, 32, 32)),
                 "label": jnp.zeros((4,), jnp.int32)}
        b1, _ = net.apply(params, state, batch)
        b2, _ = net.apply(params, state, batch)
        np.testing.assert_array_equal(b1["accuracy"], b2["accuracy"])


class TestParamSharing:
    def test_shared_by_name(self):
        net = Message("NetParameter")
        net.add("layer", name="d", type="JavaData", top=["data"],
                java_data_param=dict(shape=dict(dim=[2, 4])))
        l1 = net.add("layer", name="a", type="InnerProduct", bottom=["data"],
                     top=["a"], inner_product_param=dict(
                         num_output=4, bias_term=False,
                         weight_filler=dict(type="xavier")))
        l1.add("param", name="w_shared")
        l2 = net.add("layer", name="b", type="InnerProduct", bottom=["a"],
                     top=["b"], inner_product_param=dict(
                         num_output=4, bias_term=False))
        l2.add("param", name="w_shared")
        cn = CompiledNet(net, TRAIN)
        params, state = cn.init(jax.random.PRNGKey(0))
        assert "a" in params and "b" not in params
        pa = cn.resolve_params(params, "a")
        pb = cn.resolve_params(params, "b")
        assert pa[0] is pb[0]


class TestWeightIO:
    def test_netproto_roundtrip(self):
        cn = CompiledNet(tiny_mlp(), TRAIN)
        params, state = cn.init(jax.random.PRNGKey(42))
        npz = cn.params_to_netproto(params, state)
        # re-init differently, then load back
        params2, state2 = cn.init(jax.random.PRNGKey(7))
        loaded, _ = cn.load_netproto(npz, params2, state2)
        for lname in params:
            for a, b in zip(params[lname], loaded[lname]):
                np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_load_via_wire_format(self, tmp_path):
        cn = CompiledNet(tiny_mlp(), TRAIN)
        params, state = cn.init(jax.random.PRNGKey(42))
        npz = cn.params_to_netproto(params, state)
        path = str(tmp_path / "model.caffemodel")
        proto.save_binaryproto(npz, path)
        re = proto.load_binaryproto(path, "NetParameter")
        params2, _ = cn.load_netproto(re, *cn.init(jax.random.PRNGKey(7)))
        np.testing.assert_allclose(params["fc1"][0], params2["fc1"][0],
                                   rtol=1e-6)

    def test_size_mismatch_raises(self):
        cn = CompiledNet(tiny_mlp(), TRAIN)
        params, state = cn.init(jax.random.PRNGKey(0))
        bad = cn.params_to_netproto(params)
        bad.layer[2].blobs[0].ensure("shape").dim[0] = 999
        bad.layer[2].blobs[0].data.append(0.0)
        with pytest.raises(ValueError, match="mismatch"):
            cn.load_netproto(bad, params, state)


class TestV1Upgrade:
    def test_v1_layers_upgrade(self):
        net = Message("NetParameter", name="old")
        v1 = net.add("layers", name="ip", type="INNER_PRODUCT",
                     bottom=["data"], top=["out"],
                     inner_product_param=dict(num_output=3))
        v1.blobs_lr.extend([1.0, 2.0])
        v1.weight_decay.extend([1.0, 0.0])
        up = upgrade_v1(net)
        assert up.layer[0].type == "InnerProduct"
        assert up.layer[0].param[0].lr_mult == 1.0
        assert up.layer[0].param[1].lr_mult == 2.0
        assert up.layer[0].param[1].decay_mult == 0.0
        assert not up.layers


# every remaining stock net prototxt in the reference tree compiles AND
# runs one forward (the "a reference user finds everything they need" bar;
# quick/full/caffenet/googlenet/lenet_train_test are covered above).
# Second element: the feed_shapes override standing in for the prototxt's
# data source (None = deploy net, shapes come from its `input` decl).
_STOCK_NETS = [
    ("examples/cifar10/cifar10_full_sigmoid_train_test.prototxt",
     {"data": (2, 3, 32, 32), "label": (2,)}),
    ("examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt",
     {"data": (2, 3, 32, 32), "label": (2,)}),
    ("models/bvlc_alexnet/train_val.prototxt",
     {"data": (2, 3, 227, 227), "label": (2,)}),
    ("models/finetune_flickr_style/train_val.prototxt",
     {"data": (2, 3, 227, 227), "label": (2,)}),
    ("examples/mnist/lenet.prototxt", None),   # deploy net: `input` blobs
    # deploy-only R-CNN variant (fc-rcnn 200-way head on caffenet trunk)
    ("models/bvlc_reference_rcnn_ilsvrc13/deploy.prototxt", None),
    # HDF5Data logreg/MLP examples (4-feature vectors)
    ("examples/hdf5_classification/train_val.prototxt",
     {"data": (2, 4), "label": (2,)}),
    ("examples/hdf5_classification/nonlinear_train_val.prototxt",
     {"data": (2, 4), "label": (2,)}),
    # siamese twins: Slice of the stacked pair + SHARED conv/fc params
    # (`param { name: ... }` cross-layer sharing) + ContrastiveLoss
    ("examples/siamese/mnist_siamese_train_test.prototxt",
     {"pair_data": (2, 2, 28, 28), "sim": (2,)}),
    ("examples/siamese/mnist_siamese.prototxt", None),
    # WindowData fine-tuning net (window_data_param source absent ->
    # feeds stand in, like the other data layers)
    ("examples/finetune_pascal_detection/pascal_finetune_trainval_test"
     ".prototxt", {"data": (2, 3, 227, 227), "label": (2,)}),
    # sliced multi-loss autoencoder (label-free Data layer, Sigmoid
    # stack, SigmoidCrossEntropy + Euclidean losses off one Slice)
    ("examples/mnist/mnist_autoencoder.prototxt",
     {"data": (2, 1, 28, 28)}),
    # net-surgery pair: the 1x1-conv toy and the fully-convolutional
    # CaffeNet rewrite (deploy nets: `input` decls)
    ("examples/net_surgery/conv.prototxt", None),
    ("examples/net_surgery/bvlc_caffenet_full_conv.prototxt", None),
    # feature-extraction net (ImageData source -> feeds stand in)
    ("examples/feature_extraction/imagenet_val.prototxt",
     {"data": (2, 3, 227, 227), "label": (2,)}),
]

_INT_FEEDS = ("label", "sim")


@pytest.mark.parametrize("rel,feed", _STOCK_NETS,
                         ids=[r.split("/")[-1] for r, _ in _STOCK_NETS])
def test_stock_net_compiles_and_forwards(rel, feed):
    npm = proto.load_prototxt(f"{REF}/{rel}", "NetParameter")
    net = CompiledNet(npm, TRAIN, feed_shapes=feed)
    params, state = net.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    batch = {}
    for name, s in net.feed_shapes().items():
        batch[name] = rs.randint(0, 2, s).astype(np.int32) \
            if name in _INT_FEEDS else rs.randn(*s).astype(np.float32)
    blobs, _ = net.apply(params, state, batch, train=False)
    for b in net.output_blobs:
        assert np.isfinite(np.asarray(blobs[b], np.float32)).all(), \
            f"{rel}: non-finite output {b}"
