"""Host-level fault domains (resilience/heartbeat.py) + the hierarchical
runtime's host-side pieces, exercised single-process: leased heartbeats,
lease-expiry death, the round gate, FileConsensus masked averaging with
authority failover, the coordinated-restart barrier, host-granularity
chaos injectors, and the checkpoint world-mismatch guard."""

import json
import os
import time

import numpy as np
import pytest

from conftest import REFERENCE  # noqa: F401  (conftest sets the cpu env)

from sparknet_tpu.resilience.heartbeat import (
    HeartbeatCoordinator, FileConsensus, manifest_sha, restart_barrier)
from sparknet_tpu.resilience.chaos import ChaosMonkey
from sparknet_tpu.resilience import checkpoint
from sparknet_tpu.resilience.elastic import ElasticPolicy, QuorumLost


class _Sink:
    def __init__(self):
        self.events = []

    def log(self, event, **fields):
        self.events.append(dict(fields, event=event))

    def kinds(self):
        return [e["event"] for e in self.events]


def _coord(tmp_path, host, n, interval=0.05, lease=0.4, **kw):
    return HeartbeatCoordinator(str(tmp_path), host=host, n_hosts=n,
                                interval_s=interval, lease_s=lease,
                                log_fn=lambda *a: None, **kw)


# --------------------------------------------------------------- leases ----
class TestLeases:
    def test_beat_writes_lease_and_peer_sees_alive(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            alive, age = a.view()
            assert list(alive) == [True, True]
            assert age[1] < 0.4
        finally:
            a.stop()
            b.stop()

    def test_lease_expiry_marks_host_dead(self, tmp_path):
        sink = _Sink()
        a = _coord(tmp_path, 0, 2, metrics=sink).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            b.stop()                     # host 1 goes silent
            deadline = time.time() + 5
            while time.time() < deadline:
                alive, _ = a.view()
                if not alive[1]:
                    break
                time.sleep(0.05)
            alive, age = a.view()
            assert not alive[1] and age[1] > a.lease_s
            # self is always alive to itself
            assert alive[0]
        finally:
            a.stop()

    def test_host_alive_transition_event_emitted(self, tmp_path):
        sink = _Sink()
        a = _coord(tmp_path, 0, 2, metrics=sink).start()
        b = _coord(tmp_path, 1, 2).start()
        b.stop()
        try:
            deadline = time.time() + 5
            while time.time() < deadline:
                if any(e["event"] == "host_alive" and not e["alive"]
                       for e in sink.events):
                    break
                time.sleep(0.05)
            ev = [e for e in sink.events
                  if e["event"] == "host_alive" and e["host"] == 1]
            assert ev and ev[-1]["alive"] is False
            assert ev[-1]["lease_age_s"] > a.lease_s
        finally:
            a.stop()

    def test_startup_grace_then_dead(self, tmp_path):
        # peer never starts: alive through one lease of grace, then dead
        a = _coord(tmp_path, 0, 2).start()
        try:
            alive, _ = a.view()
            assert alive[1], "startup grace should cover a late joiner"
            time.sleep(a.lease_s + 0.2)
            alive, _ = a.view()
            assert not alive[1]
        finally:
            a.stop()

    def test_bad_lease_config_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="lease_s"):
            _coord(tmp_path, 0, 2, interval=1.0, lease=0.5)
        with pytest.raises(ValueError, match="world"):
            _coord(tmp_path, 5, 2)


# ----------------------------------------------------------------- gate ----
class TestGate:
    def test_gate_passes_when_all_arrive(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            b.announce_round(3)
            res = a.gate(3)
            assert res.arrived == [1] and res.dead == []
        finally:
            a.stop()
            b.stop()

    def test_gate_reports_dead_peer_not_hang(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            b.announce_round(0)
            a.gate(0)
            b.stop()                     # dies between rounds
            t0 = time.time()
            res = a.gate(1)
            assert res.dead == [1] and res.arrived == []
            # bounded by the lease, not a hang
            assert time.time() - t0 < a.lease_s + 3
        finally:
            a.stop()

    def test_gate_emits_host_round_event(self, tmp_path):
        sink = _Sink()
        a = _coord(tmp_path, 0, 1, metrics=sink).start()
        try:
            a.gate(0)
            ev = [e for e in sink.events if e["event"] == "host_round"]
            assert ev and ev[0]["round"] == 0
            assert "wait_s" in ev[0] and "lease_age_s" in ev[0]
        finally:
            a.stop()


# -------------------------------------------------------- file consensus ----
class TestFileConsensus:
    def test_two_host_masked_average(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            fa, fb = FileConsensus(a), FileConsensus(b)
            la = [np.ones((2, 2), np.float32), np.float32(2.0)]
            lb = [np.full((2, 2), 3.0, np.float32), np.float32(4.0)]
            # post b's part first, then run a's exchange (a is the
            # authority and will find both parts present)
            fb._post(0, lb, True, 1.0)
            out, aux = fa.exchange(0, la, True, 0.5, [0, 1])
            np.testing.assert_allclose(out[0], np.full((2, 2), 2.0))
            np.testing.assert_allclose(out[1], 3.0)
            assert list(aux["valid"]) == [1.0, 1.0]
            assert float(aux["n_live"]) == 2
            np.testing.assert_allclose(aux["worker_loss"], [0.5, 1.0])
            # b computes the IDENTICAL consensus from the same mask file
            out_b, aux_b = fb.exchange(0, lb, True, 1.0, [0, 1])
            np.testing.assert_array_equal(out[0], out_b[0])
        finally:
            a.stop()
            b.stop()

    def test_missing_host_masked_out(self, tmp_path):
        a = _coord(tmp_path, 0, 2, lease=0.3).start()
        try:
            fa = FileConsensus(a)
            la = [np.full((2,), 6.0, np.float32)]
            out, aux = fa.exchange(0, la, True, 0.1, [0, 1], timeout=0.4)
            # host 1 never contributed: consensus is host 0's leaves
            np.testing.assert_allclose(out[0], la[0])
            assert list(aux["valid"]) == [1.0, 0.0]
            assert float(aux["n_live"]) == 1
        finally:
            a.stop()

    def test_invalid_contribution_excluded(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            fa = FileConsensus(a)
            nan = [np.full((2,), np.nan, np.float32)]
            FileConsensus(b)._post(0, nan, False, float("nan"))
            out, aux = fa.exchange(0, [np.ones(2, np.float32)], True,
                                   0.2, [0, 1])
            assert np.isfinite(out[0]).all(), \
                "a NaN'd host poisoned the relay consensus"
            assert list(aux["valid"]) == [1.0, 0.0]
        finally:
            a.stop()

    def test_divergence_aux_matches_hand_computation(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            fa = FileConsensus(a)
            la = [np.zeros(4, np.float32)]
            lb = [np.full(4, 2.0, np.float32)]
            FileConsensus(b)._post(0, lb, True, 0.0)
            out, aux = fa.exchange(0, la, True, 0.0, [0, 1])
            # consensus = 1.0; each host's sq dist = 4 * 1^2 = 4
            np.testing.assert_allclose(aux["div_worker_sq"], [4.0, 4.0])
            np.testing.assert_allclose(aux["div_mean_sq"], 4.0)
        finally:
            a.stop()
            b.stop()

    def test_part_files_garbage_collected(self, tmp_path):
        a = _coord(tmp_path, 0, 1).start()
        try:
            fa = FileConsensus(a)
            for r in range(4):
                fa.exchange(r, [np.ones(2, np.float32)], True, 0.0, [0])
            import glob
            left = glob.glob(os.path.join(str(tmp_path), "part-*.npz"))
            rounds = sorted(int(p.rsplit("-", 1)[1].split(".")[0])
                            for p in left)
            assert rounds == [2, 3], rounds
        finally:
            a.stop()


# ---------------------------------------------------- coordinated restart ----
class TestCoordinatedRestart:
    def test_barrier_agreement(self, tmp_path):
        import threading
        sink = _Sink()
        a = _coord(tmp_path, 0, 2, metrics=sink).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            out = {}

            def side_b():
                out["b"] = restart_barrier(b, "abc123", timeout=10)
            t = threading.Thread(target=side_b)
            t.start()
            agreed_a, shas = restart_barrier(a, "abc123", timeout=10)
            t.join(timeout=15)
            assert agreed_a and out["b"][0]
            assert shas == {0: "abc123", 1: "abc123"}
            ev = [e for e in sink.events
                  if e.get("kind") == "coordinated_restart"]
            assert ev and ev[0]["agreed"]
        finally:
            a.stop()
            b.stop()

    def test_barrier_disagreement_reported(self, tmp_path):
        a = _coord(tmp_path, 0, 2).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            restart_barrier(b, "zzz", timeout=0.2)   # post, don't wait
            agreed, shas = restart_barrier(a, "abc", timeout=10)
            assert not agreed
            assert shas[0] != shas[1]
        finally:
            a.stop()
            b.stop()

    def test_manifest_sha_roundtrip(self, tmp_path):
        prefix = str(tmp_path / "snap")
        assert manifest_sha(prefix) is None
        with open(checkpoint.manifest_path(prefix), "w") as f:
            json.dump({"version": 1}, f)
        sha = manifest_sha(prefix)
        assert isinstance(sha, str) and len(sha) == 64


# ------------------------------------------------------------ host chaos ----
class TestHostChaos:
    def test_kill_host_virtual_feeds_policy(self):
        ch = ChaosMonkey.parse("kill_host=2,kill_host_round=3")
        assert ch.dead_hosts(2, 4) == []
        assert ch.dead_hosts(3, 4) == [2]
        assert ch.dead_hosts(4, 4) == []          # fires once

    def test_kill_host_self_mode_suppresses_virtual(self):
        ch = ChaosMonkey.parse("kill_host=1")
        ch.kill_host_self_mode = True
        assert ch.dead_hosts(0, 4) == []

    def test_maybe_kill_self_only_targets_the_named_host(self):
        ch = ChaosMonkey.parse("kill_host=1,kill_host_round=2")
        # wrong host / too early: no kill (we're alive to assert it)
        assert ch.maybe_kill_self(0, 5) is False
        assert ch.maybe_kill_self(1, 1) is False

    def test_partition_host_cuts_both_directions(self):
        ch = ChaosMonkey.parse("partition_host=1,partition_round=2")
        assert not ch.host_partitioned(0, 1, 1)
        assert ch.host_partitioned(0, 1, 2)
        assert ch.host_partitioned(1, 0, 2)
        assert not ch.host_partitioned(0, 2, 2)
        assert not ch.host_partitioned(1, 1, 2)

    def test_partitioned_peer_appears_dead(self, tmp_path):
        ch = ChaosMonkey.parse("partition_host=1,partition_round=0")
        a = _coord(tmp_path, 0, 2, chaos=ch).start()
        b = _coord(tmp_path, 1, 2).start()
        try:
            a.announce_round(0)
            time.sleep(a.lease_s + 0.2)   # outlive the startup grace
            alive, _ = a.view()
            assert not alive[1], "partitioned peer must appear dead"
        finally:
            a.stop()
            b.stop()

    def test_slow_host_sleeps_and_attributes(self):
        ch = ChaosMonkey.parse("slow_host=1,slow_host_s=0.2")
        t0 = time.time()
        assert ch.maybe_slow_host(0, 0) == 0.0
        sec = ch.maybe_slow_host(1, 0)
        assert sec == pytest.approx(0.2)
        assert time.time() - t0 >= 0.2
        assert ch.pop_slow_host() == (1, 0.2)
        assert ch.pop_slow_host() is None
        assert ch.maybe_slow_host(1, 1) == 0.0    # fires once

    def test_unknown_chaos_keys_still_rejected(self):
        with pytest.raises(ValueError, match="unknown injector"):
            ChaosMonkey.parse("kill_hosts=1")


# ------------------------------------------------- world-mismatch guard ----
def _mini_solver(mesh=None, host_axis=None):
    import jax
    from sparknet_tpu.proto import Message
    from sparknet_tpu.models import zoo
    from sparknet_tpu.parallel import LocalSGDSolver, make_mesh
    sp = Message("SolverParameter", base_lr=0.01, lr_policy="fixed",
                 momentum=0.9, display=0, random_seed=0)
    return LocalSGDSolver(
        sp, mesh=mesh if mesh is not None else make_mesh({"data": 8}),
        tau=1, host_axis=host_axis, net_param=zoo.lenet(batch_size=2),
        log_fn=lambda *a: None)


class TestWorldMismatch:
    def test_world_stamp_in_manifest(self, tmp_path):
        s = _mini_solver()
        prefix = str(tmp_path / "snap")
        s.snapshot(prefix=prefix)
        man = checkpoint.load_manifest(prefix)
        w = man["latest"]["world"]
        assert w["processes"] == 1
        assert w["mesh"] == {"data": 8}

    def test_restore_refuses_wrong_world(self, tmp_path):
        from sparknet_tpu.parallel import make_host_device_mesh
        s = _mini_solver()
        prefix = str(tmp_path / "snap")
        _, state = s.snapshot(prefix=prefix)
        other = _mini_solver(
            mesh=make_host_device_mesh(hosts=2, per_host=4),
            host_axis="host")
        with pytest.raises(checkpoint.WorldMismatch,
                           match="different world"):
            other.restore(state)
        # the message is actionable: names both worlds + the remedy
        try:
            other.restore(state)
        except checkpoint.WorldMismatch as e:
            msg = str(e)
            assert "mesh" in msg and "Relaunch" in msg

    def test_resume_auto_propagates_world_mismatch(self, tmp_path):
        from sparknet_tpu.parallel import make_host_device_mesh
        s = _mini_solver()
        prefix = str(tmp_path / "snap")
        s.snapshot(prefix=prefix)
        other = _mini_solver(
            mesh=make_host_device_mesh(hosts=2, per_host=4),
            host_axis="host")
        # NOT silently skipped-and-started-fresh: the operator must act
        with pytest.raises(checkpoint.WorldMismatch):
            checkpoint.resume_auto(other, prefix, log_fn=lambda *a: None)

    def test_same_world_restores(self, tmp_path):
        s = _mini_solver()
        prefix = str(tmp_path / "snap")
        _, state = s.snapshot(prefix=prefix)
        twin = _mini_solver()
        twin.restore(state)              # no raise
        assert twin.iter == s.iter

    def test_unstamped_legacy_entry_passes(self, tmp_path):
        s = _mini_solver()
        prefix = str(tmp_path / "snap")
        _, state = s.snapshot(prefix=prefix)
        man = checkpoint.load_manifest(prefix)
        for e in man["snapshots"]:
            e.pop("world", None)
        man["latest"].pop("world", None)
        checkpoint._atomic_write_json(checkpoint.manifest_path(prefix), man)
        twin = _mini_solver()
        twin.restore(state)              # pre-stamp snapshots still load


# ------------------------------------- policy wiring at host granularity ----
class TestHostPolicy:
    def test_lease_expired_eviction_reason(self, tmp_path):
        sink = _Sink()
        p = ElasticPolicy(n_workers=3, quorum=1, unit="host",
                          metrics=sink, log_fn=lambda *a: None)
        p.evict(2, 5, "lease_expired")
        assert p.live() == [0, 1]
        ev = [e for e in sink.events if e["event"] == "eviction"]
        assert ev[0]["unit"] == "host"
        assert ev[0]["reason"] == "lease_expired"
        he = [e for e in sink.events if e["event"] == "host_evicted"]
        assert he and he[0]["host"] == 2

    def test_quorum_names_hosts(self):
        p = ElasticPolicy(n_workers=2, quorum=2, unit="host",
                          log_fn=lambda *a: None)
        with pytest.raises(QuorumLost, match="hosts"):
            p.evict(0, 1, "lease_expired")
