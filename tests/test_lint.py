"""sparknet lint (sparknet_tpu.analysis): engine, rule corpus,
baseline add/expire, CLI exit codes, and the repo self-lint gate.

The fixture corpus under tests/fixtures/lint/ carries the expected
finding per line; these tests assert (code, line) EXACTLY, so fixture
edits must update the tables here.
"""

import argparse
import json
import os
import textwrap

import pytest

from sparknet_tpu.analysis import lint_paths, Baseline
from sparknet_tpu.analysis.cli import run_lint, DEFAULT_BASELINE
from sparknet_tpu.cli import main as cli_main

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "lint")


def fixture_findings(name, select=None):
    return lint_paths([os.path.join(FIXTURES, name)], root=FIXTURES,
                      select=select)


def code_lines(findings):
    return sorted((f.code, f.line) for f in findings)


def mk_args(**kw):
    base = dict(paths=[], strict=False, baseline=None,
                write_baseline=False, justification=None, select=None,
                root=None, json=False, verbose=False, list_rules=False,
                exclude=[], jobs=1, cache=False,
                write_event_schema=False)
    base.update(kw)
    return argparse.Namespace(**base)


# ------------------------------------------------------------ rule corpus

class TestJaxRuleCorpus:
    def test_jax_hazards_positive_lines(self):
        got = code_lines(fixture_findings("jax_hazards.py"))
        assert got == sorted([
            ("SPK101", 17),      # float() in traced step
            ("SPK101", 18),      # np.asarray in traced step
            ("SPK101", 19),      # jax.device_get in traced step
            ("SPK102", 22),      # if on traced param
            ("SPK102", 24),      # for over traced param
            ("SPK102", 25),      # mutable module global captured
            ("SPK105", 28),      # jit without donation
            ("SPK102", 61),      # unhashable literal to static arg
        ])

    def test_prng_corpus(self):
        got = code_lines(fixture_findings("prng.py"))
        assert got == sorted([
            ("SPK103", 9),       # param key reused
            ("SPK103", 16),      # local key reused
            ("SPK103", 24),      # outside-loop key consumed in loop
        ])

    def test_axes_corpus(self):
        got = code_lines(fixture_findings("axes.py"))
        assert got == sorted([
            ("SPK104", 25),      # literal mismatch
            ("SPK104", 34),      # module-constant mismatch
            ("SPK104", 43),      # forwarded through masked_mean helper
        ])

    def test_tp_axes_corpus(self):
        # the tensor-parallel helper shapes (fsdp.gather_full forwards
        # its axis to all_gather whose `axis=` kwarg is a DIMENSION —
        # the summarizer must not mistake it for the axis name)
        got = code_lines(fixture_findings("tp_axes.py"))
        assert got == sorted([
            ("SPK104", 33),      # "model" on a data-only mesh
            ("SPK104", 42),      # bad axis through the row-psum helper
            ("SPK104", 51),      # bad axis into axis_index via helper
        ])

    def test_clean_fixture_is_clean(self):
        assert fixture_findings("clean.py") == []

    def test_serve_shaped_jits_are_exempt(self):
        # serving forwards (params/state in, output blobs out — what
        # serve/engine.py jits per bucket) must never be asked to
        # donate; only the update-shaped contrast at the bottom fires
        got = code_lines(fixture_findings("serve_jit.py"))
        assert got == [
            ("SPK105", 52),      # train-shaped contrast: carries params
        ]
        quiet = {"serve_bucket_forward", "serve_single_logits",
                 "serve_with_new_state"}
        for f in fixture_findings("serve_jit.py"):
            assert f.symbol.split(".")[0] not in quiet, f

    def test_negatives_do_not_fire(self):
        # the ok/suppressed halves of every fixture stay quiet: no
        # finding may anchor inside any of these functions
        quiet = {"build_update_ok", "build_eval",
                 "build_update_suppressed", "host_driver", "split_ok",
                 "fold_in_loop_ok", "branch_ok", "rebind_ok",
                 "reuse_suppressed", "right_axes",
                 "unresolvable_is_silent", "wrong_suppressed",
                 "right_tp_axes", "wrong_tp_suppressed"}
        for fname in ("jax_hazards.py", "prng.py", "axes.py",
                      "tp_axes.py"):
            for f in fixture_findings(fname):
                head = f.symbol.split(".")[0]
                assert head not in quiet, f


class TestThreadRuleCorpus:
    def test_locks_corpus(self):
        got = code_lines(fixture_findings("locks.py"))
        assert got == sorted([
            ("SPK202", 19),      # main-side unlocked write
            ("SPK201", 24),      # thread-side unlocked read
            ("SPK204", 25),      # unannotated both-sides write
            ("SPK202", 68),      # holds= helper called without lock
            ("SPK203", 73),      # guard names a lock that doesn't exist
        ])

    def test_clean_and_opted_out_classes_quiet(self):
        for f in fixture_findings("locks.py"):
            assert not f.symbol.startswith("Clean")
            assert not f.symbol.startswith("OptedOut")
            # HoldsContract's locked path is fine; only broken() flags
            assert f.symbol != "HoldsContract.update"
            assert f.symbol != "HoldsContract._bump_locked"


# ------------------------------------------------------------ engine

class TestEngine:
    def test_inline_suppression(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent("""\
            import jax
            def f(rng):
                a = jax.random.normal(rng, (3,))
                b = jax.random.normal(rng, (3,))  # spk: disable=SPK103
                return a + b
        """))
        assert lint_paths([str(p)], root=str(tmp_path)) == []

    def test_file_level_suppression(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent("""\
            # spk: disable-file=SPK103
            import jax
            def f(rng):
                a = jax.random.normal(rng, (3,))
                b = jax.random.normal(rng, (3,))
                return a + b
        """))
        assert lint_paths([str(p)], root=str(tmp_path)) == []

    def test_bare_disable_suppresses_everything(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent("""\
            import jax
            def f(rng):
                a = jax.random.normal(rng, (3,))
                b = jax.random.normal(rng, (3,))  # spk: disable
                return a + b
        """))
        assert lint_paths([str(p)], root=str(tmp_path)) == []

    def test_syntax_error_becomes_spk001(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        fs = lint_paths([str(p)], root=str(tmp_path))
        assert [f.code for f in fs] == ["SPK001"]
        assert fs[0].severity == "error"

    def test_select_filters_rules(self):
        only = fixture_findings("jax_hazards.py", select={"SPK101"})
        assert {f.code for f in only} == {"SPK101"}

    def test_fingerprints_survive_line_shifts(self, tmp_path):
        src = textwrap.dedent("""\
            import jax
            def f(rng):
                a = jax.random.normal(rng, (3,))
                b = jax.random.normal(rng, (3,))
                return a + b
        """)
        p = tmp_path / "s.py"
        p.write_text(src)
        fp1 = [f.fingerprint()
               for f in lint_paths([str(p)], root=str(tmp_path))]
        p.write_text("# a comment pushing everything down\n\n" + src)
        fp2 = [f.fingerprint()
               for f in lint_paths([str(p)], root=str(tmp_path))]
        assert fp1 == fp2 and len(fp1) == 1

    def test_identical_findings_get_distinct_fingerprints(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(textwrap.dedent("""\
            import jax
            def f(rng, n):
                out = []
                for i in range(n):
                    out.append(jax.random.normal(rng, (2,)))
                for i in range(n):
                    out.append(jax.random.normal(rng, (2,)))
                return out
        """))
        fs = lint_paths([str(p)], root=str(tmp_path))
        fps = [f.fingerprint() for f in fs]
        assert len(fps) == len(set(fps)) and len(fps) >= 2


# ------------------------------------------------------------ baseline

BAD_SRC = textwrap.dedent("""\
    import jax
    def f(rng):
        a = jax.random.normal(rng, (3,))
        b = jax.random.normal(rng, (3,))
        return a + b
""")

CLEAN_SRC = textwrap.dedent("""\
    import jax
    def f(rng):
        k1, k2 = jax.random.split(rng)
        return jax.random.normal(k1, (3,)) + jax.random.normal(k2, (3,))
""")


class TestBaseline:
    def _setup(self, tmp_path):
        (tmp_path / "mod.py").write_text(BAD_SRC)
        bl = str(tmp_path / DEFAULT_BASELINE)
        args = dict(paths=[str(tmp_path / "mod.py")],
                    root=str(tmp_path), baseline=bl)
        return tmp_path, bl, args

    def test_write_then_clean(self, tmp_path):
        _, bl, args = self._setup(tmp_path)
        out = []
        rc = run_lint(mk_args(write_baseline=True,
                              justification="known legacy reuse",
                              **args), out=out.append)
        assert rc == 0
        data = json.load(open(bl))
        assert len(data["entries"]) == 1
        (entry,) = data["entries"].values()
        assert entry["justification"] == "known legacy reuse"
        # baselined finding no longer fails, even under --strict
        assert run_lint(mk_args(strict=True, **args),
                        out=lambda s: None) == 0

    def test_new_violation_still_fails(self, tmp_path):
        p, bl, args = self._setup(tmp_path)
        run_lint(mk_args(write_baseline=True, justification="legacy",
                         **args), out=lambda s: None)
        (p / "mod.py").write_text(
            BAD_SRC + "\n\ndef g(key):\n"
            "    x = jax.random.normal(key, (2,))\n"
            "    return x + jax.random.normal(key, (2,))\n")
        assert run_lint(mk_args(strict=True, **args),
                        out=lambda s: None) == 1
        assert run_lint(mk_args(**args), out=lambda s: None) == 1

    def test_stale_entries_reported_and_expired(self, tmp_path):
        p, bl, args = self._setup(tmp_path)
        run_lint(mk_args(write_baseline=True, justification="legacy",
                         **args), out=lambda s: None)
        (p / "mod.py").write_text(CLEAN_SRC)   # finding fixed -> stale
        out = []
        assert run_lint(mk_args(**args), out=out.append) == 0
        assert any("stale baseline entry" in s for s in out)
        # strict refuses a rotting baseline
        assert run_lint(mk_args(strict=True, **args),
                        out=lambda s: None) == 1
        # --write-baseline expires it
        run_lint(mk_args(write_baseline=True, **args),
                 out=lambda s: None)
        assert json.load(open(bl))["entries"] == {}
        assert run_lint(mk_args(strict=True, **args),
                        out=lambda s: None) == 0

    def test_unjustified_entries_fail_strict(self, tmp_path):
        _, bl, args = self._setup(tmp_path)
        # no --justification: placeholder recorded
        run_lint(mk_args(write_baseline=True, **args),
                 out=lambda s: None)
        out = []
        assert run_lint(mk_args(strict=True, **args),
                        out=out.append) == 1
        assert any("unjustified baseline entry" in s for s in out)
        assert run_lint(mk_args(**args), out=lambda s: None) == 0

    def test_malformed_baseline_is_usage_error(self, tmp_path):
        _, bl, args = self._setup(tmp_path)
        with open(bl, "w") as f:
            f.write("{nope")
        assert run_lint(mk_args(**args), out=lambda s: None,
                        err=lambda s: None) == 2


# ------------------------------------------------------------ CLI

class TestCLI:
    def test_seeded_violation_exits_nonzero(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SRC)
        rc = cli_main(["lint", str(bad), "--root", str(tmp_path),
                       "--strict",
                       "--baseline", str(tmp_path / "b.json")])
        assert rc == 1

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        ok = tmp_path / "ok.py"
        ok.write_text(CLEAN_SRC)
        rc = cli_main(["lint", str(ok), "--root", str(tmp_path),
                       "--strict",
                       "--baseline", str(tmp_path / "b.json")])
        assert rc == 0
        assert "0 findings" in capsys.readouterr().out

    def test_warnings_pass_without_strict_fail_with(self, tmp_path):
        p = tmp_path / "w.py"
        p.write_text(textwrap.dedent("""\
            import jax
            def build(updater):
                def step(params, it):
                    return updater(params, it)
                def ret(params, it):
                    params = step(params, it)
                    return params, it
                return jax.jit(ret)
        """))
        common = ["lint", str(p), "--root", str(tmp_path),
                  "--baseline", str(tmp_path / "b.json")]
        assert cli_main(common) == 0           # SPK105 is a warning
        assert cli_main(common + ["--strict"]) == 1

    def test_json_output(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SRC)
        cli_main(["lint", str(bad), "--root", str(tmp_path), "--json",
                  "--baseline", str(tmp_path / "b.json")])
        data = json.loads(capsys.readouterr().out)
        assert data["findings"] and \
            data["findings"][0]["code"] == "SPK103"
        assert data["findings"][0]["path"] == "bad.py"

    def test_list_rules(self, capsys):
        assert cli_main(["lint", "--list-rules"]) == 0
        out = capsys.readouterr().out
        for code in ("SPK101", "SPK102", "SPK103", "SPK104", "SPK105",
                     "SPK201", "SPK202", "SPK203", "SPK204",
                     "SPK205", "SPK206", "SPK207",
                     "SPK301", "SPK302", "SPK303", "SPK304",
                     "SPK401", "SPK402"):
            assert code in out

    def test_missing_path_is_usage_error(self, tmp_path, capsys):
        assert cli_main(["lint", str(tmp_path / "gone.py")]) == 2

    def test_unknown_select_code_is_usage_error(self, tmp_path):
        p = tmp_path / "ok.py"
        p.write_text(CLEAN_SRC)
        assert cli_main(["lint", str(p), "--select", "SPK999",
                         "--baseline", str(tmp_path / "b.json")]) == 2


# ------------------------------------------------------------ self-lint

class TestSelfLint:
    def test_repo_lints_clean_modulo_baseline(self):
        """The acceptance gate CI runs (scripts/lint.sh): the package
        source must produce zero non-baselined findings, zero stale
        baseline entries, and every baseline entry must carry a real
        justification."""
        out = []
        rc = run_lint(mk_args(
            paths=[os.path.join(REPO, "sparknet_tpu")], root=REPO,
            strict=True,
            baseline=os.path.join(REPO, DEFAULT_BASELINE)),
            out=out.append)
        assert rc == 0, "\n".join(out)

    def test_fixture_corpus_detects_every_rule_class(self):
        """Meta-check: the corpus must keep at least one positive per
        rule family, so a rule silently breaking shows up here."""
        codes = set()
        for fname in ("jax_hazards.py", "prng.py", "axes.py",
                      "tp_axes.py", "locks.py", "deadlock.py",
                      "protocol.py", "events.py"):
            codes |= {f.code for f in fixture_findings(fname)}
        assert {"SPK101", "SPK102", "SPK103", "SPK104", "SPK105",
                "SPK201", "SPK202", "SPK203", "SPK204",
                "SPK205", "SPK206", "SPK207",
                "SPK301", "SPK302", "SPK303", "SPK304",
                "SPK401", "SPK402"} <= codes


# ------------------------------------------------- cross-module corpus

class TestDeadlockRuleCorpus:
    def test_deadlock_corpus(self):
        got = code_lines(fixture_findings("deadlock.py"))
        assert got == sorted([
            ("SPK205", 15),      # same-class opposite nest order
            ("SPK205", 31),      # cross-class cycle via attr_types
            ("SPK205", 58),      # plain-Lock re-entry through helper
            ("SPK206", 102),     # time.sleep under self._lock
            ("SPK206", 106),     # open() two calls deep, lock held
            ("SPK206", 114),     # Event.wait() under the lock
            ("SPK207", 146),     # stored callback fired under lock
        ])

    def test_deadlock_negatives_quiet(self):
        for f in fixture_findings("deadlock.py"):
            assert not f.symbol.startswith("ReentrantOk")   # RLock
            assert not f.symbol.startswith("Ordered")       # one order
            assert not f.symbol.startswith("CondIdiom")     # cv.wait
            assert f.symbol != "SlowUnderLock.snapshot_then_block"
            assert f.symbol != "Emitter.fire_good"
            assert f.line != 122                            # disable=


class TestProtocolRuleCorpus:
    def test_protocol_corpus(self):
        got = code_lines(fixture_findings("protocol.py"))
        assert got == sorted([
            ("SPK301", 15),      # hb- f-string path, raw open
            ("SPK301", 20),      # part- np.savez, no tmp/replace
            ("SPK301", 25),      # marker via module constant concat
            ("SPK301", 35),      # marker through _mask_path helper
            ("SPK302", 60),      # os.replace src is a parameter
            ("SPK303", 64),      # bare gate() without timeout=
            ("SPK304", 81),      # sys.exit(3): name the table entry
            ("SPK304", 85),      # os._exit(7): no canonical name
        ])

    def test_protocol_negatives_quiet(self):
        syms = {f.symbol for f in fixture_findings("protocol.py")}
        for ok in ("good_atomic", "good_reader", "good_gate",
                   "bounded_barrier", "bail_named",
                   "tolerated_write", "tolerated_gate"):
            assert ok not in syms

    def test_spk304_names_canonical_constant(self):
        by_line = {f.line: f for f in fixture_findings("protocol.py")}
        assert "EXIT_RECOVERY_ABORT" in by_line[81].message


class TestEventsRuleCorpus:
    def test_events_corpus(self):
        got = code_lines(fixture_findings("events.py"))
        assert got == sorted([
            ("SPK402", 16),      # emit of an unregistered event
            ("SPK402", 20),      # registered event, drifted field
            ("SPK401", 26),      # consumer filters typo'd event
            ("SPK401", 31),      # typo inside a tuple comparator
        ])

    def test_events_negatives_quiet(self):
        lines = {f.line for f in fixture_findings("events.py")}
        assert 37 not in lines                     # disable=SPK401
        for f in fixture_findings("events.py"):
            assert f.symbol != "local_kind_ok"


# --------------------------------------------------------- project index

def _project_index(tmp_path, files):
    from sparknet_tpu.analysis.engine import Module
    from sparknet_tpu.analysis.project import ProjectIndex
    mods = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        mods.append(Module.load(str(p), str(tmp_path)))
    return ProjectIndex(mods), {m.relpath: m for m in mods}


class TestProjectIndex:
    def test_call_edges_resolve_across_modules(self, tmp_path):
        proj, mods = _project_index(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/b.py": """\
                import time
                def helper():
                    time.sleep(1)
            """,
            "pkg/a.py": """\
                from .b import helper
                class A:
                    def __init__(self):
                        self.peer = B()
                    def run(self):
                        helper()
                        self.go()
                        self.peer.pong()
                    def go(self):
                        pass
                class B:
                    def pong(self):
                        pass
            """,
        })
        import ast
        fn = proj.functions[("pkg/a.py", "A.run")]
        mod = mods["pkg/a.py"]
        keys = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                tgt = proj.resolve_call(node, mod, fn.node)
                if tgt is not None:
                    keys.add(tgt.key)
        assert ("pkg/b.py", "helper") in keys          # imported name
        assert ("pkg/a.py", "A.go") in keys            # self.method()
        assert ("pkg/a.py", "B.pong") in keys          # self.field.m()

    def test_blocking_propagates_transitively(self, tmp_path):
        proj, mods = _project_index(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/b.py": """\
                import time
                def helper():
                    time.sleep(1)
            """,
            "pkg/a.py": """\
                from .b import helper
                def outer():
                    helper()
                def pure():
                    return 1
            """,
        })
        assert proj.transitively_blocking(
            ("pkg/a.py", "outer")) is not None
        assert proj.transitively_blocking(
            ("pkg/a.py", "pure")) is None

    def test_expr_fragments_through_helper_and_join(self, tmp_path):
        import ast
        proj, mods = _project_index(tmp_path, {
            "m.py": """\
                import os
                SUFFIX = ".latest.json"
                def man(prefix):
                    return prefix + SUFFIX
                def use(prefix):
                    p = man(prefix)
                    q = os.path.join("root", f"part-{prefix}.npz")
                    return p, q
            """,
        })
        use = proj.functions[("m.py", "use")].node
        frags = {}
        for node in ast.walk(use):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.targets[0], ast.Name):
                frags[node.targets[0].id] = "".join(
                    proj.expr_fragments(node.value, mods["m.py"],
                                        use))
        assert ".latest.json" in frags["p"]   # const through helper ret
        assert "part-" in frags["q"]          # os.path.join + f-string

    def test_constants_ambiguity_and_exit_table(self, tmp_path):
        proj, _ = _project_index(tmp_path, {
            "a.py": "TAG = 'alpha'\nEXIT_BOOM = 9\n",
            "b.py": "TAG = 'beta'\nONLY = 'one'\n",
        })
        assert proj.resolve_constant("TAG") is None     # ambiguous
        assert proj.resolve_constant("ONLY") == "one"
        assert proj.exit_table[9] == "EXIT_BOOM"

    def test_emit_registry_collects_fields(self, tmp_path):
        proj, _ = _project_index(tmp_path, {
            "m.py": """\
                EVT = "boot"
                def f(metrics):
                    metrics.log(EVT, a=1, b=2)
                    metrics.log("boot", c=3)
            """,
        })
        assert "boot" in proj.events
        assert {"a", "b", "c"} <= proj.events["boot"]["fields"]


# ----------------------------------------------- profiles, cache, jobs

class TestCLIFeatures:
    def test_tests_profile_expands(self, capsys):
        # @tests excludes the concurrency families: deadlock.py is
        # silent under it, protocol.py still fires SPK301/303/304
        assert fixture_findings(
            "deadlock.py",
            select={"SPK001", "SPK301", "SPK302", "SPK304"}) == []
        rc = cli_main(["lint", os.path.join(FIXTURES, "protocol.py"),
                       "--root", FIXTURES, "--select", "@tests"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "SPK301" in out and "SPK304" in out
        assert "SPK303" not in out          # not in the @tests profile

    def test_unknown_profile_is_usage_error(self, tmp_path):
        p = tmp_path / "ok.py"
        p.write_text(CLEAN_SRC)
        assert cli_main(["lint", str(p), "--select", "@bogus"]) == 2

    def test_exclude_skips_matching_paths(self, tmp_path):
        (tmp_path / "fixtures").mkdir()
        (tmp_path / "fixtures" / "bad.py").write_text(BAD_SRC)
        (tmp_path / "ok.py").write_text(CLEAN_SRC)
        rc = cli_main(["lint", str(tmp_path), "--root", str(tmp_path),
                       "--exclude", "fixtures"])
        assert rc == 0

    def test_cache_round_trip_and_invalidation(self, tmp_path,
                                               capsys):
        p = tmp_path / "mod.py"
        p.write_text(BAD_SRC)
        argv = ["lint", str(tmp_path), "--root", str(tmp_path),
                "--cache", "--json"]
        assert cli_main(argv) == 1
        cold = json.loads(capsys.readouterr().out)
        cache = tmp_path / ".sparknet-lint-cache.json"
        assert cache.exists()
        assert cli_main(argv) == 1           # warm: served from cache
        warm = json.loads(capsys.readouterr().out)
        assert warm["findings"] == cold["findings"]
        p.write_text(CLEAN_SRC)              # content hash changes
        assert cli_main(argv) == 0

    def test_jobs_matches_serial(self):
        from sparknet_tpu.analysis.engine import LintEngine
        serial = LintEngine(jobs=1).run([FIXTURES], root=FIXTURES)
        pooled = LintEngine(jobs=2).run([FIXTURES], root=FIXTURES)
        assert code_lines(serial) == code_lines(pooled)
        assert serial  # the corpus is not empty

    def test_write_event_schema_regenerates(self, tmp_path, capsys):
        out_path = tmp_path / "event_schema.py"
        from sparknet_tpu.analysis.metrics_rules import (
            write_event_schema, load_schema)
        write_event_schema(REPO, out_path=str(out_path))
        text = out_path.read_text()
        assert "EVENTS = {" in text and "'step'" in text
        committed = load_schema()
        ns = {}
        exec(compile(text, str(out_path), "exec"), ns)
        assert ns["EVENTS"] == committed["events"]


class TestSeededViolations:
    """Acceptance: a seeded violation of each new family fails the
    CLI with its rule code in the output."""

    def _run(self, tmp_path, src, argv_extra=()):        # -> (rc, out)
        p = tmp_path / "seeded.py"
        p.write_text(textwrap.dedent(src))
        import io, contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(["lint", str(p), "--root", str(tmp_path),
                           "--strict", *argv_extra])
        return rc, buf.getvalue()

    def test_seeded_deadlock_cycle(self, tmp_path):
        rc, out = self._run(tmp_path, """\
            import threading
            class T:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def fwd(self):
                    with self._a:
                        with self._b:
                            pass
                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        """)
        assert rc == 1 and "SPK205" in out

    def test_seeded_nonatomic_rendezvous_write(self, tmp_path):
        rc, out = self._run(tmp_path, """\
            import json
            def beat(d, payload):
                with open(d + "/hb-0.json", "w") as f:
                    json.dump(payload, f)
        """)
        assert rc == 1 and "SPK301" in out

    def test_seeded_unknown_event_consumer(self, tmp_path):
        rc, out = self._run(tmp_path, """\
            def consume(rows):
                return [e for e in rows
                        if e.get("event") == "step_summry"]
        """)
        assert rc == 1 and "SPK401" in out
